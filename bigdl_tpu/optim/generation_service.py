"""Concurrent autoregressive LM serving.

The LM analog of ``PredictionService`` (≙ optim/PredictionService.scala's
instance-queue semantics — the reference has no generative serving, this
is beyond-parity): concurrent ``generate()`` requests micro-batch into
one ragged scan-decode dispatch per (prompt bucket, decode bucket)
group, which is how the MXU wants to be fed — a lone decode request
strands it.

Shape discipline (the TPU serving contract):
- prompts RIGHT-pad up to a multiple of ``prompt_bucket`` (capped by the
  context); requests whose padded widths match share a batch even with
  DIFFERENT true lengths — ``TransformerLM.generate_ragged`` decodes
  each row at its own depth with per-row position vectors.
- every request's ``max_new_tokens`` rounds UP to a multiple of
  ``bucket_tokens``; ``max_len`` is pinned per group so the compiled
  program depends only on the (prompt bucket, decode bucket) key, never
  on a particular batch's max n.
- tokens are IDENTICAL to a direct ``model.generate`` call on each
  request alone (greedy decoding is batch-, padding-, and
  length-invariant per row — tested).
"""

from __future__ import annotations

import collections
import threading
import time

import jax
import numpy as np

from bigdl_tpu.optim.prediction_service import _MicroBatcher


def _delivered_tokens(gen_row, n: int, eos_id) -> int:
    """Tokens actually served out of a generated row: the requested
    ``n``, or — when ``eos_id`` stopped the row early — the count up to
    and including the FIRST eos (the tail after it is eos padding)."""
    if eos_id is None:
        return n
    row = np.asarray(gen_row[:n])
    hits = np.flatnonzero(row == eos_id)
    return int(hits[0]) + 1 if hits.size else n


class GenerationService:
    """Thread-safe generative serving over a ``TransformerLM``.

    ``generate(prompt_ids, max_new_tokens)`` blocks until its batch
    lands and returns the 1-D ``prompt + tokens`` row for this request.
    Sampling config (temperature/top_k/top_p/eos_id) is fixed per
    service — it is part of the compiled program."""

    def __init__(self, model, max_batch: int = 8,
                 batch_timeout_ms: float = 5.0, bucket_tokens: int = 32,
                 prompt_bucket: int = 32, eos_id=None,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 max_len=None, seed: int = 0, registry=None,
                 service_name: str = "generation",
                 submit_timeout_s=None):
        if bucket_tokens < 1:
            raise ValueError(f"bucket_tokens must be >= 1, got "
                             f"{bucket_tokens}")
        if prompt_bucket < 1:
            raise ValueError(f"prompt_bucket must be >= 1, got "
                             f"{prompt_bucket}")
        from bigdl_tpu.models.transformer import _validate_sampling

        # the model's own guard, applied at construction — a service must
        # not silently drop or late-fail the caller's sampling config
        _validate_sampling(temperature > 0.0, top_k, top_p)
        self.model = model
        self.max_batch = max_batch
        self.batch_timeout_ms = batch_timeout_ms
        self.bucket_tokens = bucket_tokens
        self.prompt_bucket = prompt_bucket
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.max_len = max_len
        # bound each request's wait for its batch result (a dead drain
        # thread must raise, not hang the caller forever); None = wait
        # forever (see _MicroBatcher.submit)
        self.submit_timeout_s = submit_timeout_s
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()
        # registry-backed telemetry (replaces the bespoke _served /
        # _dispatches counters); stats() stays a compatible façade over
        # the occupancy histogram, reading the delta since construction
        from bigdl_tpu.observability import (
            OccupancyStats, generation_instruments, serving_instruments,
        )
        from bigdl_tpu.observability.events import default_recorder

        self.service_name = service_name
        self._ins = serving_instruments(service_name, registry)
        self._gen_ins = generation_instruments(service_name, registry)
        self._occ_stats = OccupancyStats(self._ins.batch_occupancy)
        # flight-recorder wiring: per-request submitted/finished events
        # plus batch/enqueue|dispatch tags from the micro-batcher, all
        # under the same request-id vocabulary as the serving engine
        self._rec = default_recorder()
        #: bounded ring of per-request timeline summaries — the
        #: stats() percentile source (lock: concurrent generate()
        #: callers append while stats() snapshots; iterating a deque
        #: under concurrent append raises in CPython)
        self._recent: collections.deque = collections.deque(maxlen=256)
        self._recent_lock = threading.Lock()
        # the micro-batcher invokes on_batch then run_batch on the SAME
        # drain thread, so a thread-local carries each dispatch's real
        # (pre-padding) request count into the tokens/sec computation
        self._tl = threading.local()
        # one device dispatch at a time: tracing generate() binds state
        # on the module (not thread-safe across concurrent traces), and
        # the chip runs one program at a time anyway — concurrency value
        # lives in the BATCHING, not in parallel dispatch
        self._dispatch = threading.Lock()
        self._batchers = {}  # (tpad, bucketed n[, tight]) -> _MicroBatcher

    def _cap(self) -> int:
        return min(self.max_len or self.model.max_len, self.model.max_len)

    def _next_key(self):
        # generate()'s internal rng default reaches for the GLOBAL key
        # stream, which concurrent drain threads would race; the service
        # owns a lock-protected stream instead
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def _batcher(self, key) -> _MicroBatcher:
        bucket = key[1]
        with self._lock:
            b = self._batchers.get(key)
            if b is None:
                def run_batch(stacked):
                    # layout per row: [padded prompt | true length | n]
                    prompts = stacked[:, :-2]
                    lengths = stacked[:, -2]
                    n_req = int(stacked[:, -1].max())
                    pinned = min(self._cap(), prompts.shape[1] + bucket)
                    kw = {}
                    if self.temperature > 0.0:
                        kw = dict(temperature=self.temperature,
                                  top_k=self.top_k, top_p=self.top_p,
                                  rng=self._next_key())
                    with self._dispatch:
                        t0 = time.monotonic()
                        toks = np.asarray(self.model.generate_ragged(
                            prompts, lengths, n_req, eos_id=self.eos_id,
                            bucket_tokens=self.bucket_tokens,
                            max_len=pinned, **kw))
                        dt = time.monotonic() - t0
                        # delivered tokens: the REAL rows sit first in
                        # the stacked batch (padding duplicates the last
                        # real row at the end); each real row delivers
                        # its requested n UNLESS eos stopped it early —
                        # then only the tokens up to and including the
                        # first eos count (the tail is eos padding, not
                        # served output) — same accounting as
                        # tokens_total. Set INSIDE the dispatch lock:
                        # dispatches publish the gauge in their
                        # serialized order, so "last dispatch" can never
                        # show a stale one.
                        real = getattr(self._tl, "real", stacked.shape[0])
                        delivered = sum(
                            _delivered_tokens(toks[i], int(stacked[i, -1]),
                                              self.eos_id)
                            for i in range(real))
                        self._gen_ins.tokens_per_sec.set(
                            delivered / max(dt, 1e-9))
                    return toks

                b = _MicroBatcher(run_batch, self.max_batch,
                                  self.batch_timeout_ms,
                                  on_batch=self._count_batch,
                                  telemetry=self._ins,
                                  submit_timeout_s=self.submit_timeout_s,
                                  recorder=self._rec,
                                  name=self.service_name)
                self._batchers[key] = b
            return b

    def generate(self, prompt_ids, max_new_tokens: int) -> np.ndarray:
        """One request: 1-D ``prompt_ids`` in, 1-D ``prompt + generated``
        out (exactly ``max_new_tokens`` tokens; with ``eos_id`` the tail
        after the first eos is eos padding, as in ``model.generate``)."""
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError("GenerationService.generate takes ONE request "
                             f"(1-D prompt), got shape {prompt.shape}")
        t0 = prompt.shape[0]
        n = max_new_tokens
        if n < 1:
            raise ValueError("max_new_tokens must be >= 1")
        cap = self._cap()
        if t0 < 1 or t0 + n > cap:
            raise ValueError(f"prompt ({t0}) + max_new_tokens ({n}) "
                             f"exceeds the context length {cap}")
        tpad = min(-(-t0 // self.prompt_bucket) * self.prompt_bucket, cap)
        bucket = -(-n // self.bucket_tokens) * self.bucket_tokens
        # Safe-coalescing key: the PINNED-WINDOW invariant (every batch
        # fits tpad + bucket) holds because lmax <= tpad and n_req <=
        # bucket — tpad is part of the key EXPLICITLY rather than
        # inherited from the micro-batcher's row-shape grouping. In the
        # TIGHT region (tpad + bucket > cap) that guarantee fails for
        # MIXED n — two individually-valid requests could combine into
        # lmax + n_req > cap — so tight requests group by their EXACT n:
        # then lmax + n = max(t0_i + n) <= cap per the per-request check
        # above.
        key = (tpad, bucket) if tpad + bucket <= cap \
            else (tpad, bucket, "tight", n)
        row = np.zeros((tpad + 2,), np.int32)
        row[:t0] = prompt
        row[-2], row[-1] = t0, n
        self._ins.requests_total.inc()
        from bigdl_tpu.observability.events import next_request_id

        rid = next_request_id()
        t_sub = time.monotonic()
        self._rec.record("request/submitted", rid,
                         service=self.service_name, prompt_tokens=t0,
                         max_new_tokens=n)
        detail: dict = {}
        # dispatch failures are counted by the micro-batcher's telemetry
        # (per failed request in the batch) — no second count here; the
        # recorder still needs a TERMINAL event, or a failed request
        # reads as stuck in flight forever
        try:
            with self._ins.inflight.track():
                toks = self._batcher(key).submit(row, request_id=rid,
                                                 detail=detail)
        except Exception as e:
            t_done = time.monotonic()
            self._rec.record("request/failed", rid,
                             service=self.service_name,
                             error=type(e).__name__)
            t_launch = detail.get("t_launch")
            with self._recent_lock:
                self._recent.append({
                    "request_id": rid, "outcome": "failed",
                    "queue_wait_s": (t_launch - t_sub)
                    if t_launch is not None else None,
                    "decode_s": None, "ttft_s": None,
                    "total_s": t_done - t_sub, "tokens": 0,
                })
            raise
        t_done = time.monotonic()
        gen = np.asarray(toks[:n])
        # count DELIVERED tokens: with eos_id, a row that stopped early
        # carries an eos-padding tail the caller never asked for —
        # tokens up to and including the first eos are what was served
        # (the same accounting run_batch's tokens/sec uses)
        delivered = _delivered_tokens(gen, n, self.eos_id)
        self._gen_ins.tokens_total.inc(delivered)
        self._rec.record("request/finished", rid,
                         service=self.service_name, tokens=delivered)
        t_launch = detail.get("t_launch")
        # batch-at-a-time timeline: every token lands when the batch
        # completes, so TTFT == total; prefill is inside the fused
        # dispatch (decode_s covers device time, launch -> done)
        with self._recent_lock:
            self._recent.append({
                "request_id": rid, "outcome": "finished",
                "queue_wait_s": (t_launch - t_sub)
                if t_launch is not None else None,
                "decode_s": (t_done - t_launch)
                if t_launch is not None else None,
                "ttft_s": t_done - t_sub,
                "total_s": t_done - t_sub,
                "tokens": delivered,
            })
        return np.concatenate([prompt, gen])

    def _count_batch(self, real_size: int):
        # the drain thread calls this immediately before run_batch on
        # the SAME thread: stash the real (pre-padding) request count
        # for the tokens/sec computation there
        self._tl.real = real_size

    def stats(self) -> dict:
        """Operational counters: requests batched, device dispatches,
        and mean real-requests-per-dispatch (how well the micro-batcher
        is coalescing — 1.0 means every request paid its own dispatch,
        ``max_batch`` means perfect occupancy). A façade over the
        registry's batch-occupancy histogram — the delta since THIS
        service was constructed; exact as long as no other live service
        shares the same ``service_name``, and disabling the service's
        registry (``observability.disable()`` when it uses the process
        default) stops these counters with the rest of that registry
        (see ``observability.OccupancyStats``).

        ``latency`` adds percentile summaries over the recent
        per-request timelines (queue wait to batch launch, device time,
        TTFT, total — in this batch-at-a-time service every token
        lands with the batch, so TTFT equals total and prefill is
        inside the fused dispatch)."""
        out = self._occ_stats.snapshot()
        from bigdl_tpu.observability.events import percentile_summary

        with self._recent_lock:
            snap = list(self._recent)
        tls = [t for t in snap if t["outcome"] == "finished"]
        out["latency"] = {
            phase: percentile_summary(t.get(phase + "_s") for t in tls)
            for phase in ("queue_wait", "ttft", "decode", "total")}
        return out
