"""Concurrent autoregressive LM serving.

The LM analog of ``PredictionService`` (≙ optim/PredictionService.scala's
instance-queue semantics — the reference has no generative serving, this
is beyond-parity): concurrent ``generate()`` requests micro-batch into
one scan-decode dispatch per (prompt-length, decode-bucket) group, which
is how the MXU wants to be fed — a lone decode request strands it.

Shape discipline (the TPU serving contract):
- prompts group by EXACT length — the prefill is maskless (dense causal
  attention), so different-length prompts never share a batch; callers
  wanting cross-length batching pad client-side to shared lengths.
- every request's ``max_new_tokens`` rounds UP to a multiple of
  ``bucket_tokens``; requests in the same bucket share one compiled scan
  program (see generate(bucket_tokens=...)) and each reply is trimmed
  back to the tokens its caller asked for. Tokens are IDENTICAL to a
  direct ``model.generate`` call — greedy decoding is batch-invariant
  and length-invariant per row.
"""

from __future__ import annotations

import threading

import jax
import numpy as np

from bigdl_tpu.optim.prediction_service import _MicroBatcher


class GenerationService:
    """Thread-safe generative serving over a ``TransformerLM``.

    ``generate(prompt_ids, max_new_tokens)`` blocks until its batch
    lands and returns the 1-D ``prompt + tokens`` row for this request.
    Sampling config (temperature/top_k/top_p/eos_id) is fixed per
    service — it is part of the compiled program."""

    def __init__(self, model, max_batch: int = 8,
                 batch_timeout_ms: float = 5.0, bucket_tokens: int = 32,
                 eos_id=None, temperature: float = 0.0, top_k=None,
                 top_p=None, max_len=None, seed: int = 0):
        if bucket_tokens < 1:
            raise ValueError(f"bucket_tokens must be >= 1, got "
                             f"{bucket_tokens}")
        if temperature <= 0.0 and (top_k is not None or top_p is not None):
            # mirror model.generate's own guard — a greedy service must
            # not silently drop the caller's sampling config
            raise ValueError("top_k/top_p filter the SAMPLED distribution; "
                             "pass temperature > 0")
        self.model = model
        self.max_batch = max_batch
        self.batch_timeout_ms = batch_timeout_ms
        self.bucket_tokens = bucket_tokens
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.max_len = max_len
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()
        # one device dispatch at a time: tracing generate() binds state
        # on the module (not thread-safe across concurrent traces), and
        # the chip runs one program at a time anyway — concurrency value
        # lives in the BATCHING, not in parallel dispatch
        self._dispatch = threading.Lock()
        self._batchers = {}  # bucketed n -> _MicroBatcher

    def _next_key(self):
        # generate()'s internal rng default reaches for the GLOBAL key
        # stream, which concurrent drain threads would race; the service
        # owns a lock-protected stream instead
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def _batcher(self, bucket: int) -> _MicroBatcher:
        with self._lock:
            b = self._batchers.get(bucket)
            if b is None:
                def run_batch(stacked):
                    # last column carries each request's max_new_tokens
                    # (generate() is given the batch max and the bucket,
                    # so its OWN bucketing applies — validation against
                    # the requested length, clamp-safe tail). max_len is
                    # pinned to (prompt + bucket, capped by the context)
                    # so the KV-cache shape — and therefore the compiled
                    # program — depends only on (prompt length, bucket),
                    # never on this batch's particular max n.
                    prompts = stacked[:, :-1]
                    n_req = int(stacked[:, -1].max())
                    cap = min(self.max_len or self.model.max_len,
                              self.model.max_len)
                    pinned = min(cap, prompts.shape[1] + bucket)
                    kw = {}
                    if self.temperature > 0.0:
                        kw = dict(temperature=self.temperature,
                                  top_k=self.top_k, top_p=self.top_p,
                                  rng=self._next_key())
                    with self._dispatch:
                        return np.asarray(self.model.generate(
                            prompts, n_req, eos_id=self.eos_id,
                            max_len=pinned,
                            bucket_tokens=self.bucket_tokens, **kw))

                b = _MicroBatcher(run_batch, self.max_batch,
                                  self.batch_timeout_ms)
                self._batchers[bucket] = b
            return b

    def generate(self, prompt_ids, max_new_tokens: int) -> np.ndarray:
        """One request: 1-D ``prompt_ids`` in, 1-D ``prompt + generated``
        out (exactly ``max_new_tokens`` tokens; with ``eos_id`` the tail
        after the first eos is eos padding, as in ``model.generate``)."""
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError("GenerationService.generate takes ONE request "
                             f"(1-D prompt), got shape {prompt.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bucket = -(-max_new_tokens // self.bucket_tokens) \
            * self.bucket_tokens
        row = self._batcher(bucket).submit(
            np.append(prompt, np.int32(max_new_tokens)))
        return np.asarray(row[:prompt.shape[0] + max_new_tokens])
