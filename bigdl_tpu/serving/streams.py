"""Per-request handles for the continuous-batching engine.

A ``RequestHandle`` is the client's side of one generation request: a
streaming token iterator (``tokens()``), a blocking ``result()``, and
``cancel()``. The engine's loop thread is the only writer; clients only
read — all cross-thread state goes through a queue and events, so no
client ever touches the engine's slot pool.

Greedy output is token-identical to a lone ``model.generate`` call on
the same prompt (the engine's acceptance contract, tested); with an
``eos_id`` the stream ends at (and includes) the first eos instead of
carrying ``generate``'s eos padding tail.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np


class RequestError(RuntimeError):
    """Base class for per-request terminal failures."""


class RequestCancelled(RequestError):
    """The request was cancelled via ``handle.cancel()``."""


class RequestTimedOut(RequestError):
    """The request's deadline passed while queued or mid-decode."""


class QueueFull(RuntimeError):
    """The bounded admission queue rejected the request (backpressure)."""


class EngineStopped(RuntimeError):
    """The engine stopped (or crashed) before the request completed."""


#: terminal sentinel on the token stream
_DONE = object()


class RequestHandle:
    """One in-flight generation request.

    Client API: ``tokens()`` (streaming iterator over generated token
    ids, in generation order), ``result()`` (blocking: the full
    ``prompt + generated`` row), ``cancel()``, ``done()``,
    ``tokens_so_far()``. A terminal failure (timeout, cancellation,
    engine stop) raises from ``result()`` and from the iterator AFTER
    every already-delivered token has been yielded — partial output is
    never silently dropped.

    Engine API (loop thread only): ``_deliver`` / ``_finish``.
    """

    def __init__(self, prompt, max_new_tokens: int,
                 timeout_s: Optional[float] = None):
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.submitted_at = time.monotonic()
        self.deadline = (self.submitted_at + timeout_s
                         if timeout_s is not None else None)
        #: set by the engine when the first token lands (TTFT source)
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._tokens: list = []
        self._stream: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._error: Optional[BaseException] = None

    # ---------------------------------------------------- engine side
    def _deliver(self, token: int, now: float) -> None:
        if self.first_token_at is None:
            self.first_token_at = now
        self._tokens.append(int(token))
        self._stream.put(int(token))

    def _finish(self, error: Optional[BaseException] = None) -> None:
        if self._done.is_set():
            return
        self._error = error
        self.finished_at = time.monotonic()
        self._done.set()
        self._stream.put(_DONE)

    # ---------------------------------------------------- client side
    def cancel(self) -> None:
        """Ask the engine to drop this request. Queued requests are
        dropped before admission; running requests are evicted at the
        next loop iteration. ``result()`` then raises
        ``RequestCancelled`` (unless the request already finished)."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def done(self) -> bool:
        return self._done.is_set()

    def error(self) -> Optional[BaseException]:
        """The terminal failure, or None (while running / on success)."""
        return self._error

    def tokens_so_far(self) -> np.ndarray:
        """Generated tokens delivered so far (a snapshot — the useful
        partial output after a timeout or cancellation)."""
        return np.asarray(list(self._tokens), np.int32)

    def tokens(self) -> Iterator[int]:
        """Stream generated token ids in order as the engine produces
        them; ends when the request finishes. A terminal failure raises
        AFTER the delivered prefix has been yielded. Single consumer."""
        while True:
            item = self._stream.get()
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; return the 1-D
        ``prompt + generated`` row (with ``eos_id`` configured on the
        engine, generation stops at — and includes — the first eos).
        Raises the terminal error on timeout/cancellation/engine-stop,
        or ``TimeoutError`` if ``timeout`` expires first."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request not finished after {timeout}s (still "
                f"{'cancelled' if self.cancelled else 'in flight'})")
        if self._error is not None:
            raise self._error
        return np.concatenate(
            [self.prompt, np.asarray(self._tokens, np.int32)])

    def __repr__(self):
        state = ("done" if self._done.is_set() else
                 "cancelled" if self.cancelled else "pending")
        return (f"RequestHandle(prompt={self.prompt.shape[0]} toks, "
                f"n={self.max_new_tokens}, {state}, "
                f"delivered={len(self._tokens)})")
