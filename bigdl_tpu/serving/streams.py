"""Per-request handles for the continuous-batching engine.

A ``RequestHandle`` is the client's side of one generation request: a
streaming token iterator (``tokens()``), a blocking ``result()``, and
``cancel()``. The engine's loop thread is the only writer; clients only
read — all cross-thread state goes through a queue and events, so no
client ever touches the engine's slot pool.

Greedy output is token-identical to a lone ``model.generate`` call on
the same prompt (the engine's acceptance contract, tested); with an
``eos_id`` the stream ends at (and includes) the first eos instead of
carrying ``generate``'s eos padding tail.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np

from bigdl_tpu.observability.events import next_request_id

#: admission priority classes, best-first. Rank (the tuple index) is
#: the primary ordering key in ``AdmissionQueue.pop_ready`` and the
#: shed/preemption order under overload: ``low`` is shed first and
#: preempted first, ``high`` is never shed.
PRIORITIES = ("high", "normal", "low")
PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}


class RequestError(RuntimeError):
    """Base class for per-request terminal failures."""


class RequestCancelled(RequestError):
    """The request was cancelled via ``handle.cancel()``."""


class RequestTimedOut(RequestError):
    """The request's deadline passed while queued or mid-decode."""


class RequestShed(RequestError):
    """The request was shed at admission by burn-rate load shedding:
    the engine's TTFT SLO is burning error budget and this request's
    priority class is in the shed set. Carries ``retry_after_s`` — the
    client should back off at least that long (the front door maps it
    to HTTP 429 with a ``Retry-After`` header)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class RequestRateLimited(RequestError):
    """The request's tenant exhausted its device-second token bucket.
    ``retry_after_s`` is the bucket's refill time back to a positive
    balance — the honest ``Retry-After`` figure, not a guess."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class QueueFull(RuntimeError):
    """The bounded admission queue rejected the request (backpressure)."""


class EngineStopped(RuntimeError):
    """The engine stopped (or crashed) before the request completed."""


class EngineDraining(RuntimeError):
    """The engine is draining: new submissions are refused while the
    requests already in flight run to completion (``engine.drain()``;
    a fleet router treats this as "route elsewhere and retry")."""


#: terminal sentinel on the token stream
_DONE = object()


class RequestHandle:
    """One in-flight generation request.

    Client API: ``tokens()`` (streaming iterator over generated token
    ids, in generation order), ``result()`` (blocking: the full
    ``prompt + generated`` row), ``cancel()``, ``done()``,
    ``tokens_so_far()``. A terminal failure (timeout, cancellation,
    engine stop) raises from ``result()`` and from the iterator AFTER
    every already-delivered token has been yielded — partial output is
    never silently dropped.

    Every handle carries a process-unique ``request_id`` — the
    correlation key the flight recorder, the ``/debug/*`` endpoints,
    and the Chrome trace all share — and, once ``result()`` returns or
    the token iterator ends, ``timeline()`` reports the final
    per-phase breakdown (queue wait, prefill, TTFT, decode, total).

    Engine API (loop thread only): ``_deliver`` / ``_finish``.
    """

    def __init__(self, prompt, max_new_tokens: int,
                 timeout_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 priority: str = "normal"):
        if priority not in PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        #: admission priority class (``high``/``normal``/``low``) —
        #: the queue's primary ordering key and the shed/preempt order
        self.priority = priority
        #: times this request was PREEMPTED (slot evicted with its KV
        #: donated to the prefix pool, then automatically requeued) —
        #: each resume re-prefills only the uncached tail, and the
        #: final output stays token-identical to an unpreempted run
        self.preempted: int = 0
        #: the request's correlation id (flight recorder events, the
        #: /debug endpoints, and Chrome traces all key on it)
        self.request_id = request_id or next_request_id()
        #: distributed-trace id (engine-stamped from
        #: ``submit(trace_id=...)``): the CROSS-process correlation
        #: key — the fleet front door mints it, every replica-side
        #: recorder event and usage record carries it, and the merged
        #: fleet trace joins the per-process arcs on it. None when
        #: the request never crossed a traced front door.
        self.trace_id: Optional[str] = None
        self.submitted_at = time.monotonic()
        self.deadline = (self.submitted_at + timeout_s
                         if timeout_s is not None else None)
        #: set by the engine when prefill starts (queue-wait boundary)
        self.admitted_at: Optional[float] = None
        #: prompt tokens served from the engine's prefix cache instead
        #: of being prefilled (0 on a miss or with the cache disabled);
        #: stamped at admission alongside the ``request/prefix_hit``
        #: flight-recorder event
        self.prefix_tokens: int = 0
        #: the tenant this request's usage is billed to — stamped by
        #: ``engine.submit(tenant=...)`` after cardinality-cap
        #: resolution (None outside an engine)
        self.tenant: Optional[str] = None
        #: speculative decoding tallies (engine-stamped per decode
        #: round; both stay 0 without a draft): draft tokens proposed
        #: for this request vs accepted by the target's verify — the
        #: per-request acceptance rate, surfaced in ``timeline()``.
        #: Multi-token acceptances reach the stream as in-order BURSTS
        #: (one ``request/decode_token`` recorder event per round,
        #: carrying ``accepted=``), so ``timeline()``'s ``decode_s /
        #: (tokens - 1)`` mean inter-token gap stays the true figure
        self.spec_proposed: int = 0
        self.spec_accepted: int = 0
        #: the engine's UsageRecord for this request (engine-stamped;
        #: read through ``usage()``)
        self._usage = None
        #: set by the engine when the first token lands (TTFT source)
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._tokens: list = []
        self._stream: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._finish_once = threading.Lock()
        self._cancelled = threading.Event()
        self._error: Optional[BaseException] = None

    # ---------------------------------------------------- engine side
    def _deliver(self, token: int, now: float) -> None:
        if self.first_token_at is None:
            self.first_token_at = now
        self._tokens.append(int(token))
        self._stream.put(int(token))

    def _finish(self, error: Optional[BaseException] = None) -> bool:
        """Mark terminal; returns True only for the ONE caller that
        actually performed the transition (the loop thread and a
        stopping submitter can race here — terminal bookkeeping keyed
        on the return value must happen exactly once)."""
        with self._finish_once:
            if self._done.is_set():
                return False
            self._error = error
            self.finished_at = time.monotonic()
            self._done.set()
        self._stream.put(_DONE)
        return True

    # ---------------------------------------------------- client side
    def cancel(self) -> None:
        """Ask the engine to drop this request. Queued requests are
        dropped before admission; running requests are evicted at the
        next loop iteration. ``result()`` then raises
        ``RequestCancelled`` (unless the request already finished)."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def done(self) -> bool:
        return self._done.is_set()

    def error(self) -> Optional[BaseException]:
        """The terminal failure, or None (while running / on success)."""
        return self._error

    def tokens_so_far(self) -> np.ndarray:
        """Generated tokens delivered so far (a snapshot — the useful
        partial output after a timeout or cancellation)."""
        return np.asarray(list(self._tokens), np.int32)

    def timeline(self) -> dict:
        """The request's per-phase wall-time breakdown (monotonic
        seconds; phases the request never reached are None):

        - ``queue_wait_s`` — submitted → admitted (prefill started)
        - ``prefill_s``    — admitted → first token
        - ``ttft_s``       — submitted → first token
        - ``decode_s``     — first token → finished
        - ``total_s``      — submitted → finished
        - ``tokens``       — tokens delivered
        - ``prefix_tokens`` — prompt tokens reused from the prefix
          cache (prefill skipped for them; 0 on a miss)
        - ``spec_proposed`` / ``spec_accepted`` — draft tokens
          proposed vs accepted for this request (0 without a draft);
          accepted extensions arrive as multi-token bursts, so
          ``decode_s / (tokens - 1)`` remains the honest mean
          inter-token gap either way
        - ``priority`` / ``preempted`` — the request's admission
          class and how many times it was preempted (slot evicted,
          KV donated, automatically resumed) — preemption cost is
          attributable per request in ``/debug/requests``

        Final once the request is ``done()`` (the engine stamps each
        boundary as the lifecycle advances), partial before that."""
        def gap(a, b):
            return (b - a) if (a is not None and b is not None) else None

        return {
            "queue_wait_s": gap(self.submitted_at, self.admitted_at),
            "prefill_s": gap(self.admitted_at, self.first_token_at),
            "ttft_s": gap(self.submitted_at, self.first_token_at),
            "decode_s": gap(self.first_token_at, self.finished_at),
            "total_s": gap(self.submitted_at, self.finished_at),
            "tokens": len(self._tokens),
            "prefix_tokens": self.prefix_tokens,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "priority": self.priority,
            "preempted": self.preempted,
        }

    def usage(self) -> Optional[dict]:
        """The request's metered resource consumption from the
        engine's usage ledger (``observability.accounting``): tenant,
        queue wait, prefilled vs prefix-reused prompt tokens (and KV
        bytes the reuse saved), delivered tokens, pro-rata
        device-seconds by dispatch kind, and KV byte-seconds held.
        Final once the request is ``done()`` (the ``outcome`` field is
        set); a live snapshot before that. None when the handle never
        entered an engine."""
        rec = self._usage
        return rec.to_dict() if rec is not None else None

    def tokens(self) -> Iterator[int]:
        """Stream generated token ids in order as the engine produces
        them; ends when the request finishes — at which point
        ``request_id`` / ``timeline()`` hold the final per-phase
        breakdown. A terminal failure raises AFTER the delivered
        prefix has been yielded. Single consumer."""
        while True:
            item = self._stream.get()
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; return the 1-D
        ``prompt + generated`` row (with ``eos_id`` configured on the
        engine, generation stops at — and includes — the first eos).
        On return, ``request_id`` and ``timeline()`` surface the
        request's identity and final phase breakdown. Raises the
        terminal error on timeout/cancellation/engine-stop, or
        ``TimeoutError`` if ``timeout`` expires first."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request not finished after {timeout}s (still "
                f"{'cancelled' if self.cancelled else 'in flight'})")
        if self._error is not None:
            raise self._error
        return np.concatenate(
            [self.prompt, np.asarray(self._tokens, np.int32)])

    def __repr__(self):
        state = ("done" if self._done.is_set() else
                 "cancelled" if self.cancelled else "pending")
        return (f"RequestHandle({self.request_id}, "
                f"prompt={self.prompt.shape[0]} toks, "
                f"n={self.max_new_tokens}, {state}, "
                f"delivered={len(self._tokens)})")
