"""The fleet A/B: prefix-affinity routing vs round-robin, plus the
mid-storm drain drill.

``run_fleet_comparison`` is the hermetic multi-process bench behind
``bench.py --serving --fleet N``: one Poisson storm over shared-prefix
templates is replayed through a fleet of ``N`` spawn-worker replicas
(each its own process, model, engine, prefix trie) twice —

- **affinity**: the ``PrefixAffinityRouter`` hashes each prompt's
  first chunk onto the ring, so every template's KV accumulates on
  exactly one replica;
- **round_robin**: the control leg — the same storm sprayed evenly,
  every replica forced to cache every template.

Each replica's prefix pool is sized to hold its affinity SHARE of the
templates (the ring's largest per-replica template count, +1 slack —
capacity provisioned for content-aware routing), so the control leg
LRU-thrashes exactly the way a fleet of budget-bound tries does when
routing ignores content: the affinity leg wins on fleet-wide hit rate
AND on client TTFT p50 (a hit prefills only the random tail; a miss
prefills the whole template). Both legs' outputs are checked token-identical to a
single in-process reference engine replaying the same workload on the
same seed — routing must never change what anyone decodes.

The third leg re-runs the affinity storm and, mid-storm, DRAINS one
replica (the degraded-replica drill: router routes away, in-flight
finishes) and later rejoins it — zero lost requests and the same
token parity is the acceptance bar.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.observability.fleettrace import (
    FLEET_HOPS, hop_breakdown,
)
from bigdl_tpu.serving.benchmark import (
    _append_itl, _engine_replay, _percentiles, _replay,
    shared_prefix_workload,
)
from bigdl_tpu.serving.fleet.router import PrefixAffinityRouter
from bigdl_tpu.serving.fleet.supervisor import ReplicaSupervisor
from bigdl_tpu.serving.fleet.worker import spawn_worker_fleet

__all__ = ["run_fleet_comparison"]

#: the bench's model recipe — every worker builds exactly this (same
#: seed => bit-identical params => any replica's greedy output is the
#: fleet's greedy output)
_MODEL = dict(vocab_size=64, embed_dim=16, num_heads=4, num_kv_heads=2,
              num_layers=2, max_len=96, use_rope=True)


def _fleet_replay(sup: ReplicaSupervisor, workload,
                  on_submitted=None) -> dict:
    """Open-loop replay of ``workload`` through ``sup.submit`` (the
    shared ``_replay`` pacer). TTFT is CLIENT-side — routing + IPC +
    queue + prefill, stamped at first-token receipt in this process.
    ``on_submitted(i)`` fires after the i-th request is handed to a
    replica (the drain drill's trigger point). Each finished request
    is decomposed into the seven fleet hops (``hop_breakdown`` on the
    supervisor-measured route/rpc_submit timings plus the replica
    timeline); the leg block reports the per-hop MEANS under
    ``hops``."""
    ttft: List[float] = []
    itl: List[float] = []
    rows: Dict[int, list] = {}
    count = {"n": 0}
    t0s: Dict[int, float] = {}
    hop_sums = dict.fromkeys(FLEET_HOPS, 0.0)
    hop_n = [0]
    lock = threading.Lock()

    def submit(req):
        t0 = time.monotonic()
        routed = sup.submit(req["prompt"], req["n"],
                            tenant=req.get("tenant"))
        with lock:
            t0s[id(req)] = t0
            count["n"] += 1
            i = count["n"]
        if on_submitted is not None:
            on_submitted(i)
        return routed

    def collect(routed, req):
        toks = routed.handle.result(timeout=300)
        done = time.monotonic()
        h = routed.handle
        with lock:
            rows[id(req)] = [int(t) for t in toks]
            if h.first_token_at is not None:
                ttft.append(h.first_token_at - h.submitted_at)
            _append_itl(itl, h)
            t0 = t0s.pop(id(req), None)
            if t0 is not None:
                tl = h.timeline() if hasattr(h, "timeline") else {}
                hops = hop_breakdown(tl or {}, routed.route_s,
                                     routed.rpc_submit_s, done - t0)
                for k, v in hops.items():
                    hop_sums[k] += v
                hop_n[0] += 1
        return len(toks)

    res = _replay(workload, submit, collect)
    res["ttft"] = _percentiles(ttft)
    res["inter_token"] = _percentiles(itl)
    res["rows"] = rows
    res["hops"] = {k: (hop_sums[k] / hop_n[0]) for k in FLEET_HOPS} \
        if hop_n[0] else None
    return res


def _capacity_stamp(cap: dict) -> dict:
    """Compress ``fleet_capacity()`` into the bench-row block
    ``perf_gate`` bands: fleet headroom/replicas-needed plus each
    replica's role split (prefill vs decode device-wall fractions)."""
    roles = {}
    for rid, rc in (cap.get("replicas") or {}).items():
        r = rc.get("roles") or {}
        if r:
            roles[rid] = {
                "bound": r.get("bound"),
                "prefill_fraction":
                    (r.get("prefill") or {}).get("wall_fraction"),
                "decode_fraction":
                    (r.get("decode") or {}).get("wall_fraction"),
                "disaggregation_speedup_bound":
                    r.get("disaggregation_speedup_bound"),
            }
    return {
        "ready": bool(cap.get("ready")),
        "headroom": cap.get("headroom"),
        "utilization": cap.get("utilization"),
        "observed_rps": cap.get("observed_rps"),
        "sustainable_rps": cap.get("sustainable_rps"),
        "replicas_needed": cap.get("replicas_needed"),
        "roles": roles or None,
    }


def _budget_stamp(budgets: dict) -> dict:
    """Compress the per-replica SLO error-budget ledgers into the
    bench-row block ``perf_gate`` floors: the fleet-worst remaining
    fraction plus the per-replica minima."""
    per = {rid: led.get("remaining_min")
           for rid, led in budgets.items() if isinstance(led, dict)}
    known = [v for v in per.values() if v is not None]
    return {
        "remaining_min": min(known) if known else None,
        "per_replica": per or None,
    }


def _leg(workload, n_replicas, engine_cfg, seed, policy, chunk, log,
         label, drain_at: Optional[int] = None,
         rejoin_at: Optional[int] = None, victim: str = "r0") -> dict:
    """One fleet leg: fresh worker processes (cold tries — the legs
    must not share cache state), warm each replica's executables
    outside the measurement, replay, aggregate, tear down."""
    replicas = spawn_worker_fleet(
        n_replicas, _MODEL, engine=engine_cfg, seed=seed)
    sup = ReplicaSupervisor(replicas, policy=policy, chunk=chunk,
                            poll_interval=0.05,
                            fleet_name=f"bench-{label}")
    log(f"[fleet-bench] {label}: spawning {n_replicas} workers...")
    with sup:
        warm = np.arange(1, 9, dtype=np.int32)
        for rep in replicas:
            rep.submit(warm, 4).result(timeout=300)

        def trigger(i):
            if drain_at is not None and i == drain_at:
                log(f"[fleet-bench] {label}: draining {victim} "
                    f"mid-storm (request {i})")
                sup.drain(victim, reason="degraded")
            if rejoin_at is not None and i == rejoin_at:
                sup.rejoin(victim)

        log(f"[fleet-bench] {label}: replaying "
            f"{len(workload)} requests...")
        res = _fleet_replay(
            sup, workload,
            on_submitted=trigger if drain_at is not None else None)
        stats = sup.stats()
        # capacity + error-budget read must happen before the
        # supervisor exits (workers are gone after teardown)
        cap = sup.fleet_capacity()
        budgets = cap.pop("slo_budget", None) or {}
        res["capacity"] = _capacity_stamp(cap)
        res["slo_budget"] = _budget_stamp(budgets)
        res["fleet"] = {
            "policy": policy,
            "replicas": n_replicas,
            "prefix_cache": stats["prefix_cache"],
            "hit_rate": stats["prefix_cache"]["hit_rate"],
            "routing": {k: stats["routing"][k]
                        for k in ("decisions", "per_replica",
                                  "draining")},
            "per_replica_finished": {
                rid: (s.get("finished") if isinstance(s, dict)
                      else None)
                for rid, s in stats["replicas"].items()},
        }
        if drain_at is not None:
            res["fleet"]["drained"] = victim
    return res


def run_fleet_comparison(n_replicas: int = 2, n_requests: int = 36,
                         rate_hz: float = 30.0,
                         n_templates: Optional[int] = None,
                         template_len: int = 48, max_slots: int = 4,
                         prefill_chunk: int = 8, prefill_rows: int = 2,
                         seed: int = 0, model_seed: int = 7,
                         drain_drill: bool = True,
                         log=print) -> dict:
    """The ``--serving --fleet N`` A/B. Returns the affinity and
    round-robin leg blocks (client TTFT / latency / inter-token
    percentiles, throughput, fleet hit rate, routing tallies), the
    drain-drill block, the headline ratios, the affinity leg's
    capacity/what-if stamp (fleet headroom, replicas-needed, per-role
    device-wall split) and SLO error-budget floor (worst
    ``remaining_min`` across replicas — ``perf_gate`` gates calm runs
    on it), and the token-parity verdict against a single-replica
    reference replay."""
    if not 2 <= n_replicas <= 4:
        raise ValueError("the fleet bench runs 2-4 replicas")
    if n_templates is None:
        n_templates = 2 * n_replicas
    # pick a workload whose template heads SPREAD over the ring — the
    # A/B measures the routing policy, not one seed's hash luck. The
    # search only hashes prompt heads (no engine), is deterministic,
    # and the chosen seed is recorded in the result's workload block
    probe = PrefixAffinityRouter(
        [f"r{i}" for i in range(n_replicas)], chunk=prefill_chunk)
    for wl_seed in range(seed, seed + 64):
        workload = shared_prefix_workload(
            n_requests, rate_hz, _MODEL["vocab_size"],
            n_templates=n_templates, template_len=template_len,
            tail_lens=(2, 6), decode_lens=(4, 10), seed=wl_seed,
            template_order="random")
        keys = {probe.key_for(req["prompt"]) for req in workload}
        owned = Counter(probe.owner(k) for k in keys)
        if (len(owned) == n_replicas
                and max(owned.values()) - min(owned.values()) <= 1):
            seed = wl_seed
            break
    else:
        raise RuntimeError(
            "no balanced template->replica assignment within 64 seeds "
            "— widen n_templates or the seed range")
    # size each replica's prefix pool for its AFFINITY share (+1
    # slack): affinity fits its owned templates; round-robin needs ALL
    # templates on every replica and thrashes its LRU
    share_rows = max(owned.values()) + 1
    engine_cfg = dict(max_slots=max_slots, prefill_chunk=prefill_chunk,
                      prefill_rows=prefill_rows,
                      prefix_cache_rows=share_rows,
                      # generous TTFT objective: calm legs keep the
                      # error budget ~full, so perf_gate can floor
                      # detail.slo_budget.remaining_min; chaos drills
                      # are what spend it
                      slo_objectives=[dict(
                          name="ttft", metric="ttft",
                          threshold_s=5.0, target=0.9,
                          window_s=60.0, min_count=3)])

    # single-replica reference on the same seed: the parity oracle for
    # every fleet leg (and the routing-never-changes-tokens contract)
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils import random as rnd

    rnd.set_seed(model_seed)
    ref_model = TransformerLM(**_MODEL)
    ref_model.evaluate()
    ref = _engine_replay(
        ref_model, workload,
        warm_prompt=np.arange(1, 9, dtype=np.int32),
        warm_tokens=4, stats_keys=("prefix_cache",), log=log,
        label="fleet-reference", service_name="fleet-ref",
        max_slots=max_slots, prefill_chunk=prefill_chunk,
        prefill_rows=prefill_rows)
    oracle = {id(req): [int(t) for t in
                        ref["rows"][id(req)][len(req["prompt"]):]]
              for req in workload}

    def parity(rows: Dict[int, list]) -> bool:
        return all(rows.get(id(req)) == oracle[id(req)]
                   for req in workload)

    aff = _leg(workload, n_replicas, engine_cfg, model_seed,
               "affinity", prefill_chunk, log, "affinity")
    rr = _leg(workload, n_replicas, engine_cfg, model_seed,
              "round_robin", prefill_chunk, log, "round-robin")
    aff_par, rr_par = parity(aff["rows"]), parity(rr["rows"])

    drain = None
    if drain_drill:
        d = _leg(workload, n_replicas, engine_cfg, model_seed,
                 "affinity", prefill_chunk, log, "drain-drill",
                 drain_at=max(2, n_requests // 3),
                 rejoin_at=max(3, (2 * n_requests) // 3))
        drain = {
            "completed": d["requests"],
            "lost": n_requests - len(d["rows"]),
            "token_parity": parity(d["rows"]),
            "drained": d["fleet"].get("drained"),
            "routing": d["fleet"]["routing"],
            "ttft": d["ttft"],
        }

    for leg in (aff, rr):
        leg.pop("rows", None)  # ndarray-free JSON row
    # the affinity leg is the headline: its capacity/what-if block and
    # error-budget floor become the row's detail.capacity /
    # detail.slo_budget (the control leg's copies add nothing)
    capacity = aff.pop("capacity", None)
    slo_budget = aff.pop("slo_budget", None)
    rr.pop("capacity", None)
    rr.pop("slo_budget", None)

    a50, r50 = aff["ttft"]["p50"], rr["ttft"]["p50"]
    ratios = {
        # > 1.0: the affinity leg's median first token lands sooner
        "ttft_p50_speedup": (round(r50 / a50, 4)
                             if a50 and r50 else None),
        # additive: round-robin's hit rate can legitimately be ~0 here
        "hit_rate_gain": round(
            aff["fleet"]["hit_rate"] - rr["fleet"]["hit_rate"], 4),
    }
    return {
        "affinity": aff,
        "round_robin": rr,
        "drain": drain,
        "capacity": capacity,
        "slo_budget": slo_budget,
        **ratios,
        "token_parity": bool(aff_par and rr_par),
        "workload": {
            "kind": "fleet_shared_prefix",
            "replicas": n_replicas,
            "requests": n_requests,
            "rate_hz": rate_hz,
            "templates": n_templates,
            "template_len": template_len,
            "prefix_rows_per_replica": share_rows,
            "max_slots": max_slots,
            "prefill_rows": prefill_rows,
            "prefill_chunk": prefill_chunk,
            "seed": seed,
        },
    }
