"""bigdl_tpu.serving.fleet — multi-replica serving behind one door.

The horizontal-scale layer over the continuous-batching engine:
BigDL's driver/executor split (arxiv 1804.05839) recast for
inference — one control plane owning N engine replicas, one data
plane streaming tokens to clients over held connections (arxiv
1805.08430: stream one-way, never per-token request/response).

- ``PrefixAffinityRouter`` (``router``): consistent-hashes each
  prompt's first prefix-cache chunk onto a virtual-node ring so
  template-sharing requests land where the trie already holds their
  KV — every template's cache cost is paid on ONE replica fleet-wide.
  Saturated targets spill to the least-loaded replica, and a
  forced-spill bound (the admission queue's bounded-bypass pattern at
  ring scale) stops one hot template from pinning its owner.
- ``ReplicaSupervisor`` (``supervisor``): owns the replicas
  (``InProcessReplica`` wrappers or ``multiprocessing``
  ``WorkerReplica`` processes), polls ``healthz()`` + load gauges,
  DRAINS what degrades or crashes (in-flight finishes, new traffic
  routes away), rejoins what recovers, and routes ``submit()`` calls
  through the ring. ``bigdl_fleet_*`` instruments cover the whole
  control plane.
- ``FleetFrontDoor`` (``frontdoor``): the stdlib-only HTTP door —
  ``POST /v1/generate`` streams tokens as Server-Sent Events off the
  replica handle's iterator (client disconnect cancels the request
  and frees the slot), ``GET /v1/stats`` aggregates per-replica
  ``stats()`` plus the fleet prefix hit rate and routing table.
- ``run_fleet_comparison`` (``benchmark``): the hermetic
  multi-process affinity-vs-round-robin storm behind
  ``bench.py --serving --fleet N``.

Quick start::

    from bigdl_tpu.serving import ContinuousBatchingEngine
    from bigdl_tpu.serving.fleet import (
        FleetFrontDoor, InProcessReplica, ReplicaSupervisor,
    )

    replicas = [InProcessReplica(f"r{i}",
                                 ContinuousBatchingEngine(model))
                for i in range(3)]
    with ReplicaSupervisor(replicas) as sup, \
         FleetFrontDoor(sup, port=8080) as door:
        ...  # POST /v1/generate, GET /v1/stats on door.port
"""

from bigdl_tpu.serving.fleet.benchmark import run_fleet_comparison
from bigdl_tpu.serving.fleet.frontdoor import (
    FleetFrontDoor, start_front_door,
)
from bigdl_tpu.serving.fleet.router import (
    NoLiveReplicas, PrefixAffinityRouter, RouteDecision,
)
from bigdl_tpu.serving.fleet.supervisor import (
    InProcessReplica, ReplicaSupervisor, Routed,
)
from bigdl_tpu.serving.fleet.worker import (
    WorkerHandle, WorkerRPCTimeout, WorkerReplica, spawn_worker_fleet,
)

__all__ = [
    "PrefixAffinityRouter", "RouteDecision", "NoLiveReplicas",
    "ReplicaSupervisor", "InProcessReplica", "Routed",
    "WorkerReplica", "WorkerHandle", "WorkerRPCTimeout",
    "spawn_worker_fleet",
    "FleetFrontDoor", "start_front_door",
    "run_fleet_comparison",
]
