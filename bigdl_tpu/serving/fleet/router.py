"""Prefix-affinity request routing over a replica ring.

The fleet's placement problem: the prefix cache (PR 4/10) only pays
when template-sharing requests land on the SAME replica — spraying a
hot template round-robin across N replicas multiplies its KV footprint
by N and divides every trie's hit rate. ``PrefixAffinityRouter``
therefore consistent-hashes the first prefix-cache chunk of each
prompt onto a ring of virtual nodes: requests sharing a cacheable head
share a hash key, the key owns a stable arc of the ring, and the arc's
replica accumulates that template's KV exactly once fleet-wide.

Affinity must not become pinning, so two relief valves mirror the
admission queue's bounded-bypass pattern (``AdmissionQueue.pop_ready``):

- **saturation spill** — when the affinity target's polled load is at
  or past ``saturation``, the request spills to the least-loaded live
  replica instead of queueing behind the hot spot;
- **forced spill** — a hot template may win affinity at most
  ``spill_window`` consecutive times while a strictly-less-loaded
  replica sits available; the next request is forced to spill. One
  viral prompt therefore costs at most a bounded affinity streak
  before the rest of the fleet shares the load, exactly as one
  cache-rich admission may bypass the FCFS head only ``window`` times.

Pure host-side data structure: no engines, no I/O, no clocks — unit
testable in isolation (join/leave moves ~1/N keys; the decision table
is deterministic given the load map).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence

import numpy as np

__all__ = ["NoLiveReplicas", "PrefixAffinityRouter", "RouteDecision"]


class NoLiveReplicas(RuntimeError):
    """Every replica is draining or removed — nothing can take traffic."""


class RouteDecision(NamedTuple):
    """One routing verdict: where the request goes and why.

    ``route`` is ``"affinity"`` (the hash owner took it) or
    ``"spilled"`` (owner saturated, or the forced-spill bound fired —
    ``forced`` distinguishes the two). ``target`` is the ring owner
    the key hashed to, kept even when the request spilled so hit-rate
    forensics can see which arc overflowed."""

    replica: str
    route: str
    target: str
    key: int
    forced: bool = False


def _stable_hash(data: bytes) -> int:
    # process-independent (PYTHONHASHSEED-proof): router decisions must
    # agree between the bench parent, tests, and any future multi-node
    # front doors fed the same ring
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


class PrefixAffinityRouter:
    """Consistent-hash router with load-aware spill and a forced-spill
    bound.

    ``chunk`` should match the engines' ``prefill_chunk``: the hash key
    is the first chunk of prompt ids — the same head the prefix cache
    indexes — so two prompts that would share a trie entry always share
    a ring key. ``vnodes`` virtual nodes per replica smooth the arcs;
    ``saturation`` is the polled-load level (queue depth + active
    slots, by default) at which the owner stops taking new affinity
    traffic; ``spill_window`` bounds consecutive affinity wins while a
    less-loaded replica idles (0 disables the bound).

    Thread-safe: the front door routes from concurrent HTTP threads
    while the supervisor's poll loop marks replicas draining/live.
    """

    def __init__(self, replicas: Iterable[str] = (), chunk: int = 16,
                 vnodes: int = 64, saturation: float = 8.0,
                 spill_window: int = 8):
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.chunk = int(chunk)
        self.vnodes = int(vnodes)
        self.saturation = float(saturation)
        self.spill_window = int(spill_window)
        self._lock = threading.RLock()
        self._replicas: List[str] = []
        self._draining: set = set()
        self._ring: List[int] = []        # sorted hash points
        self._ring_owner: List[str] = []  # point -> replica id
        # forced-spill bound state: consecutive affinity routes to one
        # replica (any other route resets it — the ring-level analogue
        # of AdmissionQueue._head_bypasses)
        self._streak_rid: Optional[str] = None
        self._streak = 0
        self._counts = {"affinity": 0, "spilled": 0, "forced": 0}
        self._per_replica: Dict[str, Dict[str, int]] = {}
        for rid in replicas:
            self.add_replica(rid)

    # ------------------------------------------------------ membership
    def add_replica(self, rid: str) -> None:
        with self._lock:
            if rid in self._replicas:
                return
            self._replicas.append(rid)
            self._per_replica.setdefault(
                rid, {"affinity": 0, "spilled": 0})
            for v in range(self.vnodes):
                p = _stable_hash(f"{rid}#{v}".encode())
                i = bisect.bisect(self._ring, p)
                self._ring.insert(i, p)
                self._ring_owner.insert(i, rid)

    def remove_replica(self, rid: str) -> None:
        with self._lock:
            if rid not in self._replicas:
                return
            self._replicas.remove(rid)
            self._draining.discard(rid)
            keep = [(p, r) for p, r in zip(self._ring, self._ring_owner)
                    if r != rid]
            self._ring = [p for p, _ in keep]
            self._ring_owner = [r for _, r in keep]
            if self._streak_rid == rid:
                self._streak_rid, self._streak = None, 0

    def mark_draining(self, rid: str) -> None:
        """Take ``rid`` out of rotation WITHOUT moving its ring arcs:
        lookups walk past it to the next live owner, and ``mark_live``
        restores the exact prior keyspace — a drain/rejoin cycle moves
        each affected key twice and every other key zero times."""
        with self._lock:
            if rid in self._replicas:
                self._draining.add(rid)

    def mark_live(self, rid: str) -> None:
        with self._lock:
            self._draining.discard(rid)

    @property
    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    @property
    def draining(self) -> List[str]:
        with self._lock:
            return sorted(self._draining)

    def live_replicas(self) -> List[str]:
        with self._lock:
            return [r for r in self._replicas if r not in self._draining]

    # ---------------------------------------------------------- lookup
    def key_for(self, prompt_ids: Sequence[int]) -> int:
        """The routing key: a stable hash of the first prefix-cache
        chunk of the prompt (the whole prompt when shorter)."""
        head = np.asarray(prompt_ids, np.int32).reshape(-1)[:self.chunk]
        return _stable_hash(head.tobytes())

    def owner(self, key: int) -> str:
        """The ring owner among LIVE replicas: the first live replica
        at or after the key's point, walking the ring."""
        with self._lock:
            return self._owner_locked(key)

    def _owner_locked(self, key: int) -> str:
        if not self._ring:
            raise NoLiveReplicas("router has no replicas")
        n = len(self._ring)
        i = bisect.bisect(self._ring, key) % n
        for step in range(n):
            rid = self._ring_owner[(i + step) % n]
            if rid not in self._draining:
                return rid
        raise NoLiveReplicas("all replicas are draining")

    # ----------------------------------------------------------- route
    def route(self, prompt_ids: Sequence[int],
              loads: Optional[Dict[str, float]] = None) -> RouteDecision:
        """Decide a replica for one prompt. ``loads`` maps replica id
        -> current load (the supervisor passes queue depth + active
        slots from its last poll; missing/None entries read as 0 —
        an unpolled replica is assumed idle)."""
        key = self.key_for(prompt_ids)
        loads = loads or {}
        with self._lock:
            target = self._owner_locked(key)
            load = float(loads.get(target) or 0.0)
            live = [r for r in self._replicas
                    if r not in self._draining]
            least = min(
                live, key=lambda r: (float(loads.get(r) or 0.0),
                                     r))
            least_load = float(loads.get(least) or 0.0)
            forced = (
                self.spill_window > 0
                and self._streak_rid == target
                and self._streak >= self.spill_window
                and least_load < load)
            if load >= self.saturation or forced:
                # spill to the least-loaded live replica (which may be
                # the target itself when the whole fleet is saturated
                # evenly — then the decision degrades to affinity-ish
                # placement but is still counted as a spill)
                rid, route = least, "spilled"
                self._counts["spilled"] += 1
                if forced:
                    self._counts["forced"] += 1
                self._per_replica.setdefault(
                    rid, {"affinity": 0, "spilled": 0})["spilled"] += 1
                self._streak_rid, self._streak = None, 0
            else:
                rid, route = target, "affinity"
                self._counts["affinity"] += 1
                self._per_replica.setdefault(
                    rid, {"affinity": 0, "spilled": 0})["affinity"] += 1
                if self._streak_rid == rid:
                    self._streak += 1
                else:
                    self._streak_rid, self._streak = rid, 1
            return RouteDecision(rid, route, target, key, forced)

    # ------------------------------------------------------- forensics
    def ownership(self, sample: int = 4096) -> Dict[str, float]:
        """Approximate live-keyspace share per replica (``sample``
        evenly spaced probe keys walked through ``owner``) — the demo's
        routing table."""
        with self._lock:
            if not self._ring:
                return {}
            out = {r: 0 for r in self._replicas
                   if r not in self._draining}
            span = 1 << 64
            for s in range(sample):
                out[self._owner_locked(s * span // sample)] += 1
            return {r: round(c / sample, 4) for r, c in out.items()}

    def snapshot(self) -> dict:
        """The routing table as one JSON-able dict: membership, drain
        set, decision tallies, and per-replica affinity/spill counts."""
        with self._lock:
            return {
                "replicas": list(self._replicas),
                "draining": sorted(self._draining),
                "vnodes": self.vnodes,
                "chunk": self.chunk,
                "saturation": self.saturation,
                "spill_window": self.spill_window,
                "decisions": dict(self._counts),
                "per_replica": {r: dict(c) for r, c in
                                self._per_replica.items()},
                "ownership": self.ownership(1024),
            }
