"""Replica ownership, health-aware draining, and fleet routing.

``ReplicaSupervisor`` is the fleet's control plane: it owns N replicas
(in-process ``InProcessReplica`` wrappers for tests and demos,
``multiprocessing`` ``WorkerReplica`` workers for the bench — anything
with the small replica protocol below), polls each one's ``healthz()``
+ load gauges on a background thread, and folds the results into the
``PrefixAffinityRouter``'s live set:

- a replica whose ``healthz()`` reports ``status: degraded`` (active
  watchdog alerts — PR 5) or raises (the crashed-loop 503 — PR 3) is
  **drained**: ``replica.drain()`` stops new admissions, the router
  stops offering it traffic, and every request already in flight runs
  to completion;
- a drained replica whose probe comes back clean **rejoins**:
  ``replica.resume()`` + back into the ring. Operator drains
  (``supervisor.drain(rid)``) never auto-rejoin.

``submit()`` is the data plane: route (affinity or round-robin),
hand the prompt to the chosen replica, and re-route once if the
replica refuses in the drain/stop race window. Every decision lands in
the ``bigdl_fleet_*`` instruments.

Replica protocol (duck-typed): ``id``, ``submit(prompt_ids,
max_new_tokens, tenant=, timeout_s=, block=) -> handle``, ``stats()``,
``healthz()`` (raising = crashed), ``drain()``, ``resume()``,
``start()``, ``stop()``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional

from bigdl_tpu.observability import fleet_instruments
from bigdl_tpu.observability.events import default_recorder
from bigdl_tpu.observability.fleettrace import (
    merge_request_timelines,
)
from bigdl_tpu.observability.timeseries import (
    merge_fleet_timeseries, render_fleet_dashboard,
)
from bigdl_tpu.serving.fleet.router import (
    NoLiveReplicas, PrefixAffinityRouter,
)
from bigdl_tpu.serving.fleet.worker import WorkerRPCTimeout
from bigdl_tpu.serving.streams import EngineDraining, EngineStopped

__all__ = ["InProcessReplica", "ReplicaSupervisor", "Routed"]

#: drain reasons the poll loop may lift again once the probe is clean
#: (rpc_timeout: the wedged child answered again)
_AUTO_REASONS = ("degraded", "crashed", "rpc_timeout")


class Routed(NamedTuple):
    """One accepted fleet submission: the replica's request handle plus
    where it landed and why (``route`` is ``affinity`` / ``spilled`` /
    ``round_robin``). ``trace_id`` is the request's distributed-trace
    id; ``route_s`` / ``rpc_submit_s`` are the supervisor-measured
    first two fleet hops (routing decision wall, replica ``submit()``
    call wall — summed across any re-route retries), which the front
    door folds into the ``bigdl_fleet_hop_seconds`` breakdown."""

    handle: object
    replica: str
    route: str
    trace_id: Optional[str] = None
    route_s: float = 0.0
    rpc_submit_s: float = 0.0


class InProcessReplica:
    """One ``ContinuousBatchingEngine`` behind the replica protocol —
    the in-process deployment used by tests and the ``serve.py`` demo
    (every replica shares this process's devices; the bench's
    ``WorkerReplica`` gives each its own)."""

    def __init__(self, rid: str, engine):
        self.id = rid
        self.engine = engine

    def submit(self, prompt_ids, max_new_tokens: int,
               tenant: Optional[str] = None,
               timeout_s: Optional[float] = None, block: bool = True,
               priority: str = "normal",
               trace_id: Optional[str] = None):
        return self.engine.submit(prompt_ids, max_new_tokens,
                                  timeout_s=timeout_s, block=block,
                                  tenant=tenant, priority=priority,
                                  trace_id=trace_id)

    def stats(self) -> dict:
        return self.engine.stats()

    def healthz(self) -> dict:
        return self.engine.healthz()

    def drain(self) -> None:
        self.engine.drain()

    def resume(self) -> None:
        self.engine.resume()

    def start(self) -> None:
        self.engine.start()

    def stop(self) -> None:
        self.engine.stop()

    def incident_export(self, n: Optional[int] = None) -> dict:
        """The engine's ``debug_incidents`` payload — same shape as
        the worker RPC, so the supervisor's fleet merge treats both
        deployments identically."""
        return self.engine.debug_incidents(n)

    def timeseries_export(self, metric: Optional[str] = None,
                          n: Optional[int] = None) -> dict:
        """The engine's ``debug_timeseries`` payload — same shape as
        the worker RPC (an in-process replica shares the parent's
        clock, so its offset is zero by construction)."""
        return self.engine.debug_timeseries(metric=metric, n=n)


class ReplicaSupervisor:
    """Own replicas, poll health, drain/rejoin, route submissions.

    ``policy`` is ``"affinity"`` (default — the prefix-affinity ring)
    or ``"round_robin"`` (the bench's control leg). ``saturation``
    and ``spill_window`` pass through to the router; ``chunk`` should
    match the engines' ``prefill_chunk``. ``poll_interval`` paces the
    health thread; ``start()`` runs one synchronous poll before
    returning so routing never begins blind.
    """

    def __init__(self, replicas, *, policy: str = "affinity",
                 chunk: int = 16, vnodes: int = 64,
                 saturation: float = 8.0, spill_window: int = 8,
                 poll_interval: float = 0.25,
                 clock_resync_s: float = 30.0,
                 fleet_name: str = "fleet", registry=None,
                 recorder=None):
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.policy = policy
        self.fleet_name = fleet_name
        self.poll_interval = float(poll_interval)
        self._replicas: Dict[str, object] = {r.id: r for r in replicas}
        if not self._replicas:
            raise ValueError("a fleet needs at least one replica")
        self.router = PrefixAffinityRouter(
            self._replicas, chunk=chunk, vnodes=vnodes,
            saturation=saturation, spill_window=spill_window)
        self._ins = fleet_instruments(fleet_name, registry=registry)
        self._rec = recorder if recorder is not None \
            else default_recorder()
        self._lock = threading.RLock()
        self._loads: Dict[str, float] = {}
        self._health: Dict[str, dict] = {}
        self._drained: Dict[str, str] = {}   # rid -> reason
        self._rr_next = 0
        #: how stale a worker's ping-estimated clock offset may get
        #: before the poll loop re-syncs it (drift guard for the
        #: merged fleet trace)
        self.clock_resync_s = float(clock_resync_s)
        # finished-request hop breakdowns, newest last (the
        # /debug/fleet/requests ring)
        self._requests: "collections.deque" = collections.deque(
            maxlen=256)
        # rid -> collected crash-postmortem summary (path + error)
        self._postmortems: Dict[str, dict] = {}
        # rid -> monotonic deadline before which a wedged replica is
        # NOT re-probed (each probe of a wedged child costs a full
        # rpc_timeout — without backoff the poll loop would spend all
        # its wall blocked on the one stuck pipe)
        self._wedged_until: Dict[str, float] = {}
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ReplicaSupervisor":
        if self._started:
            return self
        for r in self._replicas.values():
            r.start()
        self._started = True
        self.poll_once()
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, name="fleet-supervisor",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for r in self._replicas.values():
            try:
                r.stop()
            except Exception:
                # graftlint: ok[resource-hygiene] — best-effort fan-out stop; one dead replica must not block the rest
                pass
        self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---------------------------------------------------- health plane
    def _poll_loop(self) -> None:
        while not self._stop_evt.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception:
                # graftlint: ok[resource-hygiene] — a poll crash must not kill supervision; the next tick retries
                pass

    def poll_once(self) -> Dict[str, dict]:
        """One synchronous health sweep: probe every replica, refresh
        the router's load map and the ``bigdl_fleet_*`` gauges, drain
        what degraded/crashed, rejoin what recovered. Returns the
        per-replica probe results (exception reprs for crashed ones)."""
        results: Dict[str, dict] = {}
        for rid, rep in list(self._replicas.items()):
            until = self._wedged_until.get(rid)
            if until is not None and time.monotonic() < until:
                results[rid] = {"status": "wedged", "backoff": True}
                continue
            try:
                hz = rep.healthz()
                results[rid] = hz
                self._wedged_until.pop(rid, None)
            except WorkerRPCTimeout as e:
                # alive but not answering: the wedged-child path —
                # count it and degrade to auto-drain instead of
                # letting the next poll block on it again
                self._ins.rpc_timeouts_total.labels(
                    self.fleet_name, rid).inc()
                self._wedged_until[rid] = time.monotonic() \
                    + 2 * getattr(rep, "rpc_timeout", 10.0)
                results[rid] = {"status": "wedged", "error": repr(e)}
                with self._lock:
                    self._health[rid] = results[rid]
                    self._loads.pop(rid, None)
                if self._drained.get(rid) is None:
                    self.drain(rid, reason="rpc_timeout")
                continue
            except Exception as e:
                results[rid] = {"status": "crashed", "error": repr(e)}
                with self._lock:
                    self._health[rid] = results[rid]
                    self._loads.pop(rid, None)
                if self._drained.get(rid) is None:
                    self.drain(rid, reason="crashed")
                continue
            load = float(hz.get("queue_depth", 0)
                         + hz.get("active_slots", 0))
            with self._lock:
                self._health[rid] = hz
                self._loads[rid] = load
            if hasattr(rep, "maybe_sync_clock"):
                try:
                    off = rep.maybe_sync_clock(self.clock_resync_s)
                    if off is not None:
                        self._ins.clock_offset_seconds.labels(
                            self.fleet_name, rid).set(off)
                except Exception:
                    # graftlint: ok[resource-hygiene] — a failed resync keeps the last estimate; the next poll retries
                    pass
            self._ins.replica_queue_depth.labels(
                self.fleet_name, rid).set(hz.get("queue_depth", 0))
            self._ins.replica_active_slots.labels(
                self.fleet_name, rid).set(hz.get("active_slots", 0))
            reason = self._drained.get(rid)
            if hz.get("status") == "degraded" and reason is None:
                self.drain(rid, reason="degraded")
            elif reason in _AUTO_REASONS \
                    and hz.get("status") == "ok":
                self.rejoin(rid)
        live = self.router.live_replicas()
        self._ins.replicas_live.set(len(live))
        self._ins.replicas_draining.set(
            len(self._replicas) - len(live))
        return results

    def drain(self, rid: str, reason: str = "operator") -> None:
        """Take ``rid`` out of rotation: the router routes new traffic
        away and the replica refuses new admissions while its in-flight
        requests finish. Recovered auto-drains rejoin on a clean poll;
        operator drains wait for ``rejoin()``."""
        with self._lock:
            if rid not in self._replicas:
                raise KeyError(f"unknown replica {rid!r}")
            already = rid in self._drained
            self._drained[rid] = reason
        self.router.mark_draining(rid)
        try:
            self._replicas[rid].drain()
        except Exception:
            pass  # graftlint: ok[resource-hygiene] — a crashed replica can't ack the drain; it's marked draining either way
        if not already:
            self._ins.drains_total.labels(
                self.fleet_name, reason).inc()
            pm = (self._collect_postmortem(rid)
                  if reason in ("crashed", "rpc_timeout") else None)
            extra = {"postmortem": pm["path"],
                     "postmortem_error": (pm.get("error") or {}
                                          ).get("type")} \
                if pm else {}
            self._rec.record("fleet/drain", rid, fleet=self.fleet_name,
                             replica=rid, reason=reason, **extra)

    def _collect_postmortem(self, rid: str) -> Optional[dict]:
        """Read the crashed worker's postmortem artifact (if its
        engine wrote one) into a parent-side summary — path, error
        type/message, event count — so the child's crash is
        diagnosable from the fleet ``stats()`` without shelling into
        the worker's filesystem view. Best-effort: a missing or torn
        file just means no summary."""
        with self._lock:
            rep = self._replicas.get(rid)
        path = getattr(rep, "postmortem_path", None)
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                pm = json.load(f)
        except (OSError, ValueError):
            return None
        err = pm.get("error") or {}
        summary = {
            "path": path,
            "schema": pm.get("schema"),
            "created_at": pm.get("created_at"),
            "error": {"type": err.get("type"),
                      "message": err.get("message")},
            "events": len(pm.get("events") or []),
            "requests": len(pm.get("requests") or []),
        }
        with self._lock:
            self._postmortems[rid] = summary
        return summary

    def rejoin(self, rid: str) -> None:
        """Return a drained replica to rotation (``resume()`` + back
        into the ring)."""
        with self._lock:
            if rid not in self._replicas:
                raise KeyError(f"unknown replica {rid!r}")
            was = self._drained.pop(rid, None)
        try:
            self._replicas[rid].resume()
        except Exception:
            # graftlint: ok[resource-hygiene] — a dead replica can't ack the resume; health polling re-drains it
            pass
        self.router.mark_live(rid)
        if was is not None:
            self._ins.rejoins_total.inc()
            self._rec.record("fleet/rejoin", rid, fleet=self.fleet_name,
                             replica=rid, was=was)

    # ------------------------------------------------------ data plane
    def submit(self, prompt_ids, max_new_tokens: int,
               tenant: Optional[str] = None,
               priority: str = "normal",
               timeout_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> Routed:
        """Route one request and submit it. ``priority`` reaches the
        replica engine's admission queue (class-ordered pop,
        preemption eligibility, shed order — see the engine's QoS
        docs) and also maps to the backpressure stance here:
        ``"low"`` never blocks on a full replica queue (``QueueFull``
        propagates to the caller — the front door turns it into 429),
        everything else waits. An engine-side ``RequestShed`` /
        ``RequestRateLimited`` rejection propagates unchanged (the
        front door's 429 + Retry-After). The chosen replica refusing
        (drain/stop race with the poll thread) re-routes once per
        remaining live replica before giving up.

        ``trace_id`` (the front door's minted/forwarded id) is passed
        through to the replica — worker replicas carry it over the
        pipe into the child ``engine.submit`` so the whole
        cross-process arc shares one id. The returned ``Routed``
        carries the measured ``route_s`` / ``rpc_submit_s`` hops."""
        block = priority != "low"
        tried: set = set()
        kwargs = {} if trace_id is None else {"trace_id": trace_id}
        route_s = rpc_submit_s = 0.0
        while True:
            t0 = time.monotonic()
            rid, route = self._pick(prompt_ids, tried)
            t1 = time.monotonic()
            route_s += t1 - t0
            try:
                h = self._replicas[rid].submit(
                    prompt_ids, max_new_tokens, tenant=tenant,
                    timeout_s=timeout_s, block=block,
                    priority=priority, **kwargs)
            except (EngineDraining, EngineStopped):
                rpc_submit_s += time.monotonic() - t1
                tried.add(rid)
                self._ins.rerouted_total.inc()
                if len(tried) >= len(self._replicas):
                    raise
                continue
            rpc_submit_s += time.monotonic() - t1
            self._ins.requests_total.inc()
            self._ins.routed_total.labels(self.fleet_name, route).inc()
            req_id = getattr(h, "request_id", None)
            if trace_id is not None and req_id is not None:
                # the front-door process's side of the request carries
                # the trace too — its fleet/* events join the child's
                # arc in the merged trace
                self._rec.bind_request(req_id, trace=trace_id,
                                       replica=rid)
            self._rec.record("fleet/submitted", req_id,
                             fleet=self.fleet_name, replica=rid,
                             route=route)
            return Routed(h, rid, route, trace_id, route_s,
                          rpc_submit_s)

    def _pick(self, prompt_ids, tried) -> tuple:
        with self._lock:
            loads = dict(self._loads)
        live = [r for r in self.router.live_replicas()
                if r not in tried]
        if not live:
            raise NoLiveReplicas(
                "no live replica can take the request "
                f"(draining: {self.router.draining})")
        if self.policy == "round_robin":
            with self._lock:
                rid = live[self._rr_next % len(live)]
                self._rr_next += 1
            return rid, "round_robin"
        if tried:
            # re-route: hash owner already refused — go least-loaded
            rid = min(live, key=lambda r: (loads.get(r) or 0.0, r))
            return rid, "spilled"
        d = self.router.route(prompt_ids, loads)
        return d.replica, d.route

    # --------------------------------------------------- fleet tracing
    def note_request(self, routed: Routed, hops: Dict[str, float],
                     total_s: float, outcome: str = "finished"
                     ) -> dict:
        """Record one completed request's hop decomposition: observe
        every ``bigdl_fleet_hop_seconds`` component, append the entry
        to the ``/debug/fleet/requests`` ring, and close the front-
        door process's side of the trace with a ``fleet/request_done``
        event. Called by the front door once the stream is fully
        written — ``total_s`` is the client-observed wall."""
        for hop, s in hops.items():
            self._ins.hop_seconds.labels(self.fleet_name,
                                         hop).observe(s)
        entry = {
            "request_id": getattr(routed.handle, "request_id", None),
            "trace_id": routed.trace_id,
            "replica": routed.replica,
            "route": routed.route,
            "outcome": outcome,
            "hops": {k: round(v, 6) for k, v in hops.items()},
            "hop_sum_s": round(sum(hops.values()), 6),
            "total_s": round(float(total_s), 6),
            "ts_s": time.monotonic(),
        }
        with self._lock:
            self._requests.append(entry)
        self._rec.record("fleet/request_done", entry["request_id"],
                         fleet=self.fleet_name,
                         replica=routed.replica, outcome=outcome,
                         total_s=round(float(total_s), 6))
        return entry

    def trace_exports(self, last: Optional[int] = None) -> List[dict]:
        """Per-process event exports for the fleet trace merge: the
        front-door process's own recorder (offset 0 — it IS the
        reference clock; in-process replicas share it) plus every
        worker replica's ``trace_export`` RPC, each tagged with its
        ping-estimated ``clock_offset_s``. Feed to
        ``merge_fleet_trace`` with ``wall_offset=self.wall_offset``."""
        exports: List[dict] = [{
            "process": "front-door",
            "pid": os.getpid(),
            "clock_offset_s": 0.0,
            "events": self._rec.snapshot(last),
        }]
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            export_fn = getattr(rep, "trace_export", None)
            if export_fn is None:
                continue
            try:
                payload = export_fn(last)
            except Exception as e:
                exports.append({"process": rid, "error": repr(e),
                                "events": [], "clock_offset_s": 0.0})
                continue
            exports.append({
                "process": rid,
                "clock_offset_s": getattr(rep, "clock_offset_s",
                                          None) or 0.0,
                "clock_rtt_s": getattr(rep, "clock_rtt_s", None),
                "events": payload.get("events") or [],
            })
        return exports

    @property
    def wall_offset(self) -> float:
        """The reference (front-door) monotonic→wall anchor the
        merged trace's microsecond axis uses."""
        return self._rec.wall_offset

    def fleet_requests(self, last: Optional[int] = None) -> dict:
        """The ``/debug/fleet/requests`` aggregate: the finished-
        request hop ring plus every request's per-process timeline
        joined across the fleet's trace exports (aligned first/last
        timestamps, event-kind sequences, trace ids)."""
        with self._lock:
            ring = list(self._requests)
        return {
            "fleet": self.fleet_name,
            "requests": ring,
            "timelines": merge_request_timelines(
                self.trace_exports(last)),
        }

    def metrics_snapshots(self) -> Dict[str, list]:
        """Every worker replica's registry as plain data (the
        ``metrics_export`` RPC) — the front door renders them under a
        ``replica=`` label on ``/metrics``. In-process replicas share
        the parent registry and are skipped."""
        out: Dict[str, list] = {}
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            metrics_fn = getattr(rep, "metrics_export", None)
            if metrics_fn is None:
                continue
            try:
                out[rid] = metrics_fn()
            except Exception:
                # graftlint: ok[resource-hygiene] — a dead/wedged replica just drops out of this scrape
                continue
        return out

    def incident_exports(self, n: Optional[int] = None
                         ) -> Dict[str, dict]:
        """Every replica's ``incident_export`` payload keyed by
        replica id (duck-typed, best-effort like
        ``metrics_snapshots`` — a replica without the method or with
        a dead pipe just drops out)."""
        out: Dict[str, dict] = {}
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            export_fn = getattr(rep, "incident_export", None)
            if export_fn is None:
                continue
            try:
                out[rid] = export_fn(n)
            except Exception as e:
                out[rid] = {"error": repr(e), "incidents": []}
        return out

    def fleet_incidents(self, n: Optional[int] = None) -> dict:
        """The ``/debug/fleet/incidents`` aggregate: every replica's
        bundles stamped with their replica id, fleet-wide counts by
        kind, detector states per replica, and the set of trace ids
        the bundles' exemplars reference — each resolvable in the
        merged fleet trace (``/debug/fleet/requests`` timelines)."""
        per = self.incident_exports(n)
        incidents: List[dict] = []
        by_kind: Dict[str, int] = {}
        detectors: Dict[str, dict] = {}
        trace_ids: set = set()
        for rid, payload in sorted(per.items()):
            if payload.get("error"):
                continue
            detectors[rid] = payload.get("detectors") or {}
            for kind, c in (payload.get("by_kind") or {}).items():
                by_kind[kind] = by_kind.get(kind, 0) + int(c)
            for bundle in payload.get("incidents") or []:
                stamped = dict(bundle)
                stamped["replica"] = rid
                incidents.append(stamped)
                for ex in bundle.get("exemplars") or []:
                    tid = ex.get("trace_id")
                    if tid:
                        trace_ids.add(tid)
        incidents.sort(key=lambda b: b.get("ts_s") or 0.0,
                       reverse=True)
        return {
            "fleet": self.fleet_name,
            "count": sum(by_kind.values()),
            "by_kind": by_kind,
            "detectors": detectors,
            "trace_ids": sorted(trace_ids),
            "incidents": incidents,
            "replicas": {rid: {"count": p.get("count", 0),
                               "error": p.get("error")}
                         for rid, p in sorted(per.items())},
        }

    def timeseries_exports(self, metric: Optional[str] = None,
                           n: Optional[int] = None) -> List[dict]:
        """Every replica's ``timeseries_export`` payload tagged with
        its ping-estimated clock offset — the
        ``merge_fleet_timeseries`` input (duck-typed, best-effort
        like ``incident_exports``; a replica without the method or
        with a dead pipe carries an ``error`` entry instead)."""
        exports: List[dict] = []
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            export_fn = getattr(rep, "timeseries_export", None)
            if export_fn is None:
                continue
            try:
                payload = export_fn(metric=metric, n=n)
            except Exception as e:
                exports.append({"replica": rid, "error": repr(e)})
                continue
            exports.append({
                "replica": rid,
                "clock_offset_s": getattr(rep, "clock_offset_s",
                                          None) or 0.0,
                "clock_rtt_s": getattr(rep, "clock_rtt_s", None),
                "export": payload,
            })
        return exports

    def fleet_timeseries(self, metric: Optional[str] = None,
                         n: Optional[int] = None) -> dict:
        """The ``/debug/fleet/timeseries`` aggregate: every replica's
        sampler rings merged onto the supervisor's clock (each
        point shifted by that replica's measured offset), keyed
        ``metric -> replica -> ring``, with fleet-sum/mean derived
        series."""
        return merge_fleet_timeseries(
            self.timeseries_exports(metric=metric, n=n),
            fleet=self.fleet_name)

    def fleet_capacity(self, offered_rps: Optional[float] = None
                       ) -> dict:
        """The ``/debug/fleet/capacity`` aggregate: every replica's
        ``stats()["capacity"]`` estimate folded into the fleet view
        (summed sustainable rates, fleet headroom, replicas-needed
        for the observed — or an explicit what-if — offered load),
        exported as the ``bigdl_fleet_capacity_{headroom,
        replicas_needed}`` gauges."""
        from bigdl_tpu.observability.capacity import (
            aggregate_fleet_capacity,
        )

        per: Dict[str, Optional[dict]] = {}
        budgets: Dict[str, dict] = {}
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            try:
                s = rep.stats()
            except Exception:
                per[rid] = None
                continue
            per[rid] = s.get("capacity")
            if s.get("slo_budget"):
                budgets[rid] = s["slo_budget"]
        out = aggregate_fleet_capacity(per, offered_rps=offered_rps,
                                       fleet=self.fleet_name)
        out["slo_budget"] = budgets
        if out.get("headroom") is not None:
            self._ins.capacity_headroom.set(out["headroom"])
        if out.get("replicas_needed") is not None:
            self._ins.capacity_replicas_needed.set(
                out["replicas_needed"])
        return out

    def fleet_markers(self, n: Optional[int] = None) -> List[dict]:
        """Clock-aligned event markers for the fleet dashboard:
        drain/rejoin events from the front-door recorder (offset 0 —
        it IS the reference clock) plus every replica's captured
        incidents shifted by that replica's offset."""
        markers = []
        for ev in self._rec.snapshot():
            kind = ev.get("kind") or ""
            if kind == "fleet/drain":
                markers.append({"ts_s": ev.get("ts_s"),
                                "kind": "drain",
                                "label": "drain %s"
                                % (ev.get("request_id") or "")})
            elif kind == "fleet/rejoin":
                markers.append({"ts_s": ev.get("ts_s"),
                                "kind": "rejoin",
                                "label": "rejoin %s"
                                % (ev.get("request_id") or "")})
        with self._lock:
            replicas = list(self._replicas.items())
        offsets = {rid: getattr(rep, "clock_offset_s", None) or 0.0
                   for rid, rep in replicas}
        fi = self.fleet_incidents(n)
        for bundle in fi.get("incidents") or []:
            ts = bundle.get("ts_s")
            if ts is None:
                continue
            rid = bundle.get("replica")
            markers.append({
                "ts_s": ts + offsets.get(rid, 0.0),
                "kind": "incident",
                "label": "%s %s (%s)" % (rid, bundle.get("id"),
                                         bundle.get("kind")),
            })
        markers.sort(key=lambda m: m.get("ts_s") or 0.0)
        return markers

    def fleet_dashboard(self) -> str:
        """The ``/debug/fleet/dashboard`` page: one self-contained
        HTML document over the merged fleet timeline — one row per
        metric with per-replica overlays on the shared clock,
        incident/drain markers, per-replica SLO budget bars, and the
        fleet capacity block."""
        cap = self.fleet_capacity()
        budgets = []
        for rid, ledger in sorted((cap.get("slo_budget") or {}
                                   ).items()):
            for obj in ledger.get("objectives") or []:
                budgets.append({
                    "replica": rid,
                    "objective": obj.get("objective"),
                    "budget_remaining": obj.get("budget_remaining"),
                    "exhaustion_eta_s": obj.get("exhaustion_eta_s"),
                })
        return render_fleet_dashboard(
            self.fleet_timeseries(),
            title=self.fleet_name,
            extra={"capacity": {k: v for k, v in cap.items()
                                if k not in ("replicas",
                                             "slo_budget")},
                   "routing": self.router.snapshot()},
            markers=self.fleet_markers(),
            budgets=budgets or None)

    # ------------------------------------------------------ aggregates
    def loads(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._loads)

    def replica_ids(self) -> List[str]:
        return list(self._replicas)

    def healthz(self) -> dict:
        """Fleet-level health: ``ok`` while every replica serves,
        ``degraded`` when any is draining/crashed but at least one
        serves, raising when NOTHING can take traffic (the front
        door's 503, same convention as the engine's crashed loop)."""
        with self._lock:
            health = {rid: dict(h) for rid, h in self._health.items()}
            drained = dict(self._drained)
        live = self.router.live_replicas()
        if not live:
            raise NoLiveReplicas(
                f"no live replicas (drained: {drained})")
        return {
            "status": "ok" if not drained else "degraded",
            "fleet": self.fleet_name,
            "live": live,
            "draining": sorted(drained),
            "drain_reasons": drained,
            "replicas": health,
        }

    def stats(self) -> dict:
        """Fleet-wide ``GET /v1/stats``: per-replica ``stats()`` blocks
        plus the aggregate the router optimizes for — the fleet prefix
        hit rate (total hits over total lookups across every trie) —
        and the routing table."""
        per: Dict[str, dict] = {}
        hits = lookups = reused = prefilled = 0
        finished = 0
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, rep in replicas:
            try:
                s = rep.stats()
            except WorkerRPCTimeout as e:
                self._ins.rpc_timeouts_total.labels(
                    self.fleet_name, rid).inc()
                per[rid] = {"error": repr(e), "wedged": True}
                continue
            except Exception as e:
                per[rid] = {"error": repr(e)}
                continue
            per[rid] = s
            pc = s.get("prefix_cache") or {}
            if pc.get("enabled"):
                hits += pc.get("hits", 0)
                lookups += pc.get("hits", 0) + pc.get("misses", 0)
                reused += pc.get("reused_tokens", 0)
                prefilled += pc.get("prefilled_tokens", 0)
            finished += int(s.get("finished", 0) or 0)
        # a crash postmortem may land on disk AFTER the drain (the
        # child's crash handler races the parent's poll) — re-check
        # any crashed replica we have no summary for yet
        with self._lock:
            missing = [rid for rid, why in self._drained.items()
                       if why in ("crashed", "rpc_timeout")
                       and rid not in self._postmortems]
        for rid in missing:
            self._collect_postmortem(rid)
        with self._lock:
            postmortems = dict(self._postmortems)
        denom = reused + prefilled
        return {
            "fleet": self.fleet_name,
            "policy": self.policy,
            "finished": finished,
            "replicas": per,
            "prefix_cache": {
                "hits": hits,
                "lookups": lookups,
                "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
                "reused_tokens": reused,
                "prefilled_tokens": prefilled,
                "reused_fraction": (round(reused / denom, 4)
                                    if denom else 0.0),
            },
            "routing": self.router.snapshot(),
            "loads": self.loads(),
            # parent-side views of the workers: wedged-RPC tallies,
            # clock-offset estimates, and any collected crash
            # postmortems (path + error summary — satellite of the
            # fleet-tracing work; a child crash is diagnosable here)
            "rpc_timeouts": {
                rid: rep.rpc_timeouts
                for rid, rep in replicas
                if getattr(rep, "rpc_timeouts", 0)},
            "clock": {
                rid: {"offset_s": rep.clock_offset_s,
                      "rtt_s": rep.clock_rtt_s}
                for rid, rep in replicas
                if getattr(rep, "clock_offset_s", None) is not None},
            "postmortems": postmortems,
        }

    def routing_table(self) -> dict:
        return self.router.snapshot()

    def drain_wait(self, rid: str, timeout: float = 30.0) -> bool:
        """Block until ``rid`` reports zero in-flight work (drain
        completion) or ``timeout`` passes; True on fully drained."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                hz = self._replicas[rid].healthz()
            except Exception:
                return True  # crashed: nothing in flight survives it
            if hz.get("in_flight", hz.get("active_slots", 0)
                      + hz.get("queue_depth", 0)) == 0:
                return True
            time.sleep(0.01)
        return False
