"""Out-of-process replicas: one engine per worker process.

The bench's deployment shape (and the template for a real multi-host
fleet): each replica is a ``multiprocessing`` *spawn* worker that
builds its own model + ``ContinuousBatchingEngine`` on its own device
slice (a fresh process means a fresh XLA client — on CPU each worker
gets its own host device; on real hardware ``env`` pins
``JAX_PLATFORMS`` / visible-device flags per worker). The parent talks
to it over one duplex ``Pipe`` with a tiny message protocol, streaming
tokens one-way as they decode — never per-token request/response
(PAPERS.md, "RPC Considered Harmful"):

parent -> worker   ``{op: submit|cancel|healthz|stats|ping|``
                   ``trace_export|metrics_export|drain|resume|stop}``
worker -> parent   ``{ev: ready|token|done|error|reply|bye}``

Fleet tracing rides this protocol: ``submit`` carries the front
door's ``trace`` id into ``engine.submit(trace_id=...)`` (every child
recorder event then carries it, plus the ``replica=`` context stamped
at startup); ``ping`` answers with the child's monotonic clock for
the supervisor's min-RTT offset estimate (``sync_clock``);
``trace_export`` / ``metrics_export`` ship the child's flight-recorder
events and registry snapshot back for the merged fleet trace and the
replica-labelled ``/metrics`` aggregation. Control calls that miss
their deadline raise :class:`WorkerRPCTimeout` (counted in
``bigdl_fleet_rpc_timeouts_total``) so a wedged child degrades to
auto-drain instead of blocking the supervisor's poll loop.

``WorkerReplica`` implements the supervisor's replica protocol;
``WorkerHandle`` mirrors the ``RequestHandle`` streaming surface
(``tokens()`` / ``result()`` / ``cancel()``) with TTFT stamped on the
PARENT's clock at first-token receipt — monotonic clocks don't agree
across processes, and the router's A/B numbers must be measured where
the client sits.

Model/engine config crosses the fork as plain dicts (spawn pickles
them), so every worker built from the same ``cfg`` + seed holds a
bit-identical model — the fleet bench's token-parity oracle relies on
it.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.serving.streams import (
    EngineDraining, EngineStopped, QueueFull, RequestCancelled,
    RequestError, RequestRateLimited, RequestShed, RequestTimedOut,
)

__all__ = ["WorkerHandle", "WorkerRPCTimeout", "WorkerReplica",
           "spawn_worker_fleet"]


class WorkerRPCTimeout(EngineStopped):
    """A control round-trip (healthz/stats/ping/...) missed its
    deadline: the child process is alive but not answering — wedged.
    The supervisor counts it and auto-drains the replica."""

_ERRORS = {
    "RequestCancelled": RequestCancelled,
    "RequestTimedOut": RequestTimedOut,
    "RequestError": RequestError,
    "RequestShed": RequestShed,
    "RequestRateLimited": RequestRateLimited,
    "QueueFull": QueueFull,
    "EngineStopped": EngineStopped,
    "EngineDraining": EngineDraining,
}


def _worker_main(conn, cfg: dict) -> None:
    """Worker entry point (spawn target — must stay top-level).

    Applies ``cfg["env"]`` BEFORE importing jax (device-slice pinning
    has to precede backend init), builds the seeded model + engine,
    acks ``ready``, then serves the op loop until ``stop``/EOF."""
    import os

    for k, v in (cfg.get("env") or {}).items():
        os.environ[k] = str(v)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.observability.events import default_recorder
    from bigdl_tpu.observability.metrics import default_registry
    from bigdl_tpu.observability.postmortem import registry_snapshot
    from bigdl_tpu.serving import ContinuousBatchingEngine
    from bigdl_tpu.utils import random as rnd

    send_lock = threading.Lock()

    def send(msg: dict) -> None:
        with send_lock:
            try:
                conn.send(msg)
            except (OSError, EOFError, BrokenPipeError):
                pass

    try:
        # every event this process records carries its replica id —
        # the merged fleet trace's per-process attribution key
        default_recorder().set_context(
            replica=cfg.get("service", "worker"))
        rnd.set_seed(cfg.get("seed", 7))
        model = TransformerLM(**cfg["model"])
        model.evaluate()
        eng = ContinuousBatchingEngine(
            model, service_name=cfg.get("service", "worker"),
            **(cfg.get("engine") or {}))
        eng.start()
    except Exception as e:
        send({"ev": "ready", "error": repr(e)})
        return
    send({"ev": "ready"})

    handles: Dict[str, object] = {}
    cancelled: set = set()

    def submit_and_pump(rid: str, msg: dict) -> None:
        # runs on its own thread: a blocking put on a full admission
        # queue must never stall the op loop (healthz polls keep
        # answering mid-storm)
        toks: List[int] = []
        try:
            h = eng.submit(
                np.asarray(msg["prompt"], np.int32),
                msg["max_new"], tenant=msg.get("tenant"),
                timeout_s=msg.get("timeout_s"),
                block=msg.get("block", True),
                priority=msg.get("priority", "normal"),
                trace_id=msg.get("trace"))
        except Exception as e:
            send({"ev": "error", "rid": rid,
                  "kind": type(e).__name__, "msg": str(e),
                  "retry_after": getattr(e, "retry_after_s", None),
                  "tokens": []})
            return
        handles[rid] = h
        if rid in cancelled:  # cancel raced the blocking submit
            cancelled.discard(rid)
            h.cancel()
        try:
            for tok in h.tokens():
                toks.append(int(tok))
                send({"ev": "token", "rid": rid, "tok": int(tok)})
            send({"ev": "done", "rid": rid, "tokens": toks,
                  "timeline": h.timeline()})
        except Exception as e:
            send({"ev": "error", "rid": rid,
                  "kind": type(e).__name__, "msg": str(e),
                  "tokens": toks})
        finally:
            handles.pop(rid, None)

    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        op = msg.get("op")
        if op == "submit":
            threading.Thread(target=submit_and_pump,
                             args=(msg["rid"], msg),
                             daemon=True).start()
        elif op == "cancel":
            h = handles.get(msg["rid"])
            if h is not None:
                h.cancel()
            else:
                cancelled.add(msg["rid"])
        elif op == "ping":
            # the clock-sync fast path: answer with this process's
            # monotonic reading immediately (no engine call) so the
            # parent's min-RTT offset estimate stays tight
            send({"ev": "reply", "seq": msg["seq"],
                  "payload": {"mono": time.monotonic(),
                              "wall": time.time()}})
        elif op in ("healthz", "stats", "trace_export",
                    "metrics_export", "incident_export",
                    "timeseries_export"):
            try:
                if op == "healthz":
                    payload = eng.healthz()
                elif op == "stats":
                    payload = eng.stats()
                elif op == "trace_export":
                    # raw monotonic ts_s — the PARENT aligns them
                    # with its ping-estimated clock offset
                    payload = {
                        "service": cfg.get("service", "worker"),
                        "events": default_recorder().snapshot(
                            msg.get("last")),
                    }
                elif op == "incident_export":
                    payload = eng.debug_incidents(msg.get("n"))
                elif op == "timeseries_export":
                    # raw monotonic ts — the PARENT shifts them by
                    # its ping-estimated clock offset when merging
                    payload = eng.debug_timeseries(
                        metric=msg.get("metric"), n=msg.get("n"))
                else:
                    payload = registry_snapshot(default_registry())
                send({"ev": "reply", "seq": msg["seq"],
                      "payload": payload})
            except Exception as e:
                send({"ev": "reply", "seq": msg["seq"],
                      "kind": type(e).__name__, "error": str(e)})
        elif op in ("drain", "resume"):
            getattr(eng, op)()
            send({"ev": "reply", "seq": msg["seq"], "payload": True})
        elif op == "stop":
            try:
                eng.stop(drain=msg.get("drain", True),
                         timeout=msg.get("timeout", 10.0))
            finally:
                send({"ev": "bye"})
            break
    conn.close()


class WorkerHandle:
    """Parent-side view of one streaming request in a worker."""

    def __init__(self, rid: str, replica: "WorkerReplica"):
        self.request_id = rid
        self._replica = replica
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._tokens: List[int] = []
        self._timeline: Optional[dict] = None
        self._error: Optional[tuple] = None
        self._done_evt = threading.Event()

    # fed by the replica's reader thread
    def _push(self, msg: dict) -> None:
        ev = msg["ev"]
        if ev == "token":
            if self.first_token_at is None:
                self.first_token_at = time.monotonic()
            self._tokens.append(msg["tok"])
        elif ev == "done":
            self._timeline = msg.get("timeline")
            self.finished_at = time.monotonic()
            self._done_evt.set()
        elif ev == "error":
            self._error = (msg.get("kind", "RequestError"),
                           msg.get("msg", ""),
                           msg.get("retry_after"))
            self.finished_at = time.monotonic()
            self._done_evt.set()
        self._q.put(msg)

    def _raise_error(self):
        kind, text, retry = self._error
        cls = _ERRORS.get(kind, RequestError)
        if retry is not None and cls in (RequestShed,
                                         RequestRateLimited):
            # re-raise with the worker engine's bucket-derived backoff
            # intact — the front door turns it into Retry-After
            raise cls(text, retry_after_s=retry)
        raise cls(text)

    def tokens(self):
        """Stream generated token ids as the worker delivers them
        (terminal errors raise after the delivered prefix, matching
        ``RequestHandle.tokens()``)."""
        i = 0
        while True:
            # replay anything already received, then block for more
            if i < len(self._tokens):
                yield self._tokens[i]
                i += 1
                continue
            if self._done_evt.is_set() and self._q.empty():
                if self._error is not None:
                    self._raise_error()
                return
            try:
                self._q.get(timeout=0.1)
            except queue_mod.Empty:
                if not self._replica.alive():
                    self._error = self._error or (
                        "EngineStopped", "worker process died", None)
                    self._done_evt.set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; returns the GENERATED token ids (the
        parity row — prompt not included)."""
        if not self._done_evt.wait(timeout):
            raise RequestTimedOut(
                f"no terminal event within {timeout}s")
        if self._error is not None:
            self._raise_error()
        return list(self._tokens)

    def cancel(self) -> None:
        self._replica._send({"op": "cancel", "rid": self.request_id})

    def done(self) -> bool:
        return self._done_evt.is_set()

    def tokens_so_far(self) -> List[int]:
        return list(self._tokens)

    def timeline(self) -> dict:
        """The worker engine's own timeline, augmented with the
        parent-measured TTFT (``client_ttft_s``) — the number the
        fleet bench reports, since it includes routing + IPC."""
        tl = dict(self._timeline or {})
        if self.first_token_at is not None:
            tl["client_ttft_s"] = self.first_token_at \
                - self.submitted_at
        if self.finished_at is not None:
            tl["client_total_s"] = self.finished_at - self.submitted_at
        return tl


class WorkerReplica:
    """Supervisor replica protocol over one spawn worker process."""

    def __init__(self, rid: str, cfg: dict,
                 start_timeout: float = 120.0,
                 rpc_timeout: float = 10.0):
        self.id = rid
        self._cfg = dict(cfg)
        self._cfg.setdefault("service", rid)
        self._start_timeout = start_timeout
        #: control-call deadline (healthz/ping/drain/resume; stats
        #: gets 3x — it renders percentiles). A miss raises
        #: ``WorkerRPCTimeout`` instead of blocking the caller.
        self.rpc_timeout = float(rpc_timeout)
        #: control calls that hit their deadline (the supervisor
        #: mirrors this into ``bigdl_fleet_rpc_timeouts_total``)
        self.rpc_timeouts = 0
        #: ping-estimated monotonic-clock offset: add to a child
        #: timestamp to land on THIS process's monotonic timeline
        #: (None until the post-ready handshake syncs it)
        self.clock_offset_s: Optional[float] = None
        #: round trip of the winning ping sample — the offset's
        #: error bound is rtt/2
        self.clock_rtt_s: Optional[float] = None
        self._clock_synced_at: Optional[float] = None
        self._proc: Optional[mp.process.BaseProcess] = None
        self._conn = None
        self._reader: Optional[threading.Thread] = None
        self._send_lock = threading.Lock()
        self._reply_lock = threading.Lock()
        self._replies: "queue_mod.Queue" = queue_mod.Queue()
        self._handles: Dict[str, WorkerHandle] = {}
        self._handles_lock = threading.Lock()
        self._seq = 0
        self._next_rid = 0
        self._ready = threading.Event()
        self._ready_error: Optional[str] = None

    # ------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._proc is not None and self._proc.is_alive():
            return
        ctx = mp.get_context("spawn")
        self._conn, child = ctx.Pipe(duplex=True)
        self._proc = ctx.Process(
            target=_worker_main, args=(child, self._cfg),
            name=f"fleet-{self.id}", daemon=True)
        self._proc.start()
        child.close()
        self._reader = threading.Thread(
            target=self._read_loop, name=f"fleet-{self.id}-reader",
            daemon=True)
        self._reader.start()
        deadline = time.monotonic() + self._start_timeout
        while not self._ready.wait(0.2):
            if not self._proc.is_alive():
                raise EngineStopped(
                    f"worker {self.id} died during startup "
                    f"(exitcode {self._proc.exitcode})")
            if time.monotonic() > deadline:
                raise EngineStopped(
                    f"worker {self.id} did not come up within "
                    f"{self._start_timeout}s")
        if self._ready_error is not None:
            raise EngineStopped(
                f"worker {self.id} failed to start: "
                f"{self._ready_error}")
        try:
            # clock-sync handshake: part of coming up, but a failed
            # estimate must not kill an otherwise-healthy worker —
            # the supervisor's poll loop retries it
            self.sync_clock()
        except Exception:
            # graftlint: ok[resource-hygiene] — best-effort first sync; maybe_sync_clock refreshes on the poll loop
            pass

    def alive(self) -> bool:
        return self._proc is not None and self._proc.is_alive()

    def stop(self, timeout: float = 15.0) -> None:
        if self._proc is None:
            return
        try:
            self._send({"op": "stop", "drain": True,
                        "timeout": max(0.0, timeout - 5.0)})
        except Exception:
            # graftlint: ok[resource-hygiene] — best-effort goodbye on a possibly-dead pipe; join below is the real stop
            pass
        self._proc.join(timeout=timeout)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5)
        self._fail_all("worker stopped")

    # ---------------------------------------------------------- plumbing
    def _send(self, msg: dict) -> None:
        with self._send_lock:
            if self._conn is None:
                raise EngineStopped(f"worker {self.id} not started")
            try:
                self._conn.send(msg)
            except (OSError, EOFError, BrokenPipeError) as e:
                raise EngineStopped(
                    f"worker {self.id} pipe closed") from e

    def _read_loop(self) -> None:
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                break
            ev = msg.get("ev")
            if ev == "ready":
                self._ready_error = msg.get("error")
                self._ready.set()
            elif ev in ("token", "done", "error"):
                with self._handles_lock:
                    h = self._handles.get(msg["rid"])
                    if ev in ("done", "error"):
                        self._handles.pop(msg["rid"], None)
                if h is not None:
                    h._push(msg)
            elif ev == "reply":
                self._replies.put(msg)
            elif ev == "bye":
                break
        self._fail_all("worker pipe closed")

    def _fail_all(self, why: str) -> None:
        with self._handles_lock:
            pending, self._handles = dict(self._handles), {}
        for h in pending.values():
            h._push({"ev": "error", "kind": "EngineStopped",
                     "msg": why})

    def _call(self, op: str, timeout: float = 30.0, **extra):
        """One control round-trip (serialized: one outstanding call)."""
        with self._reply_lock:
            self._seq += 1
            seq = self._seq
            self._send({"op": op, "seq": seq, **extra})
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.rpc_timeouts += 1
                    raise WorkerRPCTimeout(
                        f"worker {self.id}: no {op} reply in "
                        f"{timeout}s (process alive but wedged)")
                try:
                    # graftlint: ok[lock-discipline] — _reply_lock IS the one-outstanding-call serializer; replies arrive from _read_loop, which never takes it
                    msg = self._replies.get(timeout=min(remaining, 0.5))
                except queue_mod.Empty:
                    if not self.alive():
                        raise EngineStopped(
                            f"worker {self.id} process died")
                    continue
                if msg.get("seq") != seq:
                    continue  # stale reply from a timed-out call
                if "error" in msg:
                    raise _ERRORS.get(msg.get("kind", ""),
                                      EngineStopped)(msg["error"])
                return msg.get("payload")

    # ------------------------------------------------ replica protocol
    def submit(self, prompt_ids, max_new_tokens: int,
               tenant: Optional[str] = None,
               timeout_s: Optional[float] = None,
               block: bool = True,
               priority: str = "normal",
               trace_id: Optional[str] = None) -> WorkerHandle:
        if not self.alive():
            raise EngineStopped(f"worker {self.id} process died")
        self._next_rid += 1
        rid = f"{self.id}-{self._next_rid}"
        h = WorkerHandle(rid, self)
        with self._handles_lock:
            self._handles[rid] = h
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self._send({"op": "submit", "rid": rid,
                    "prompt": [int(t) for t in prompt],
                    "max_new": int(max_new_tokens), "tenant": tenant,
                    "timeout_s": timeout_s, "block": block,
                    "priority": priority, "trace": trace_id})
        return h

    def healthz(self) -> dict:
        return self._call("healthz", timeout=self.rpc_timeout)

    def stats(self) -> dict:
        return self._call("stats", timeout=3 * self.rpc_timeout)

    def drain(self) -> None:
        self._call("drain", timeout=self.rpc_timeout)

    def resume(self) -> None:
        self._call("resume", timeout=self.rpc_timeout)

    # -------------------------------------------------- fleet tracing
    def sync_clock(self, samples: int = 8) -> float:
        """Ping the worker ``samples`` times and keep the min-RTT
        estimate of its monotonic-clock offset (``clock_offset_s``:
        add to a child timestamp to land on this process's timeline).
        Called once after ready and refreshed from the supervisor's
        poll loop (``maybe_sync_clock``) so drift never accumulates
        into the merged trace."""
        from bigdl_tpu.observability.fleettrace import (
            estimate_clock_offset,
        )

        def ping() -> float:
            return self._call("ping",
                              timeout=self.rpc_timeout)["mono"]

        off, rtt = estimate_clock_offset(ping, samples=samples)
        self.clock_offset_s, self.clock_rtt_s = off, rtt
        self._clock_synced_at = time.monotonic()
        return off

    def maybe_sync_clock(self, max_age_s: float = 30.0,
                         samples: int = 4) -> Optional[float]:
        """Refresh the offset estimate when the last sync is older
        than ``max_age_s`` (the poll loop's periodic refresh); returns
        the current offset (None before any successful sync)."""
        age_ok = (self._clock_synced_at is not None
                  and time.monotonic() - self._clock_synced_at
                  < max_age_s)
        if not age_ok:
            self.sync_clock(samples=samples)
        return self.clock_offset_s

    def trace_export(self, last: Optional[int] = None) -> dict:
        """The worker's flight-recorder snapshot (raw monotonic
        ``ts_s`` — ``merge_fleet_trace`` aligns them with
        ``clock_offset_s``)."""
        return self._call("trace_export",
                          timeout=3 * self.rpc_timeout, last=last)

    def metrics_export(self) -> list:
        """The worker's metric registry as plain data
        (``registry_snapshot`` shape) — the front door renders it
        under a ``replica=`` label on ``/metrics``."""
        return self._call("metrics_export",
                          timeout=3 * self.rpc_timeout)

    def incident_export(self, n: Optional[int] = None) -> dict:
        """The worker engine's ``debug_incidents`` payload (newest-n
        bundles, counts by kind, detector states) — the supervisor
        merges these into ``/debug/fleet/incidents``."""
        return self._call("incident_export",
                          timeout=3 * self.rpc_timeout, n=n)

    def timeseries_export(self, metric: Optional[str] = None,
                          n: Optional[int] = None) -> dict:
        """The worker engine's ``debug_timeseries`` payload (the
        sampler's bounded rings, raw monotonic ``ts``) — the
        supervisor shifts each point by ``clock_offset_s`` when
        merging into ``/debug/fleet/timeseries``."""
        return self._call("timeseries_export",
                          timeout=3 * self.rpc_timeout,
                          metric=metric, n=n)

    @property
    def postmortem_path(self) -> Optional[str]:
        """Where this worker's engine writes its crash postmortem
        (``spawn_worker_fleet`` assigns one per worker) — the
        supervisor collects it on a crash drain."""
        return (self._cfg.get("engine") or {}).get("postmortem_path")


def spawn_worker_fleet(n: int, model: dict, engine: Optional[dict]
                       = None, seed: int = 7,
                       env: Optional[dict] = None,
                       prefix: str = "r",
                       rpc_timeout: float = 10.0,
                       postmortem_dir: Optional[str] = None
                       ) -> List[WorkerReplica]:
    """Build (NOT start) ``n`` same-seed worker replicas — the
    supervisor's ``start()`` brings them up. Same ``model``/``seed``
    in every worker means bit-identical params, so any replica's
    greedy output is every replica's greedy output (the fleet bench's
    token-parity invariant).

    Unless the engine config pins ``postmortem_path``, each worker
    gets its own under ``postmortem_dir`` (a fresh temp dir by
    default) so a child crash leaves an artifact the supervisor can
    collect from the parent."""
    import os
    import tempfile

    base_engine = dict(engine or {})
    if "postmortem_path" not in base_engine:
        postmortem_dir = postmortem_dir or tempfile.mkdtemp(
            prefix="bigdl_fleet_pm_")
    cfg = {"model": dict(model), "seed": seed, "env": dict(env or {})}
    fleet = []
    for i in range(n):
        rid = f"{prefix}{i}"
        eng = dict(base_engine)
        if "postmortem_path" not in eng:
            eng["postmortem_path"] = os.path.join(
                postmortem_dir, f"{rid}_postmortem.json")
        fleet.append(WorkerReplica(
            rid, dict(cfg, engine=eng, service=rid),
            rpc_timeout=rpc_timeout))
    return fleet
