"""The fleet's HTTP front door: streaming inference over stdlib HTTP.

One ``ThreadingHTTPServer`` (the ``MetricsHTTPServer`` idiom — no
framework, no dependencies) in front of a ``ReplicaSupervisor``:

- ``POST /v1/generate`` — body ``{"prompt_ids": [...],
  "max_new_tokens": N, "tenant": ..., "priority": "high|normal|low",
  "stream": true}``. The streaming default answers with Server-Sent
  Events driven directly by the replica handle's token iterator — one
  held connection, tokens flowing one way as the engine decodes
  (PAPERS.md, "RPC Considered Harmful" — never a per-token
  request/response):

  ``event: meta``  — ``{request_id, replica, route, trace_id}``
  (where the router placed it, first thing on the wire);
  ``data:`` lines — ``{"token": t, "index": i}`` per decoded token;
  ``event: done`` — the terminal summary (token count, timeline).

  A client that disappears mid-stream is detected by the failed
  socket write and the request is CANCELLED into the engine — the
  slot frees immediately instead of decoding tokens nobody will read
  (``bigdl_fleet_client_disconnects_total``) — including while the
  request is still QUEUED (the socket is probed until the first
  token, so a vanished client frees its queue slot too).
  ``"stream": false`` returns one JSON body after completion.
  Backpressure maps to HTTP: ``QueueFull`` -> 429,
  ``RequestShed``/``RequestRateLimited`` -> 429 with a
  ``Retry-After`` header derived from the engine's token-bucket
  refill time, fleet down -> 503, bad request -> 400.
- ``GET /v1/stats`` — the supervisor's fleet-wide aggregate: per-
  replica ``stats()``, the fleet prefix hit rate, the routing table.
- ``GET /v1/replicas`` — just the routing table (the ``serve.py
  --fleet`` demo's table source).
- ``GET /healthz`` — 200 with the fleet health dict; 503 once no
  replica can take traffic (same crashed-loop convention as the
  engine endpoint).
- ``GET /metrics`` — Prometheus text, ``bigdl_fleet_*`` included,
  PLUS every worker child's registry fetched over pipe RPC and
  rendered with a ``replica="<rid>"`` label — one scrape, whole
  fleet.

Fleet tracing: every request gets a ``trace_id`` — an inbound W3C
``traceparent`` header is honored, otherwise one is minted — which
rides the pipe RPC into the replica so every recorder event and
usage record fleet-wide carries it. Responses echo ``X-Trace-Id`` /
``X-Request-Id``; the ``meta`` SSE event and the JSON body carry
``trace_id`` too. Finished requests are decomposed into
``bigdl_fleet_hop_seconds`` histogram observations
(route / rpc_submit / queue / prefill / first_token / decode /
stream) whose per-request sum reconciles with the client-observed
total. Two debug endpoints expose the merged view:

- ``GET /debug/fleet/trace`` — ONE Chrome/Perfetto trace merging the
  front door's and every worker process's recorder events onto a
  clock-aligned common timeline (per-process tracks).
- ``GET /debug/fleet/requests`` — the recent-request ring (hop
  breakdowns) plus per-request cross-process timelines.
- ``GET /debug/fleet/incidents[?n=]`` — every replica's incident
  bundles (``IncidentManager`` captures, fetched over the
  ``incident_export`` RPC) stamped with ``replica=``, fleet-wide
  counts by kind, per-replica detector states, and the trace ids the
  exemplars reference — each resolvable in the merged fleet trace.
- ``GET /debug/fleet/timeseries[?metric=&n=]`` — every replica's
  sampler rings (the ``timeseries_export`` RPC) merged onto the
  supervisor's clock-aligned timeline, keyed ``metric -> replica ->
  ring``, with fleet-sum/mean derived series.
- ``GET /debug/fleet/dashboard`` — one self-contained HTML page over
  the merged timeline: per-metric rows with per-replica SVG
  overlays, incident/drain markers, and SLO error-budget bars.
- ``GET /debug/fleet/capacity[?offered=]`` — the fleet capacity /
  what-if aggregate (sustainable rates, headroom, replicas-needed
  for the observed or an explicit offered load) plus each replica's
  error-budget ledger.
"""

from __future__ import annotations

import json
import math
import select
import socket
import threading
import time
from typing import Optional
from urllib.parse import parse_qs

from bigdl_tpu.observability.exporters import (
    PROMETHEUS_CONTENT_TYPE, render_prometheus,
    render_snapshot_prometheus,
)
from bigdl_tpu.observability.fleettrace import (
    hop_breakdown, mint_trace_id, parse_traceparent,
    render_fleet_trace,
)
from bigdl_tpu.observability.metrics import default_registry
from bigdl_tpu.serving.fleet.router import NoLiveReplicas
from bigdl_tpu.serving.streams import (
    EngineDraining, EngineStopped, QueueFull, RequestCancelled,
    RequestRateLimited, RequestShed, RequestTimedOut,
)

__all__ = ["FleetFrontDoor", "start_front_door"]

_MAX_BODY = 8 << 20  # refuse absurd request bodies before parsing


class FleetFrontDoor:
    """Serve a ``ReplicaSupervisor`` over HTTP. ``port=0`` binds an
    ephemeral port — read it back from ``.port``. Context manager;
    ``close()`` stops the listener (the supervisor's lifecycle stays
    the caller's)."""

    def __init__(self, supervisor, host: str = "127.0.0.1",
                 port: int = 0, registry=None):
        from http.server import (
            BaseHTTPRequestHandler, ThreadingHTTPServer,
        )

        sup = supervisor
        ins = sup._ins
        get_registry = (lambda: registry) if registry is not None \
            else default_registry

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _send_json(self, payload, status: int = 200,
                           headers: Optional[dict] = None):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_html(self, text: str, status: int = 200):
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_429(self, e) -> None:
                # structured shed / rate-limit rejection: Retry-After
                # comes from the engine's token-bucket refill math (or
                # the shed backoff), rounded UP to the header's whole
                # seconds — never 0, which clients read as "retry now"
                retry = max(1, math.ceil(
                    getattr(e, "retry_after_s", 1.0)))
                self._send_json(
                    {"error": str(e),
                     "kind": type(e).__name__,
                     "retry_after_s": getattr(e, "retry_after_s",
                                              1.0)},
                    429, headers={"Retry-After": str(retry)})

            def _client_gone(self) -> bool:
                # a disconnected client shows up as a readable socket
                # whose peek returns EOF — the only portable way to
                # see a hangup while we are WAITING (not writing)
                try:
                    r, _, _ = select.select([self.connection], [], [],
                                            0)
                    if not r:
                        return False
                    return self.connection.recv(
                        1, socket.MSG_PEEK) == b""
                except OSError:
                    return True

            # ------------------------------------------------ streaming
            def _sse(self, event: Optional[str], payload: dict) -> None:
                chunk = b""
                if event:
                    chunk += b"event: " + event.encode() + b"\n"
                chunk += b"data: " + json.dumps(payload).encode() \
                    + b"\n\n"
                self.wfile.write(chunk)
                self.wfile.flush()

            def _generate(self, req: dict) -> None:
                prompt = req.get("prompt_ids")
                if not isinstance(prompt, list) or not prompt \
                        or not all(isinstance(t, int) for t in prompt):
                    return self._send_json(
                        {"error": "prompt_ids must be a non-empty "
                                  "list of ints"}, 400)
                try:
                    n = int(req.get("max_new_tokens", 32))
                except (TypeError, ValueError):
                    return self._send_json(
                        {"error": "max_new_tokens must be an int"}, 400)
                stream = bool(req.get("stream", True))
                # trace context: honor an inbound W3C ``traceparent``
                # (or bare 32-hex id) so the fleet joins the caller's
                # distributed trace; mint fresh otherwise. The id rides
                # the pipe RPC into the replica and back out in the
                # merged fleet trace.
                trace_id = parse_traceparent(
                    self.headers.get("traceparent")) or mint_trace_id()
                t_start = time.monotonic()
                try:
                    routed = sup.submit(
                        prompt, n, tenant=req.get("tenant"),
                        priority=req.get("priority", "normal"),
                        timeout_s=req.get("timeout_s"),
                        trace_id=trace_id)
                except (RequestShed, RequestRateLimited) as e:
                    return self._send_429(e)
                except QueueFull as e:
                    return self._send_json(
                        {"error": f"fleet saturated: {e}"}, 429)
                except (NoLiveReplicas, EngineStopped,
                        EngineDraining) as e:
                    return self._send_json(
                        {"error": f"fleet unavailable: {e}"}, 503)
                except ValueError as e:
                    return self._send_json({"error": str(e)}, 400)
                h = routed.handle
                meta = {"request_id": getattr(h, "request_id", None),
                        "replica": routed.replica,
                        "route": routed.route,
                        "trace_id": trace_id}
                if not stream:
                    return self._collect(routed, meta, t_start)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("X-Trace-Id", trace_id)
                if meta["request_id"] is not None:
                    self.send_header("X-Request-Id",
                                     str(meta["request_id"]))
                # SSE is an unbounded stream: no Content-Length; close
                # delimits the body
                self.send_header("Connection", "close")
                self.end_headers()
                delivered = 0
                try:
                    self._sse("meta", meta)
                    # queued-phase disconnect watch: until the first
                    # token, no socket write happens — a vanished
                    # client would hold its queue slot until admission.
                    # Probe the connection while the request waits and
                    # cancel into the engine the moment the peer hangs
                    # up, freeing the slot for live traffic.
                    while (getattr(h, "first_token_at", None) is None
                           and not h.done()):
                        if self._client_gone():
                            h.cancel()
                            ins.disconnects_total.inc()
                            return
                        time.sleep(0.02)
                    for tok in h.tokens():
                        self._sse(None, {"token": int(tok),
                                         "index": delivered})
                        delivered += 1
                    total_s = time.monotonic() - t_start
                    hops = self._note_hops(routed, total_s)
                    self._sse("done", {**meta, "tokens": delivered,
                                       "timeline": h.timeline(),
                                       "hops": hops,
                                       "total_s": total_s})
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    # the client vanished mid-stream: cancel into the
                    # engine so the slot frees NOW instead of decoding
                    # to an audience of zero
                    h.cancel()
                    ins.disconnects_total.inc()
                except RequestCancelled:
                    try:
                        self._sse("error", {**meta,
                                            "error": "cancelled",
                                            "tokens": delivered})
                    except OSError:
                        pass
                except (RequestTimedOut, EngineStopped, RequestShed,
                        RequestRateLimited) as e:
                    # shed/rate-limit can surface HERE (not at submit)
                    # on worker replicas — their submit is async, so
                    # the rejection arrives as the stream's terminal
                    # event, retry advice included
                    payload = {**meta, "error": type(e).__name__,
                               "detail": str(e), "tokens": delivered}
                    if isinstance(e, (RequestShed,
                                      RequestRateLimited)):
                        payload["retry_after_s"] = e.retry_after_s
                    try:
                        self._sse("error", payload)
                    except OSError:
                        pass

            def _note_hops(self, routed, total_s: float):
                """Decompose the client-observed total into fleet hops
                and feed the supervisor's ``bigdl_fleet_hop_seconds``
                histograms + request ring. Best-effort: a hop record
                must never fail a request that already finished."""
                try:
                    h = routed.handle
                    tl = h.timeline() if hasattr(h, "timeline") else {}
                    hops = hop_breakdown(tl or {}, routed.route_s,
                                         routed.rpc_submit_s, total_s)
                    sup.note_request(routed, hops, total_s)
                    return hops
                except Exception:
                    return None

            def _collect(self, routed, meta: dict,
                         t_start: float) -> None:
                h = routed.handle
                hdrs = {"X-Trace-Id": meta["trace_id"]}
                if meta["request_id"] is not None:
                    hdrs["X-Request-Id"] = str(meta["request_id"])
                try:
                    toks = h.result(timeout=None) \
                        if hasattr(h, "result") else list(h.tokens())
                    toks = [int(t) for t in toks]
                except (RequestShed, RequestRateLimited) as e:
                    return self._send_429(e)
                except RequestCancelled:
                    return self._send_json(
                        {**meta, "error": "cancelled"}, 499,
                        headers=hdrs)
                except RequestTimedOut as e:
                    return self._send_json(
                        {**meta, "error": "timeout",
                         "detail": str(e)}, 504, headers=hdrs)
                except EngineStopped as e:
                    return self._send_json(
                        {**meta, "error": "engine stopped",
                         "detail": str(e)}, 503, headers=hdrs)
                total_s = time.monotonic() - t_start
                hops = self._note_hops(routed, total_s)
                # in-process handles' result() includes the prompt —
                # normalize to generated-only via the timeline count
                tl = h.timeline() if hasattr(h, "timeline") else {}
                gen = tl.get("tokens")
                if gen is not None and len(toks) > gen:
                    toks = toks[-gen:]
                self._send_json({**meta, "tokens": toks,
                                 "timeline": tl, "hops": hops,
                                 "total_s": total_s}, headers=hdrs)

            # ------------------------------------------------- requests
            def do_POST(self):  # noqa: N802 (stdlib handler contract)
                path = self.path.partition("?")[0]
                if path != "/v1/generate":
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    if not 0 < length <= _MAX_BODY:
                        return self._send_json(
                            {"error": "missing or oversized body"}, 400)
                    req = json.loads(self.rfile.read(length))
                    if not isinstance(req, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._send_json(
                        {"error": f"bad request body: {e}"}, 400)
                self._generate(req)

            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                path = self.path.partition("?")[0]
                if path == "/v1/stats":
                    try:
                        self._send_json(sup.stats())
                    except Exception as e:
                        self._send_json({"error": str(e)}, 500)
                elif path == "/v1/replicas":
                    self._send_json(sup.routing_table())
                elif path == "/healthz":
                    try:
                        self._send_json(sup.healthz())
                    except Exception as e:
                        self._send_json(
                            {"status": "unhealthy", "error": str(e)},
                            503)
                elif path == "/debug/fleet/trace":
                    # ONE merged Chrome/Perfetto trace for the whole
                    # fleet: front-door events plus every worker
                    # replica's recorder export, timestamps aligned by
                    # the supervisor's clock-offset estimates
                    try:
                        body = render_fleet_trace(
                            sup.trace_exports(),
                            wall_offset=sup.wall_offset).encode()
                    except Exception as e:
                        return self._send_json({"error": str(e)}, 500)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/debug/fleet/requests":
                    try:
                        self._send_json(sup.fleet_requests())
                    except Exception as e:
                        self._send_json({"error": str(e)}, 500)
                elif path == "/debug/fleet/incidents":
                    # every replica's incident bundles stamped with
                    # replica=, fleet counts by kind, and the trace
                    # ids the exemplars reference (each resolvable in
                    # /debug/fleet/requests' merged timelines)
                    try:
                        query = self.path.partition("?")[2]
                        n_raw = parse_qs(query).get("n", ["10"])[0]
                        self._send_json(
                            sup.fleet_incidents(int(n_raw)))
                    except Exception as e:
                        self._send_json({"error": str(e)}, 500)
                elif path == "/debug/fleet/timeseries":
                    # every replica's sampler rings merged onto the
                    # supervisor's clock (points shifted by each
                    # replica's ping-estimated offset)
                    try:
                        q = parse_qs(self.path.partition("?")[2])
                        metric = q.get("metric", [None])[0]
                        n_raw = q.get("n", [None])[0]
                        n = int(n_raw) if n_raw is not None else None
                        self._send_json(
                            sup.fleet_timeseries(metric=metric, n=n))
                    except Exception as e:
                        self._send_json({"error": str(e)}, 500)
                elif path == "/debug/fleet/dashboard":
                    try:
                        self._send_html(sup.fleet_dashboard())
                    except Exception as e:
                        self._send_html(
                            "<!doctype html><html><body><pre>fleet "
                            "dashboard error: %s</pre></body></html>"
                            % str(e), status=500)
                elif path == "/debug/fleet/capacity":
                    try:
                        q = parse_qs(self.path.partition("?")[2])
                        offered = q.get("offered", [None])[0]
                        self._send_json(sup.fleet_capacity(
                            offered_rps=(float(offered)
                                         if offered is not None
                                         else None)))
                    except Exception as e:
                        self._send_json({"error": str(e)}, 500)
                elif path == "/metrics":
                    text = render_prometheus(get_registry())
                    try:
                        # replica-labeled aggregation: each worker
                        # child's registry, fetched over pipe RPC and
                        # rendered with a replica="<rid>" label so one
                        # scrape sees the whole fleet
                        snaps = sup.metrics_snapshots()
                        if snaps:
                            text += "\n" + render_snapshot_prometheus(
                                snaps, label="replica")
                    except Exception:
                        # graftlint: ok[resource-hygiene] — child metrics are best-effort; the parent text still serves
                        pass
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *args):  # silence request spam
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-front-door",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start_front_door(supervisor, host: str = "127.0.0.1",
                     port: int = 0, registry=None) -> FleetFrontDoor:
    """Convenience wrapper: start and return a ``FleetFrontDoor``."""
    return FleetFrontDoor(supervisor, host=host, port=port,
                          registry=registry)
