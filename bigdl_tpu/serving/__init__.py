"""bigdl_tpu.serving — continuous-batching LM inference.

The serving-at-scale layer (BigDL 2.0's north-star capability, arxiv
2204.01715): a persistent device-resident decode loop over a
slot-pooled KV cache, replacing batch-at-a-time request/response
dispatch with token-granular continuous batching —

- ``ContinuousBatchingEngine`` (``engine``): the loop thread, the
  pooled ``(max_slots, ...)`` KV cache, mid-flight chunked-prefill
  admission (batched ``prefill_rows`` wide through one ragged dispatch
  per round), and per-token slot eviction/reuse. Compiled shapes
  depend only on ``max_slots``/``prefill_rows``/pool rows — never on
  load. Pass ``draft=`` (plus ``spec_gamma``) for SPECULATIVE decode:
  the draft proposes gamma tokens for every live slot in one scan,
  the target verifies them in one ragged dispatch, and each row
  accepts its own variable-length extension — greedy output stays
  token-identical, decode dispatches per token drop by the acceptance
  rate (``SpeculationPolicy``). Pass ``mesh=`` (a model-axis device
  mesh) for TENSOR-PARALLEL serving: params Megatron-shard, every KV
  pool shards its heads dimension, and each compiled program runs as
  one SPMD dispatch with jit-inserted collectives — token-identical
  to the unsharded engine, jit gauge still flat.
- ``PrefixCache`` (``prefix_cache``): the host-side radix-trie index
  over token-id prefixes mapping to retained KV pool rows — a new
  request whose prompt shares a cached prefix skips prefill for the
  shared head (O(novel-suffix) TTFT); finished slots donate their KV
  back under an LRU/ref-count policy within a configurable byte
  budget.
- ``AdmissionQueue`` / ``PrefillPolicy`` (``scheduler``): bounded
  admission with backpressure, deadline/cancellation sweeps,
  QoS-ordered pop — (priority class, deadline slack, prefix-affinity
  score) under a per-class bounded bypass window — plus the
  prefill-vs-decode token budget and the per-tenant ``TokenBucket``
  rate limiter. Under overload the engine PREEMPTS lower-class slots
  (KV donated to the prefix pool, automatic token-identical resume),
  SHEDS lowest-class admissions on SLO burn (``RequestShed``), and
  throttles over-budget tenants (``RequestRateLimited``) — see
  ``stats()["qos"]`` and ``engine(chaos=ChaosInjector())`` for drills.
- ``RequestHandle`` (``streams``): per-request streaming token
  iterator + blocking ``result()``; greedy output is token-identical
  to a lone ``model.generate`` call (tested).
- ``run_poisson_comparison`` (``benchmark``): the Poisson-arrival
  engine-vs-``GenerationService`` comparison behind
  ``bench.py --serving``.

Quick start::

    from bigdl_tpu.serving import ContinuousBatchingEngine

    with ContinuousBatchingEngine(model, max_slots=8,
                                  eos_id=eos) as engine:
        h = engine.submit(prompt_ids, max_new_tokens=128)
        for tok in h.tokens():      # streams as the loop decodes
            ...
        row = h.result()            # prompt + generated

Telemetry lands in the observability registry under
``bigdl_serving_*{service=...}`` (TTFT and inter-token histograms,
slot-occupancy gauge, admitted/evicted/timed-out counters, loop spans),
and every lifecycle transition lands in the flight recorder under the
handle's ``request_id`` (``handle.timeline()`` breakdowns,
``engine.debug_requests()`` / ``/debug/*`` endpoints, Chrome trace
export, and a crash postmortem from ``engine.healthz()``'s failing
loop — see ``bigdl_tpu.observability``). Usage is BILLED per request
under ``submit(..., tenant=...)``: the engine's ``UsageLedger``
attributes queue wait, prefilled vs prefix-reused tokens, delivered
tokens, KV byte-seconds held, and pro-rata dispatch device-seconds to
each tenant (``handle.usage()``, ``stats()["usage"]``,
``GET /debug/usage``, ``bigdl_serving_tenant_*`` counters).
"""

from bigdl_tpu.serving.chaos import ChaosFault, ChaosInjector
from bigdl_tpu.serving.engine import ContinuousBatchingEngine
from bigdl_tpu.serving.paging import (
    SCRATCH_PAGE, BlockTable, PagedPrefixIndex, PagePool,
)
from bigdl_tpu.serving.prefix_cache import PrefixCache, PrefixEntry
from bigdl_tpu.serving.scheduler import (
    AdmissionQueue, PrefillPolicy, SpeculationPolicy, TokenBucket,
    page_fit_score, pages_needed,
)
from bigdl_tpu.serving.streams import (
    PRIORITIES, EngineDraining, EngineStopped, QueueFull,
    RequestCancelled, RequestError, RequestHandle,
    RequestRateLimited, RequestShed, RequestTimedOut,
)
from bigdl_tpu.serving.benchmark import (
    mixed_length_workload, poisson_workload, quantized_quality_report,
    repeated_text_workload, run_paged_comparison,
    run_poisson_comparison, run_qos_storm, run_quantized_comparison,
    run_shared_prefix_comparison, run_speculative_comparison,
    run_tp_comparison, run_working_set_sweep, shared_prefix_workload,
)

__all__ = [
    "ContinuousBatchingEngine",
    "ChaosInjector", "ChaosFault",
    "PrefixCache", "PrefixEntry",
    "PagePool", "BlockTable", "PagedPrefixIndex", "SCRATCH_PAGE",
    "AdmissionQueue", "PrefillPolicy", "SpeculationPolicy",
    "TokenBucket", "pages_needed", "page_fit_score",
]

__all__ += [
    "RequestHandle", "RequestError", "RequestCancelled",
    "RequestTimedOut", "RequestShed", "RequestRateLimited",
    "QueueFull", "EngineStopped", "EngineDraining", "PRIORITIES",
    "poisson_workload", "run_poisson_comparison",
    "shared_prefix_workload", "run_shared_prefix_comparison",
    "repeated_text_workload", "run_speculative_comparison",
    "run_tp_comparison", "run_working_set_sweep",
    "quantized_quality_report", "run_quantized_comparison",
    "run_qos_storm",
    "mixed_length_workload", "run_paged_comparison",
]
