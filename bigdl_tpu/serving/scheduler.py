"""Admission control for the continuous-batching engine.

Two policies live here, deliberately separate from the device loop:

- ``AdmissionQueue`` — a bounded FCFS queue with BACKPRESSURE
  (``put`` blocks or raises ``QueueFull`` when the bound is hit, so an
  overloaded engine pushes back instead of buffering unboundedly) plus
  deadline/cancellation sweeps: expired or cancelled requests are
  dropped from the queue without ever costing a prefill.
- ``PrefillPolicy`` — the prefill-vs-decode interleave: how many
  prompt tokens each loop iteration may spend on admission before the
  shared decode step runs. Chunked prefill under a per-iteration token
  budget means admitting a 10k-token prompt never stalls the decode of
  already-running requests for more than one chunk's worth of work.

The reference's serving story (optim/PredictionService.scala) bounds
concurrency with an instance queue; this is the generative analog where
the bounded resource is KV-cache slots, not model clones.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from bigdl_tpu.serving.streams import (
    QueueFull, RequestCancelled, RequestHandle, RequestTimedOut,
)


class AdmissionQueue:
    """Bounded FCFS admission queue with backpressure.

    Thread contract: any thread may ``put``; only the engine loop calls
    ``pop_ready`` / ``sweep``. Dropped handles (cancelled or past their
    deadline while queued) are returned to the caller as
    ``(handle, error)`` pairs — the ENGINE finishes them, so all
    terminal bookkeeping (metrics, stream sentinels) stays in one
    place."""

    def __init__(self, capacity: int = 64, recorder=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._q: "deque[RequestHandle]" = deque()
        self._lock = threading.Condition()
        # queue transitions land in the flight recorder (request/queued
        # on put, request/queue_dropped for sweep/pop casualties) so a
        # request's timeline starts before it ever reaches a slot
        if recorder is None:
            from bigdl_tpu.observability.events import default_recorder
            recorder = default_recorder()
        self._rec = recorder

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def snapshot(self) -> List[RequestHandle]:
        """The queued handles, FCFS order (a copy — ``/debug/requests``
        reads it without racing the loop thread's pops)."""
        with self._lock:
            return list(self._q)

    def put(self, handle: RequestHandle, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Enqueue FCFS. When full: raise ``QueueFull`` immediately
        (``block=False``), or wait up to ``timeout`` (None = forever)
        for space — the backpressure path."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._lock:
            while len(self._q) >= self.capacity:
                if not block:
                    raise QueueFull(
                        f"admission queue full ({self.capacity} queued); "
                        "retry later or raise queue_capacity")
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise QueueFull(
                        f"admission queue still full ({self.capacity} "
                        f"queued) after {timeout}s")
                if not self._lock.wait(timeout=remaining):
                    raise QueueFull(
                        f"admission queue still full ({self.capacity} "
                        f"queued) after {timeout}s")
            self._q.append(handle)
            # recorded while still holding the queue lock: pop_ready
            # takes the same lock, so the loop thread cannot record
            # request/admitted before request/queued exists (the
            # recorder has its own independent lock — no ordering
            # between the two is ever taken in reverse)
            self._rec.record("request/queued", handle.request_id,
                             depth=len(self._q))
            self._lock.notify_all()

    def pop_ready(self, now: Optional[float] = None
                  ) -> Tuple[Optional[RequestHandle],
                             List[Tuple[RequestHandle, Exception]]]:
        """Pop the first LIVE handle (FCFS), skipping over — and
        returning — any cancelled/expired ones encountered on the way.
        Returns ``(handle_or_None, dropped)``."""
        now = time.monotonic() if now is None else now
        dropped: List[Tuple[RequestHandle, Exception]] = []
        with self._lock:
            while self._q:
                h = self._q.popleft()
                err = self._terminal(h, now)
                if err is None:
                    self._lock.notify_all()
                    return h, dropped
                dropped.append((h, err))
            self._lock.notify_all()
            return None, dropped

    def sweep(self, now: Optional[float] = None
              ) -> List[Tuple[RequestHandle, Exception]]:
        """Drop every cancelled/expired handle anywhere in the queue
        (not just the head) — a deep queue must not let a mid-queue
        deadline rot until it reaches the front."""
        now = time.monotonic() if now is None else now
        dropped: List[Tuple[RequestHandle, Exception]] = []
        with self._lock:
            keep: "deque[RequestHandle]" = deque()
            for h in self._q:
                err = self._terminal(h, now)
                (keep.append(h) if err is None
                 else dropped.append((h, err)))
            self._q = keep
            if dropped:
                self._lock.notify_all()
        return dropped

    def drain(self) -> List[RequestHandle]:
        """Remove and return everything (engine shutdown)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            self._lock.notify_all()
            return out

    def _terminal(self, h: RequestHandle, now: float
                  ) -> Optional[Exception]:
        err: Optional[Exception] = None
        if h.cancelled:
            err = RequestCancelled("cancelled while queued")
        elif h.deadline is not None and now > h.deadline:
            waited = now - h.submitted_at
            err = RequestTimedOut(
                f"deadline passed after {waited:.3f}s in the admission "
                "queue (never admitted to a slot)")
        if err is not None:
            self._rec.record("request/queue_dropped", h.request_id,
                             reason=type(err).__name__)
        return err


class PrefillPolicy:
    """The prefill-vs-decode interleave: each loop iteration may spend
    at most ``budget_tokens`` prompt tokens on chunked prefill before
    the shared decode step runs. ``chunk`` is the compiled prefill
    chunk length (ONE program serves every offset — pos0 is traced), so
    the budget is consumed ``chunk`` tokens at a time.

    Defaults: ``budget_tokens = 2 * chunk`` — admission makes steady
    progress (a C-token prompt admits in one iteration) while a running
    decode never waits more than two chunks' worth of prefill."""

    def __init__(self, chunk: int = 16,
                 budget_tokens: Optional[int] = None):
        if chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        self.chunk = chunk
        self.budget_tokens = (2 * chunk if budget_tokens is None
                              else budget_tokens)
        if self.budget_tokens < chunk:
            raise ValueError(
                f"budget_tokens ({self.budget_tokens}) must cover at "
                f"least one chunk ({chunk}) or admission never advances")
        self._left = 0

    def begin_iteration(self) -> None:
        self._left = self.budget_tokens

    def take_chunk(self) -> bool:
        """Spend one chunk of this iteration's budget; False once the
        iteration's prefill allowance is exhausted."""
        if self._left < self.chunk:
            return False
        self._left -= self.chunk
        return True

    def n_chunks(self, prompt_len: int) -> int:
        """Chunks a prompt of this length needs (last chunk padded)."""
        return -(-prompt_len // self.chunk)
