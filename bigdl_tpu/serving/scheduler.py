"""Admission control for the continuous-batching engine.

Two policies live here, deliberately separate from the device loop:

- ``AdmissionQueue`` — a bounded FCFS queue with BACKPRESSURE
  (``put`` blocks or raises ``QueueFull`` when the bound is hit, so an
  overloaded engine pushes back instead of buffering unboundedly) plus
  deadline/cancellation sweeps: expired or cancelled requests are
  dropped from the queue without ever costing a prefill. ``pop_ready``
  reorders within a bounded window by ``(priority class, deadline
  slack, -prefix score)`` — high-class and deadline-tight requests
  admit first, the caller-supplied scorer (the engine scores by
  cached-prefix length) breaks ties, and a per-class forced-FCFS
  starvation bound keeps even best-effort traffic finite-wait.
  ``requeue`` re-heads a preempted handle past the capacity bound.
- ``TokenBucket`` — per-tenant post-paid device-second rate limiting:
  admit while positive, debit the UsageLedger's measured cost at
  finalize, refuse with an exact ``retry_after()`` once negative.
- ``PrefillPolicy`` — the prefill-vs-decode interleave: how many
  prompt tokens each loop iteration may spend on admission before the
  shared decode step runs (``budget_tokens``), and how many admissions
  prefill TOGETHER through one ragged dispatch (``prefill_rows``).
  Chunked prefill under a per-iteration token budget means admitting a
  10k-token prompt never stalls the decode of already-running requests
  for more than one round's worth of work.
- ``SpeculationPolicy`` — the draft-propose/target-verify decode
  config (``engine(draft=..., spec_gamma=...)``): how many tokens the
  draft model proposes per fused decode round (``gamma``), and the
  derived shapes the engine's compiled programs depend on (the
  ``gamma + 1``-wide verify chunk, the extra KV positions every pool
  row must carry for rejected-proposal scratch writes).

The reference's serving story (optim/PredictionService.scala) bounds
concurrency with an instance queue; this is the generative analog where
the bounded resource is KV-cache slots, not model clones.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from bigdl_tpu.serving.streams import (
    PRIORITIES, PRIORITY_RANK, QueueFull, RequestCancelled,
    RequestHandle, RequestTimedOut,
)


def _rank(h: RequestHandle) -> int:
    return PRIORITY_RANK.get(getattr(h, "priority", "normal"), 1)


class AdmissionQueue:
    """Bounded FCFS admission queue with backpressure.

    Thread contract: any thread may ``put``; only the engine loop calls
    ``pop_ready`` / ``sweep``. Dropped handles (cancelled or past their
    deadline while queued) are returned to the caller as
    ``(handle, error)`` pairs — the ENGINE finishes them, so all
    terminal bookkeeping (metrics, stream sentinels) stays in one
    place."""

    def __init__(self, capacity: int = 64, recorder=None,
                 wait_histogram=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._q: "deque[RequestHandle]" = deque()
        self._lock = threading.Condition()
        #: optional histogram child observing each popped handle's
        #: submit→admission wait (the engine binds
        #: bigdl_serving_queue_wait_seconds — the queue-wait series the
        #: SLO watchdog burns against)
        self._wait_hist = wait_histogram
        # queue transitions land in the flight recorder (request/queued
        # on put, request/queue_dropped for sweep/pop casualties) so a
        # request's timeline starts before it ever reaches a slot
        if recorder is None:
            from bigdl_tpu.observability.events import default_recorder
            recorder = default_recorder()
        self._rec = recorder
        #: consecutive scorer-driven head bypasses (pop_ready fairness)
        self._head_bypasses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def snapshot(self) -> List[RequestHandle]:
        """The queued handles, FCFS order (a copy — ``/debug/requests``
        reads it without racing the loop thread's pops)."""
        with self._lock:
            return list(self._q)

    def put(self, handle: RequestHandle, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Enqueue FCFS. When full: raise ``QueueFull`` immediately
        (``block=False``), or wait up to ``timeout`` (None = forever)
        for space — the backpressure path.

        A handle with its own request deadline never out-sleeps it: the
        wait is bounded by the deadline too, and a request whose
        deadline expired while it was blocked here is rejected with
        ``RequestTimedOut`` at wake-up — admitting it would hand a slot
        (and a prefill) to a request that can only ever time out."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._lock:
            while len(self._q) >= self.capacity:
                now = time.monotonic()
                if handle.deadline is not None and now > handle.deadline:
                    self._rec.record("request/queue_dropped",
                                     handle.request_id,
                                     reason="RequestTimedOut",
                                     tenant=getattr(handle, "tenant",
                                                    None))
                    raise RequestTimedOut(
                        f"deadline passed after "
                        f"{now - handle.submitted_at:.3f}s blocked on a "
                        f"full admission queue ({self.capacity} queued) "
                        "— rejected instead of admitted with a dead "
                        "deadline")
                if not block:
                    raise QueueFull(
                        f"admission queue full ({self.capacity} queued); "
                        "retry later or raise queue_capacity")
                remaining = None if deadline is None else deadline - now
                if remaining is not None and remaining <= 0:
                    raise QueueFull(
                        f"admission queue still full ({self.capacity} "
                        f"queued) after {timeout}s")
                if handle.deadline is not None:
                    dl_left = handle.deadline - now
                    remaining = (dl_left if remaining is None
                                 else min(remaining, dl_left))
                if (not self._lock.wait(timeout=remaining)
                        and handle.deadline is None):
                    raise QueueFull(
                        f"admission queue still full ({self.capacity} "
                        f"queued) after {timeout}s")
            self._q.append(handle)
            # recorded while still holding the queue lock: pop_ready
            # takes the same lock, so the loop thread cannot record
            # request/admitted before request/queued exists (the
            # recorder has its own independent lock — no ordering
            # between the two is ever taken in reverse)
            self._rec.record("request/queued", handle.request_id,
                             depth=len(self._q),
                             tenant=getattr(handle, "tenant", None))
            self._lock.notify_all()

    def pop_ready(self, now: Optional[float] = None, scorer=None,
                  window: int = 1
                  ) -> Tuple[Optional[RequestHandle],
                             List[Tuple[RequestHandle, Exception]]]:
        """Pop the next LIVE handle, skipping over — and returning —
        any cancelled/expired ones encountered on the way. Returns
        ``(handle_or_None, dropped)``.

        QoS ordering: with ``window > 1`` the pop considers the first
        ``window`` live handles and takes the best by the composite
        key ``(priority class, deadline slack, -score)`` — high class
        beats tight deadline beats cached-prefix length (``scorer``:
        handle → number, e.g. the cached-prefix length of the handle's
        prompt). Ties keep strict FCFS — the key only ever REORDERS
        within the window on a strict improvement, so all-default
        traffic (same class, no deadlines, no scorer) stays exactly
        FCFS and admission stays work-conserving.

        Starvation is bounded PER CLASS: after ``window`` consecutive
        pops bypass a high/normal queue head — or ``2 * window`` for a
        low-class head — the next pop is forced FCFS, so even a
        best-effort request under a priority storm waits at most a
        bounded number of extra admissions, never forever.

        The scorer MAY carry side effects: the engine's prefix scorer
        starts the async host→device promotion the moment a candidate's
        trie walk lands on a host-tier row, so the transfer overlaps
        the rest of the candidate's QUEUE WAIT (``pop_ready`` calls the
        scorer once per live windowed candidate per pop — candidates
        put back at the head keep their in-flight transfer and are
        re-scored, not re-started, on the next pop). By the admission
        that finally consumes the entry, the copy has usually landed
        and the reuse path proceeds exactly as a device-tier hit."""
        now = time.monotonic() if now is None else now
        dropped: List[Tuple[RequestHandle, Exception]] = []
        with self._lock:
            if window <= 1:
                # plain FCFS fast path: O(1) popleft per live pop —
                # a deep queue must not pay a full rebuild per
                # admission when nothing reorders
                while self._q:
                    h = self._q.popleft()
                    err = self._terminal(h, now)
                    if err is None:
                        self._head_bypasses = 0
                        if self._wait_hist is not None:
                            self._wait_hist.observe(
                                max(0.0, now - h.submitted_at))
                        self._lock.notify_all()
                        return h, dropped
                    dropped.append((h, err))
                self._lock.notify_all()
                return None, dropped
            # scored path: materialize only the first `window` live
            # candidates off the head; the tail never moves
            live: List[RequestHandle] = []
            while self._q and len(live) < window:
                h = self._q.popleft()
                err = self._terminal(h, now)
                (live.append(h) if err is None
                 else dropped.append((h, err)))
            if not live:
                self._lock.notify_all()
                return None, dropped
            pick = live[0]
            # the head's class sets its own starvation tolerance: a
            # low-class head may be bypassed twice as long before the
            # forced-FCFS pop, but the bound stays finite — low never
            # starves completely, it just yields longer under load
            budget = window * (2 if _rank(live[0]) >= 2 else 1)
            if len(live) > 1 and self._head_bypasses < budget:
                # one scorer call per candidate (each is a trie walk)
                keys = [(_rank(h),
                         (h.deadline - now) if h.deadline is not None
                         else float("inf"),
                         -(scorer(h) if scorer is not None else 0))
                        for h in live]
                best = min(range(len(live)), key=keys.__getitem__)
                if keys[best] < keys[0]:
                    pick = live[best]
            self._head_bypasses = (self._head_bypasses + 1
                                   if pick is not live[0] else 0)
            for h in reversed(live):
                if h is not pick:
                    self._q.appendleft(h)
            if self._wait_hist is not None:
                self._wait_hist.observe(
                    max(0.0, now - pick.submitted_at))
            self._lock.notify_all()
            return pick, dropped

    def requeue(self, handle: RequestHandle) -> None:
        """Put a PREEMPTED handle back at the queue head, bypassing
        the capacity bound — the handle already held a slot, and
        re-admission must not deadlock behind the very backlog that
        caused the preemption. Bounded in practice by the engine's
        slot count (at most one preemption per occupied slot).
        Priority ordering still applies on the next pop: a requeued
        best-effort victim yields to the high-class request whose
        wait triggered the preemption."""
        with self._lock:
            self._q.appendleft(handle)
            self._rec.record("request/requeued", handle.request_id,
                             depth=len(self._q),
                             preempted=getattr(handle, "preempted", 0),
                             tenant=getattr(handle, "tenant", None))
            self._lock.notify_all()

    def oldest_waiting(self, priority: str,
                       now: Optional[float] = None) -> Optional[float]:
        """Longest current submit→now wait (seconds) among live queued
        handles of the given priority class, or None when none are
        queued — the engine's preemption trigger reads the high-class
        figure every iteration."""
        now = time.monotonic() if now is None else now
        with self._lock:
            waits = [now - h.submitted_at for h in self._q
                     if getattr(h, "priority", "normal") == priority
                     and not h.cancelled]
        return max(waits) if waits else None

    def depth_by_class(self) -> dict:
        """Queued handle count per priority class (``stats()["qos"]``
        composition figure)."""
        with self._lock:
            out = {p: 0 for p in PRIORITIES}
            for h in self._q:
                p = getattr(h, "priority", "normal")
                out[p] = out.get(p, 0) + 1
            return out

    def sweep(self, now: Optional[float] = None
              ) -> List[Tuple[RequestHandle, Exception]]:
        """Drop every cancelled/expired handle anywhere in the queue
        (not just the head) — a deep queue must not let a mid-queue
        deadline rot until it reaches the front."""
        now = time.monotonic() if now is None else now
        dropped: List[Tuple[RequestHandle, Exception]] = []
        with self._lock:
            keep: "deque[RequestHandle]" = deque()
            for h in self._q:
                err = self._terminal(h, now)
                (keep.append(h) if err is None
                 else dropped.append((h, err)))
            self._q = keep
            if dropped:
                self._lock.notify_all()
        return dropped

    def drain(self) -> List[RequestHandle]:
        """Remove and return everything (engine shutdown)."""
        with self._lock:
            out = list(self._q)
            self._q.clear()
            self._lock.notify_all()
            return out

    def _terminal(self, h: RequestHandle, now: float
                  ) -> Optional[Exception]:
        err: Optional[Exception] = None
        if h.cancelled:
            err = RequestCancelled("cancelled while queued")
        elif h.deadline is not None and now > h.deadline:
            waited = now - h.submitted_at
            err = RequestTimedOut(
                f"deadline passed after {waited:.3f}s in the admission "
                "queue (never admitted to a slot)")
        if err is not None:
            self._rec.record("request/queue_dropped", h.request_id,
                             reason=type(err).__name__,
                             tenant=getattr(h, "tenant", None))
        return err


class TokenBucket:
    """Per-tenant device-second token bucket (POST-PAID): a request is
    admitted while the balance is positive and its measured
    device-seconds are debited at finalize — the balance may go
    negative (the in-flight request could not know its cost up
    front), at which point further admissions are refused until the
    refill brings it back above zero. ``retry_after()`` is therefore
    the exact refill time to a positive balance — the honest
    ``Retry-After`` figure the front door forwards.

    Post-paid was chosen over pre-paid reservation because a
    generation request's device cost is unknowable at submit (early
    eos, speculative acceptance, preemption all change it) and the
    UsageLedger already meters the true figure — the bucket just
    consumes ``UsageRecord.device_s`` at the same finalize point.

    Thread-safe; monotonic-clock based; rate and burst are in
    device-seconds (per wall second / absolute)."""

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0:
            raise ValueError(
                f"rate_per_s must be > 0, got {rate_per_s}")
        if burst <= 0:
            raise ValueError(f"burst must be > 0, got {burst}")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._level = float(burst)
        self._last: Optional[float] = None
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        if self._last is not None and now > self._last:
            self._level = min(self.burst,
                              self._level + (now - self._last)
                              * self.rate)
        self._last = now

    def try_admit(self, now: Optional[float] = None) -> bool:
        """True while the balance is positive (admit); no tokens are
        taken here — the debit lands at finalize with the measured
        cost."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._refill(now)
            return self._level > 0.0

    def retry_after(self, now: Optional[float] = None) -> float:
        """Seconds until the balance refills back above zero (0.0
        when already positive)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._refill(now)
            if self._level > 0.0:
                return 0.0
            return (-self._level) / self.rate + 1e-9

    def debit(self, amount: float,
              now: Optional[float] = None) -> None:
        """Consume ``amount`` device-seconds (finalize-time, measured
        — may push the balance negative)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._refill(now)
            self._level -= float(amount)

    def level(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._refill(now)
            return self._level

    def snapshot(self, now: Optional[float] = None) -> dict:
        # graftlint: ok[lock-discipline] — rate/burst are immutable after __init__
        rate, burst = self.rate, self.burst
        return {"rate_device_s_per_s": rate,
                "burst_device_s": burst,
                "level_device_s": round(self.level(now), 9)}


class PrefillPolicy:
    """The prefill-vs-decode interleave: each loop iteration may spend
    at most ``budget_tokens`` prompt tokens (per staged row) on chunked
    prefill before the shared decode step runs. ``chunk`` is the
    compiled prefill chunk length (ONE program serves every offset —
    pos0 is traced), so the budget is consumed ``chunk`` tokens at a
    time — one *round* per take.

    ``prefill_rows`` is the second lever: the width of the engine's
    staging cache. Each prefill round advances up to ``prefill_rows``
    queued admissions by one chunk THROUGH ONE ragged dispatch (each
    row at its own offset), instead of one admission at a time — under
    a burst of arrivals, admission cost per request amortizes across
    the batch while the decode stall per iteration stays bounded by
    the same per-row token budget.

    Defaults: ``budget_tokens = 2 * chunk``, ``prefill_rows = 1`` —
    admission makes steady progress (a C-token prompt admits in one
    iteration) while a running decode never waits more than two
    rounds' worth of prefill."""

    def __init__(self, chunk: int = 16,
                 budget_tokens: Optional[int] = None,
                 prefill_rows: int = 1):
        if chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        if prefill_rows < 1:
            raise ValueError(
                f"prefill_rows must be >= 1, got {prefill_rows}")
        self.chunk = chunk
        self.prefill_rows = prefill_rows
        self.budget_tokens = (2 * chunk if budget_tokens is None
                              else budget_tokens)
        if self.budget_tokens < chunk:
            raise ValueError(
                f"budget_tokens ({self.budget_tokens}) must cover at "
                f"least one chunk ({chunk}) or admission never advances")
        self._left = 0

    def begin_iteration(self) -> None:
        self._left = self.budget_tokens

    def take_chunk(self) -> bool:
        """Spend one round (``chunk`` tokens per staged row) of this
        iteration's budget; False once the iteration's prefill
        allowance is exhausted."""
        if self._left < self.chunk:
            return False
        self._left -= self.chunk
        return True

    def n_chunks(self, prompt_len: int) -> int:
        """Chunks a prompt of this length needs (last chunk padded)."""
        return -(-prompt_len // self.chunk)


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages a ``tokens``-position KV span occupies (ceil division) —
    the paged engine's reservation unit for admission sizing, submit
    validation, and the admission scorer's fit check."""
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    return -(-int(tokens) // int(page_size))


def page_fit_score(reuse_tokens: int, fresh_pages: int,
                   available_pages: int) -> int:
    """Admission-window score for a paged engine: candidates are
    ranked by the prefill work their cached prefix skips (exactly the
    dense scorer's currency), but a candidate whose FRESH page need
    cannot currently be met — free pages plus everything the prefix
    LRU could reclaim — scores strictly below every admittable one
    (negative, by its shortfall), so the bounded bypass never elects a
    request the allocator would immediately bounce back to the queue
    head while an admittable neighbor waits behind it."""
    if fresh_pages > available_pages:
        return available_pages - fresh_pages
    return int(reuse_tokens)


class SpeculationPolicy:
    """Speculative-decoding config for the engine's fused decode loop:
    per round the DRAFT model proposes ``gamma`` tokens for every live
    slot in one ``lax.scan`` dispatch and the TARGET scores all of
    them in one ragged ``verify_chunk`` forward — each row then
    accepts a variable-length extension (1..gamma+1 tokens: the
    matched proposal prefix plus the target's correction/bonus token).

    Compiled-shape contract: every speculative program's shape depends
    only on ``(max_slots, gamma)`` — the verify chunk is always
    ``gamma + 1`` wide and the propose scan always ``gamma`` long, so
    acceptance raggedness is a HOST-side slice, never a recompile.

    ``kv_headroom`` is the extra KV positions every pool row must
    carry beyond the serving window: a verify round starting at the
    window's last decodable position still writes ``gamma`` scratch
    positions of (possibly rejected) proposal KV past it. Rejected
    scratch is overwritten by the next round before any query can
    attend it (the same position-mask argument as slot reuse)."""

    def __init__(self, gamma: int = 4):
        if gamma < 1:
            raise ValueError(
                f"spec_gamma must be >= 1 (one proposed token), "
                f"got {gamma}")
        self.gamma = gamma

    @property
    def verify_len(self) -> int:
        """Width of the ragged verify chunk: the pending token whose
        KV the round writes first, plus the ``gamma`` proposals."""
        return self.gamma + 1

    @property
    def kv_headroom(self) -> int:
        """Extra cache positions each KV row needs for the scratch
        writes of a verify round launched at the window edge."""
        return self.gamma
