"""Paged KV cache: one refcounted block-pool under every KV surface.

The dense engine gives every request a full ``(cache_len, ...)`` KV row
in each of its pools (slot KV, prefill staging, prefix pool, host tier,
draft mirrors) — so a 32-token chat bills the same HBM as a
document that fills ``cache_len``, and a prefix hit *copies* a pool row
into staging before the first novel token is prefetched. This module is
the fix, BigDL's block-manager discipline (Dai et al., 2018, arxiv
1804.05839) applied at page granularity: the unit of KV storage becomes
a fixed ``page_size``-token **page** of one persistent
``(max_pages, page_size, ...)`` device buffer per layer, and every KV
surface becomes host-side bookkeeping over page ids —

* ``PagePool`` — the allocator: a free list plus per-page reference
  counts over the device tree. Pages are claimed (``alloc``), shared
  (``share``: refcount bump, never a tensor copy — the zero-copy ethos
  of "RPC Considered Harmful", arxiv 1805.08430, applied intra-engine),
  and returned (``free``: a page is reusable only when its LAST
  reference drops).
* ``BlockTable`` — one request's view: the ordered page ids whose
  concatenation is its KV row. Token position ``i`` lives at offset
  ``i % page_size`` of page ``pages[i // page_size]``. ``fork`` shares
  every page copy-on-write; ``ensure_writable`` breaks a share with a
  single-page device copy only when a writer actually lands on a page
  someone else still references.
* ``PagedPrefixIndex`` — the prefix cache re-based on pages: the radix
  trie, LRU, pin, and generation machinery is inherited unchanged from
  ``PrefixCache``; what changes is the currency. A donation SHARES the
  donor slot's pages into the entry (no slot→pool copy), a hit SHARES
  the entry's aligned pages into the new request's table (no
  pool→staging copy), and eviction / host-tier demotion are refcount
  moves plus — for demotion only — one bulk device→host spill per page.

Why shared pages are never written (the COW invariant the engine
maintains): the engine requires ``prefill_chunk % page_size == 0``, so
the chunk-aligned reuse boundary ``base`` is page-aligned — a hit
shares exactly the pages covering ``[0, base)`` and the first novel
write lands at ``base``, i.e. at offset 0 of a freshly allocated page.
Decode and speculative-verify writes land at positions ``>= prompt_len
> base`` for the same reason. ``ensure_writable`` therefore never fires
on the engine's own paths; it exists (and is tested) as the safety net
for future writers — n>1 completion forks — that DO write under a
share.

Thread contract (mirrors ``PrefixCache``): the engine loop thread is
the only mutator; ``stats()`` readers may race in from HTTP/debug
threads, so counters and the free list sit behind an internal lock.
Lock order is strictly index → pool (``PagedPrefixIndex`` calls
``PagePool`` while holding its own lock; the pool never calls back), so
the two locks cannot deadlock.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.serving.prefix_cache import PrefixCache, PrefixEntry

__all__ = ["PagePool", "BlockTable", "PagedPrefixIndex", "SCRATCH_PAGE"]

#: page id 0 is never allocated: it is the write sink for idle dispatch
#: lanes (an all-zero block table routes their junk KV writes here) and
#: the padding value of every device block-table array, so a gather
#: through padding reads initialized — if garbage — memory that the
#: causal mask then discards.
SCRATCH_PAGE = 0


class PagePool:
    """Refcounted block allocator over one persistent device KV tree.

    ``buffers`` is ``model.init_cache(max_pages, page_size, ...)`` — a
    per-layer tuple of ``(k, v)`` (or quantized ``(k_q, v_q, k_scale,
    v_scale)``) arrays whose leading dim indexes pages; the pool never
    touches device memory itself, it only decides which page ids are
    live. The engine rebinds ``buffers`` after every donating dispatch
    (decode/prefill writes, COW copies) exactly as it rebinds its dense
    cache trees.

    Counters are cumulative and monotonic (the engine publishes them as
    the ``bigdl_serving_page_*_total`` instruments): ``allocated`` =
    pages handed out by ``alloc``, ``shared`` = reference bumps from
    ``share``, ``cow_forks`` = shares broken by ``ensure_writable``,
    ``freed`` = pages whose last reference dropped (so
    ``allocated - freed == pages_in_use`` at all times).
    """

    def __init__(self, buffers, page_size: int):
        import jax

        leaves = jax.tree_util.tree_leaves(buffers)
        if not leaves:
            raise ValueError("PagePool needs a non-empty buffer tree")
        max_pages = int(leaves[0].shape[0])
        if max_pages < 2:
            raise ValueError(
                f"max_pages must be >= 2 (page 0 is the reserved "
                f"scratch page), got {max_pages}")
        if page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {page_size}")
        self.buffers = buffers
        self.max_pages = max_pages
        self.page_size = int(page_size)
        #: device bytes one page owns across every layer's buffers
        #: (scale sidecars included) — the billing unit
        self.page_bytes = sum(int(l.nbytes) for l in leaves) // max_pages
        # LIFO free list: recently freed pages are re-issued first so a
        # churning workload keeps touching the same HBM region
        self._free: List[int] = list(range(max_pages - 1, 0, -1))
        self._refs = np.zeros(max_pages, np.int32)
        self._lock = threading.Lock()
        # cumulative flow
        self.allocated = 0
        self.shared = 0
        self.cow_forks = 0
        self.freed = 0

    # ------------------------------------------------------------ alloc
    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` fresh pages (refcount 1 each), all-or-nothing:
        ``None`` when fewer than ``n`` pages are free, so a caller
        never holds a partial reservation it must unwind."""
        if n < 0:
            raise ValueError(f"alloc(n={n})")
        with self._lock:
            if len(self._free) < n:
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            self.allocated += n
            return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one reference to each page — the whole of what a prefix
        hit or a table fork costs. Sharing a free page is a
        bookkeeping bug and fails loudly."""
        with self._lock:
            for p in pages:
                if self._refs[p] <= 0:
                    raise RuntimeError(
                        f"share() of free page {p}")
                self._refs[p] += 1
            self.shared += len(pages)

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference from each page; a page returns to the
        free list only when its last reference drops."""
        with self._lock:
            for p in pages:
                if self._refs[p] <= 0:
                    raise RuntimeError(
                        f"free() of free page {p}")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    self._free.append(p)
                    self.freed += 1

    def note_cow_fork(self) -> None:
        with self._lock:
            self.cow_forks += 1

    # ------------------------------------------------------------ views
    def refcount(self, page: int) -> int:
        with self._lock:
            return int(self._refs[page])

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return self.max_pages - 1 - len(self._free)

    @property
    def capacity_bytes(self) -> int:
        # graftlint: ok[lock-discipline] — max_pages and page_bytes are immutable after __init__
        return self.max_pages * self.page_bytes

    @property
    def bytes_in_use(self) -> int:
        # graftlint: ok[lock-discipline] — page_bytes is immutable after __init__ (pages_in_use takes the lock)
        return self.pages_in_use * self.page_bytes

    def holder_bytes(self, pages: Sequence[int]) -> float:
        """One holder's pro-rata device footprint: each held page's
        bytes divided by its CURRENT refcount, so a page shared by
        ``r`` holders bills ``1/r`` to each and the sum over all
        holders of a page is exactly its bytes — the conservation
        property the usage ledger's paged KV billing rests on."""
        with self._lock:
            total = 0.0
            for p in pages:
                r = int(self._refs[p])
                if r > 0:
                    total += self.page_bytes / r
            return total

    def stats(self) -> dict:
        with self._lock:
            in_use = self.max_pages - 1 - len(self._free)
            return {
                "max_pages": self.max_pages,
                "page_size": self.page_size,
                "page_bytes": self.page_bytes,
                "pages_in_use": in_use,
                "free_pages": len(self._free),
                "bytes_in_use": in_use * self.page_bytes,
                "capacity_bytes": self.max_pages * self.page_bytes,
                "allocated_total": self.allocated,
                "shared_total": self.shared,
                "cow_forks_total": self.cow_forks,
                "freed_total": self.freed,
            }


class BlockTable:
    """One request's ordered view of pool pages: position ``i`` lives
    at offset ``i % page_size`` of ``pages[i // page_size]``. The table
    owns one reference per listed page; ``free()`` (or the engine's
    release path) drops them all."""

    __slots__ = ("pool", "pages")

    def __init__(self, pool: PagePool, pages: List[int]):
        self.pool = pool
        self.pages = pages

    @classmethod
    def build(cls, pool: PagePool, shared: Sequence[int],
              n_fresh: int) -> Optional["BlockTable"]:
        """Assemble a table from a shared prefix head plus ``n_fresh``
        newly allocated pages, atomically: on allocation failure the
        shared references are never taken and ``None`` comes back, so
        the caller (the engine's admission path) can reclaim and
        retry without unwinding anything."""
        fresh = pool.alloc(n_fresh)
        if fresh is None:
            return None
        pool.share(shared)
        return cls(pool, list(shared) + fresh)

    def __len__(self) -> int:
        return len(self.pages)

    def fork(self) -> "BlockTable":
        """Copy-on-write clone: every page shared, nothing copied —
        the n>1-completions primitive."""
        self.pool.share(self.pages)
        return BlockTable(self.pool, list(self.pages))

    def ensure_writable(self, idx: int,
                        copy_page: Callable[[int, int], None]) -> bool:
        """Break the share on ``pages[idx]`` before a write: when the
        page's refcount is > 1, allocate a fresh page, have the caller
        copy the old page's device contents into it (``copy_page(dst,
        src)`` — one jitted single-page copy), and swap the table over
        to the private copy. Returns True when a COW copy happened.
        Raises when the pool is exhausted — the engine reserves a
        request's full span at admission precisely so this cannot
        trigger mid-flight."""
        page = self.pages[idx]
        if self.pool.refcount(page) <= 1:
            return False
        fresh = self.pool.alloc(1)
        if fresh is None:
            raise RuntimeError(
                "ensure_writable: pool exhausted mid-COW")
        copy_page(fresh[0], page)
        self.pool.free([page])
        self.pages[idx] = fresh[0]
        self.pool.note_cow_fork()
        return True

    def covering(self, n_tokens: int) -> Tuple[int, ...]:
        """The page ids holding positions ``[0, n_tokens)``."""
        ps = self.pool.page_size
        return tuple(self.pages[: -(-int(n_tokens) // ps)])

    def as_array(self, table_len: int) -> np.ndarray:
        """Fixed-shape device-dispatch form: the page ids padded to
        ``table_len`` with the scratch page, so compiled shapes depend
        only on the pool geometry, never on this request's length."""
        out = np.full(table_len, SCRATCH_PAGE, np.int32)
        out[: len(self.pages)] = self.pages
        return out

    def free(self) -> None:
        self.pool.free(self.pages)
        self.pages = []


class PagedPrefixIndex(PrefixCache):
    """``PrefixCache`` with pages as the currency instead of pool rows.

    The trie, lookup, LRU stamps, pin/unpin, ``pin_covering``, hit/miss
    accounting, and the ``generation`` stale-probe guard are inherited
    verbatim — prefix REUSE semantics are unchanged. What this subclass
    replaces is storage motion:

    * ``donate_pages(tokens, pages)`` — a finished/preempted slot's
      covering pages are SHARED into a new entry (refcount bump; the
      dense slot→pool row copy does not exist here).
    * a hit consumes ``entry.pages[: base // page_size]`` via
      ``PagePool.share`` (the engine does this; the dense pool→staging
      copy does not exist here).
    * ``reclaim(n_pages, spill)`` — eviction under allocation pressure:
      LRU unpinned entries drop their page references until the pool
      can satisfy the allocation. With a host budget and a ``spill``
      callback the victim DEMOTES instead: its pages are bulk-copied to
      pinned host buffers (one per page, outside the index lock) and
      the entry stays in the trie as a host-tier resident.
    * ``promote_pages(entry, pages)`` — the engine has allocated fresh
      pages and device_put the host buffers back; the entry flips to
      device tier. Promotion is synchronous at admission in paged mode
      (page copies are small and the async overlap machinery of the
      dense tier buys little), so the dense pending-demotion handshake
      (``pop_pending_demotion``/``complete_demotion``) is unused here.

    The dense row-allocation surface (``donate``, ``allocate_row``,
    ``promote``, ``release_row``) is disabled and fails loudly — a
    paged engine must never fall back to row motion.
    """

    def __init__(self, pool: PagePool, *, max_entries: int,
                 min_tokens: int = 1, token_bytes: float = 0.0,
                 devices: int = 1, host_pages: int = 0):
        if max_entries < 0:
            raise ValueError(
                f"max_entries must be >= 0, got {max_entries}")
        # rows=max_entries keeps the base class's "rows == 0 disables"
        # convention; row_bytes=0 because bytes are per-page here (the
        # byte properties and stats() are overridden below).
        super().__init__(rows=max_entries, row_bytes=0,
                         min_tokens=min_tokens, token_bytes=token_bytes,
                         devices=devices, host_rows=0)
        self.pool = pool
        #: host-tier budget in PAGES (0 disables the tier; eviction
        #: then drops instead of demoting)
        self.host_pages = int(host_pages)
        # the engine (and _sync_prefix_gauges) gates the host tier on
        # host_rows > 0; in page currency the page budget IS that gate
        self.host_rows = self.host_pages

    # ------------------------------------------------- dense API fences
    def donate(self, tokens: np.ndarray) -> Optional[int]:
        raise RuntimeError(
            "PagedPrefixIndex: use donate_pages(), not the dense "
            "row-copy donate()")

    def allocate_row(self) -> Optional[int]:
        raise RuntimeError(
            "PagedPrefixIndex: rows do not exist; allocate pages "
            "from the PagePool")

    # --------------------------------------------------------- donation
    def donate_pages(self, tokens: np.ndarray,
                     pages: Sequence[int]) -> bool:
        """Retain a finished request's prefix by sharing the ``pages``
        that hold its KV (position order; the caller keeps its own
        references — the slot's table is freed separately). Declined
        (False) when too short, already covered by an existing entry
        (LRU-touched instead), or the entry budget is exhausted by
        pinned entries."""
        tokens = np.array(tokens, np.int32, copy=True)
        # graftlint: ok[lock-discipline] — the pool reference is immutable after __init__; page_size is a pool constant
        n_pages = -(-tokens.shape[0] // self.pool.page_size)
        with self._lock:
            if (self.rows == 0 or tokens.shape[0] < self.min_tokens
                    or n_pages == 0):
                return False
            if n_pages > len(pages):
                raise ValueError(
                    f"donate_pages: {tokens.shape[0]} tokens need "
                    f"{n_pages} pages, got {len(pages)}")
            covered = self._covering_entry(tokens)
            if covered is not None:
                self._stamp += 1
                covered.last_used = self._stamp
                return False
            if len(self._entries) >= self.rows:
                victim = self._lru_unpinned()
                if victim is None:
                    return False
                self._drop_device_entry(victim)
            held = tuple(pages[:n_pages])
            # index -> pool lock order (see module docstring): the pool
            # never calls back into the index, so this nesting is safe
            self.pool.share(held)
            self._stamp += 1
            self.generation += 1
            entry = PrefixEntry(tokens, -1, self._stamp)
            entry.pages = held
            self._insert(entry)
            self._entries.append(entry)
            self.donations += 1
            return True

    def _drop_device_entry(self, entry: PrefixEntry) -> None:
        """Evict a device-tier entry outright (lock held): drop its
        page references and remove it from the trie."""
        self._entries.remove(entry)
        self._trie_remove(entry)
        self.pool.free(entry.pages)
        entry.pages = ()
        self.evictions += 1
        self.generation += 1

    # --------------------------------------------------------- pressure
    def reclaim(self, n_pages: int,
                spill: Optional[Callable[[Tuple[int, ...]], list]]
                = None) -> bool:
        """Free pool pages for an ``n_pages`` allocation by evicting
        LRU unpinned entries; True when the pool can now satisfy it.
        With ``spill`` and host budget, victims demote: ``spill(pages)``
        returns one pinned host buffer per page (run OUTSIDE the index
        lock — it dispatches device work), or None to abandon the
        demotion and drop the victim. Note an evicted entry only frees
        the pages nobody else references — shared pages survive under
        their other holders, so reclaim can legitimately run out of
        victims before the pool has ``n_pages`` free."""
        # graftlint: ok[lock-discipline] — the pool reference is immutable and the pool has its OWN lock; calling it under the index lock would nest the two
        while self.pool.free_pages < n_pages:
            with self._lock:
                victim = self._lru_unpinned()
                if victim is None:
                    return self.pool.free_pages >= n_pages
                demote = (spill is not None and self.host_pages > 0
                          and self._make_host_page_room(
                              len(victim.pages)))
                self._entries.remove(victim)
                self.evictions += 1
                self.generation += 1
                if demote:
                    victim.tier = "host"
                    victim.row = -1
                    victim.host_buf = None
                    self._host_entries.append(victim)
                else:
                    self._trie_remove(victim)
            held = victim.pages
            if demote:
                buf = spill(held)
                with self._lock:
                    if buf is None:
                        # spill failed: degrade to a plain drop
                        if victim in self._host_entries:
                            self._host_entries.remove(victim)
                            self._trie_remove(victim)
                            self.generation += 1
                    elif victim in self._host_entries:
                        victim.host_buf = buf
                        self.demotions += 1
            # graftlint: ok[lock-discipline] — the pool reference is immutable and the pool has its OWN lock; freeing outside the index lock avoids nesting the two
            self.pool.free(held)
            victim.pages = ()
        return True

    def _make_host_page_room(self, incoming: int) -> bool:
        """Ensure the host tier can absorb ``incoming`` more pages
        (lock held), evicting host-LRU ``refs == 0`` entries past the
        page budget; False when pinned entries block it (the demotion
        then degrades to a drop — never an over-budget spill)."""
        if incoming > self.host_pages:
            return False
        while (self._host_pages_in_use_locked() + incoming
               > self.host_pages):
            cand = [e for e in self._host_entries if e.refs == 0]
            if not cand:
                return False
            hv = min(cand, key=lambda e: e.last_used)
            self._host_entries.remove(hv)
            self._trie_remove(hv)
            hv.host_buf = None
            self.host_evictions += 1
            self.generation += 1
        return True

    def _host_pages_in_use_locked(self) -> int:
        return sum(len(e.host_buf) for e in self._host_entries
                   if e.host_buf is not None)

    # -------------------------------------------------------- promotion
    def promote_pages(self, entry: PrefixEntry,
                      pages: Sequence[int]) -> None:
        """Flip a host-tier entry back to device residency over freshly
        allocated ``pages`` (the caller has already device_put each
        host buffer into its page). Mirrors the base ``promote``
        contract: LRU touch, host buffer dropped, generation bump."""
        with self._lock:
            if entry.tier != "host" or entry not in self._host_entries:
                raise RuntimeError(
                    f"promote_pages() of a non-host entry: {entry!r}")
            self._host_entries.remove(entry)
            entry.tier = "device"
            entry.pages = tuple(pages)
            entry.host_buf = None
            self._entries.append(entry)
            self._stamp += 1
            entry.last_used = self._stamp
            self.promotions += 1
            self.generation += 1

    @property
    def device_pages(self) -> int:
        """Total pages referenced by device-tier entries — the upper
        bound on what a full ``reclaim`` sweep could return to the
        pool (shared pages survive under their other holders, so the
        true yield can be lower). Admission scoring input."""
        with self._lock:
            return sum(len(e.pages) for e in self._entries)

    def drop_all(self) -> None:
        """Release every retained entry's page references (engine
        stop/crash path — the leak check counts on this)."""
        with self._lock:
            for e in list(self._entries):
                self._drop_device_entry(e)
            for e in list(self._host_entries):
                self._host_entries.remove(e)
                self._trie_remove(e)
                e.host_buf = None
                self.generation += 1

    # ------------------------------------------------------------ bytes
    @property
    def bytes_in_use(self) -> int:
        """Pro-rata device bytes the retained entries hold (a page
        shared with live requests bills the index only its refcount
        share) — the honest `/debug/memory` attribution."""
        with self._lock:
            return int(sum(self.pool.holder_bytes(e.pages)
                           for e in self._entries))

    @property
    def capacity_bytes(self) -> int:
        # graftlint: ok[lock-discipline] — the pool reference is immutable after __init__
        return self.pool.capacity_bytes

    @property
    def host_capacity_bytes(self) -> int:
        # graftlint: ok[lock-discipline] — host_pages and the pool reference are immutable after __init__
        return self.host_pages * self.pool.page_bytes

    @property
    def host_bytes_in_use(self) -> int:
        with self._lock:
            return (self._host_pages_in_use_locked()
                    * self.pool.page_bytes)

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            looked = self.hits + self.misses
            dev_pages = sum(len(e.pages) for e in self._entries)
            host_pages = self._host_pages_in_use_locked()
            pro_rata = int(sum(self.pool.holder_bytes(e.pages)
                               for e in self._entries))
            return {
                "entries": len(self._entries),
                "rows": self.rows,
                "pages": dev_pages,
                "bytes": pro_rata,
                "capacity_bytes": self.pool.capacity_bytes,
                "devices": self.devices,
                "bytes_per_device": pro_rata // self.devices,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / looked, 4)
                            if looked else 0.0,
                "reused_tokens": self.reused_tokens,
                "bytes_saved": self.bytes_saved,
                "donations": self.donations,
                "evictions": self.evictions,
                # host tier (page units)
                "host_rows": self.host_pages,
                "host_entries": len(self._host_entries),
                "host_pages": host_pages,
                "host_bytes": host_pages * self.pool.page_bytes,
                "host_capacity_bytes": (self.host_pages
                                        * self.pool.page_bytes),
                "host_hits": self.host_hits,
                "device_hits": self.hits - self.host_hits,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "host_evictions": self.host_evictions,
            }

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"length": e.length, "pages": list(e.pages),
                     "tier": e.tier, "refs": e.refs, "hits": e.hits,
                     "last_used": e.last_used}
                    for e in sorted(self._entries + self._host_entries,
                                    key=lambda e: e.last_used)]
