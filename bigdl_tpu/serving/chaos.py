"""Deterministic fault injection for the serving engine.

Overload behavior (shedding, preemption, crash postmortems) is the
hardest serving surface to test: the triggering conditions — SLO burn
under a storm, a device fault mid-dispatch, a wedged slot — are
timing-dependent and slow to reproduce for real. ``ChaosInjector`` is
the scripted stand-in: the engine consults it at three fixed points
(``engine(chaos=...)``), and a test (or the ``serve.py --chaos``
overload drill) flips exactly the condition it wants, deterministically:

- ``force_burn(active, severe=...)`` — a synthetic TTFT SLO burn: the
  engine's load-shedding decision treats it exactly like an active
  SloWatchdog burn alert (``severe`` escalates the shed set from
  low-class to low+normal), without needing real latency violations.
- ``fail_dispatch(nth)`` — raise ``ChaosFault`` on the Nth device
  dispatch from now: exercises the loop-crash → postmortem →
  ``EngineStopped`` path on demand.
- ``freeze_slot(sid, iterations)`` — withhold one slot from the fused
  decode for N loop iterations: its request stalls mid-decode (the
  deadline sweep and the preemption victim scan still see it), the
  other slots keep streaming.

Everything is host-side and thread-safe; the injector never touches a
compiled program, so the jit gauge stays flat with chaos enabled. A
default-constructed injector injects nothing.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class ChaosFault(RuntimeError):
    """The scripted dispatch failure (``fail_dispatch``): raised from
    the engine loop thread at the chosen dispatch, crashing the loop
    through the same postmortem path a real device fault would."""


class ChaosInjector:
    """Scripted, deterministic fault injection (see module docstring).

    Control side (any thread): ``force_burn`` / ``fail_dispatch`` /
    ``freeze_slot``. Engine side (loop thread + submit path):
    ``burn_active`` / ``burn_severe`` / ``on_dispatch`` /
    ``begin_iteration`` / ``slot_frozen``. ``snapshot()`` renders the
    current script state for ``stats()["qos"]["chaos"]``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._burn = False
        self._burn_severe = False
        #: dispatches until the scripted fault (None = disarmed)
        self._fail_in: Optional[int] = None
        #: slot id -> remaining frozen iterations
        self._frozen: Dict[int, int] = {}
        self._dispatches = 0
        self._iterations = 0
        self._faults_raised = 0

    # ---------------------------------------------------- control side
    def force_burn(self, active: bool = True,
                   severe: bool = False) -> None:
        """Assert (or clear) a synthetic TTFT SLO burn. ``severe``
        models a burn past twice the alert threshold — the engine
        escalates shedding from low-class-only to low+normal."""
        with self._lock:
            self._burn = bool(active)
            self._burn_severe = bool(active) and bool(severe)

    def fail_dispatch(self, nth: int = 1) -> None:
        """Arm a ``ChaosFault`` on the ``nth`` device dispatch from
        now (1 = the very next one)."""
        if nth < 1:
            raise ValueError(f"nth must be >= 1, got {nth}")
        with self._lock:
            self._fail_in = int(nth)

    def freeze_slot(self, sid: int, iterations: int) -> None:
        """Withhold slot ``sid`` from the fused decode for the next
        ``iterations`` loop iterations."""
        if iterations < 1:
            raise ValueError(
                f"iterations must be >= 1, got {iterations}")
        with self._lock:
            self._frozen[int(sid)] = int(iterations)

    # ----------------------------------------------------- engine side
    def burn_active(self) -> bool:
        with self._lock:
            return self._burn

    def burn_severe(self) -> bool:
        with self._lock:
            return self._burn_severe

    def on_dispatch(self) -> None:
        """Engine loop hook, once per device dispatch: raises the
        scripted ``ChaosFault`` when armed and due."""
        with self._lock:
            self._dispatches += 1
            if self._fail_in is None:
                return
            self._fail_in -= 1
            if self._fail_in > 0:
                return
            self._fail_in = None
            self._faults_raised += 1
            n = self._dispatches
        raise ChaosFault(
            f"scripted dispatch failure injected at dispatch #{n} "
            "(chaos drill — not a real device fault)")

    def begin_iteration(self) -> None:
        """Engine loop hook, once per iteration: ages the slot
        freezes."""
        with self._lock:
            self._iterations += 1
            done = [sid for sid, left in self._frozen.items()
                    if left <= 0]
            for sid in done:
                del self._frozen[sid]

    def slot_frozen(self, sid: int) -> bool:
        """True while slot ``sid`` must sit out the decode (consumes
        one iteration of the freeze per call from the loop)."""
        with self._lock:
            left = self._frozen.get(int(sid))
            if left is None or left <= 0:
                return False
            self._frozen[int(sid)] = left - 1
            return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "burn": self._burn,
                "burn_severe": self._burn_severe,
                "fail_dispatch_in": self._fail_in,
                "frozen_slots": dict(self._frozen),
                "dispatches_seen": self._dispatches,
                "iterations_seen": self._iterations,
                "faults_raised": self._faults_raised,
            }

    def __repr__(self):
        s = self.snapshot()
        return (f"ChaosInjector(burn={s['burn']}, "
                f"fail_in={s['fail_dispatch_in']}, "
                f"frozen={sorted(s['frozen_slots'])})")
