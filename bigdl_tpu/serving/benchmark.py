"""Poisson-arrival serving benchmark: engine vs ``GenerationService``.

Replays ONE sampled open-loop workload (exponential inter-arrival gaps,
mixed prompt/decode lengths) against both serving paths and reports the
numbers a serving SLO is written in: per-request latency p50/p99, TTFT
p50/p99 (engine only — the batch service has no streaming), and
aggregate delivered tokens/sec. Engine rows also carry the usage
ledger's GOODPUT block (device-seconds by dispatch kind, padding-waste
mean, occupancy-weighted utilization, tokens per device-second) and a
per-tenant token / device-second breakdown — the workload submits
round-robin under three tenant names (one per template on the
shared-prefix variant) so attribution is exercised under load.
``bench.py --serving`` emits the result into ``bench_history.jsonl``
and the Prometheus snapshot so the serving perf trajectory is tracked
alongside the training headline.

``--serving --shared-prefix`` runs the PREFIX-HEAVY variant instead
(:func:`run_shared_prefix_comparison`): Poisson arrivals over N shared
prompt templates, replayed through the engine with its prefix cache
enabled vs disabled — the O(prompt) → O(novel-suffix) TTFT claim,
measured, with greedy token parity asserted between the two paths.

``--serving --speculative`` runs the SPECULATIVE A/B
(:func:`run_speculative_comparison`): one repeated-text Poisson
workload replayed through the engine with an int8-clone draft
proposing ``gamma`` tokens per fused round vs the plain one-token
decode — inter-token p50/p99 both ways, the draft acceptance rate,
and greedy token parity (a draft must never change the output, only
how many dispatches it costs).

``--serving --quantized`` runs the QUANTIZED A/B
(:func:`run_quantized_comparison`): one repeated-text Poisson workload
replayed through the engine with int8 KV pools + int8 weights vs full
precision — inter-token p50/p99 both ways, the cost model's
membw-utilization pair (decode is memory-bound, so halved bytes is
the claim), physical row bytes both ways, and the QUALITY gate: a
deterministic teacher-forced per-token logit-divergence report
(:func:`quantized_quality_report`) plus the speculative
acceptance-rate delta between fp-KV and int8-KV runs under the same
int8 draft.

``--serving --tp N`` runs the TENSOR-PARALLEL A/B
(:func:`run_tp_comparison`): the same Poisson workload replayed
through the engine sharded over an ``N``-way model-axis device mesh
(``engine(mesh=...)`` — Megatron param split, heads-sharded KV pools,
SPMD dispatches) vs the plain single-device engine — TTFT and
inter-token percentiles both ways, the sharded run's mesh/pool block,
and greedy token parity (a mesh changes where the math runs, never
the tokens). Hermetic on a CPU host-device mesh; the same call
measures real ICI scaling on hardware.

``--serving --qos`` runs the MIXED-PRIORITY STORM
(:func:`run_qos_storm`): one Poisson storm of interactive high-class
requests, normal-class traffic, long-decode low-class batch jobs, and
an over-budget ``greedy`` tenant, replayed into a deliberately
undersized engine with a hair-trigger TTFT SLO objective — so the
burn-rate shedder, the KV-donating preemption path, and the
per-tenant token bucket all fire on REAL machinery, not mocks — vs an
uncontended replay of only the high-class requests through the same
engine config. The headline is the high-class p99 TTFT ratio
storm/uncontended (the acceptance bar is <= 1.25x: the class buys
isolation), alongside the shed / preempted / rate-limited counts, the
outcome-conservation verdict (every submit ends in exactly one
terminal outcome — no silent drops), and the per-tenant ledger
breakdown.

``--serving --paged`` runs the PAGED-KV A/B
(:func:`run_paged_comparison`): one mixed short/long Poisson storm
replayed through the engine in paged mode (``page_size`` block pool,
per-request BlockTables) vs dense full-row slots, at an EQUAL device
KV byte budget — the paged pool holds exactly as many bytes as the
dense leg's slot rows, it just hands them out page-granular instead
of window-granular. The headline is the peak admitted-concurrency
ratio (paged must admit >= 3x the dense leg's concurrent requests
from the same bytes), alongside TTFT p50/p99 both ways (less
queueing behind full-window reservations), the paged leg's pool /
fragmentation block, and greedy token parity (paging moves bytes,
never tokens).

``scripts/perf_gate.py`` turns consecutive rows of any variant into a
CI regression gate.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


def poisson_workload(n_requests: int, rate_hz: float, vocab: int,
                     prompt_lens=(4, 16), decode_lens=(4, 24),
                     seed: int = 0,
                     tenants=("tenant-a", "tenant-b", "tenant-c")
                     ) -> List[dict]:
    """Sample an open-loop workload: each request gets an arrival OFFSET
    (cumulative exponential gaps at ``rate_hz``), a random prompt, a
    random decode length, and a round-robin ``tenant`` (the usage
    ledger's attribution key) — the same list replays against every
    serving path under comparison."""
    r = np.random.RandomState(seed)
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        t0 = int(r.randint(prompt_lens[0], prompt_lens[1] + 1))
        out.append({
            "arrival_s": float(at[i]),
            "prompt": r.randint(0, vocab, (t0,)).astype(np.int32),
            "n": int(r.randint(decode_lens[0], decode_lens[1] + 1)),
            "tenant": tenants[i % len(tenants)] if tenants else None,
        })
    return out


def _percentiles(xs) -> dict:
    if not xs:
        return {"p50": None, "p99": None}
    return {"p50": round(float(np.percentile(xs, 50)), 6),
            "p99": round(float(np.percentile(xs, 99)), 6)}


def _append_itl(itl: List[float], handle) -> None:
    """Record the request's mean inter-token gap (decode wall time over
    the decoded-token count) — the per-request figure whose p99 the
    perf gate tracks next to TTFT."""
    tl = handle.timeline()
    if tl["decode_s"] is not None and tl["tokens"] > 1:
        itl.append(tl["decode_s"] / (tl["tokens"] - 1))


def _usage_blocks(stats: dict) -> dict:
    """Compress ``engine.stats()["usage"]`` into the bench-row shape:
    the goodput block verbatim plus a per-tenant token /
    device-second breakdown (the columns a capacity planner reads)."""
    u = stats.get("usage") or {}
    tenants = {
        t: {"requests": a["requests"],
            "prefill_tokens": a["prefill_tokens"],
            "decode_tokens": a["decode_tokens"],
            "device_s": a["device_s"],
            "tokens_per_device_second": a["tokens_per_device_second"]}
        for t, a in (u.get("tenants") or {}).items()}
    return {"goodput": u.get("goodput"), "tenants": tenants}


def _engine_replay(model, workload, warm_prompt, warm_tokens,
                   stats_keys, log, label, after_warm=None,
                   **engine_kw) -> dict:
    """One ENGINE leg of an A/B comparison (the speculative,
    shared-prefix, and tensor-parallel variants all replay the same
    way): build the engine, warm every executable outside the
    measurement window, open-loop replay the workload, and return the
    standard result block — latency / TTFT / inter-token percentiles,
    delivered-token throughput, the usage/goodput blocks, alerts, the
    per-request output rows (keyed by ``id(req)``, for the caller's
    token-parity check), plus the ``engine.stats()`` entries named by
    ``stats_keys``. ``after_warm(engine)`` runs between the warm
    request and the replay — a probe point for baselines that must
    exclude warmup (e.g. the jit-compile gauge the tiered-cache sweep
    asserts flat across demote/promote traffic)."""
    from bigdl_tpu.serving import ContinuousBatchingEngine

    engine = ContinuousBatchingEngine(model, **engine_kw)
    ttft: List[float] = []
    itl: List[float] = []
    rows: dict = {}
    tlock = threading.Lock()

    def collect(handle, req):
        row = handle.result()
        with tlock:
            rows[id(req)] = row
            if handle.first_token_at is not None:
                ttft.append(handle.first_token_at - handle.submitted_at)
            _append_itl(itl, handle)
        return row.shape[0] - req["prompt"].shape[0]

    log(f"[serving-bench] {label} replay ({engine.service_name})...")
    with engine:
        engine.submit(warm_prompt, warm_tokens).result(timeout=300)
        if after_warm is not None:
            after_warm(engine)
        res = _replay(
            workload,
            lambda req: engine.submit(req["prompt"], req["n"],
                                      tenant=req.get("tenant")),
            collect)
        stats = engine.stats()
    res["ttft"] = _percentiles(ttft)
    res["inter_token"] = _percentiles(itl)
    for key in stats_keys:
        res[key] = stats[key]
    res.update(_usage_blocks(stats))
    res["cost"] = stats.get("cost")
    res["loop"] = stats.get("loop")
    res["alerts"] = stats["alerts"]
    res["rows"] = rows
    return res


def _replay(workload, submit_fn, collect_fn) -> dict:
    """Open-loop replay: a pacer thread submits each request at its
    arrival offset (late submissions go immediately — arrival times are
    an offered load, not a synchronization barrier); ``collect_fn``
    blocks per request and returns delivered token count."""
    lat: List[float] = []
    toks: List[int] = []
    errs: List[BaseException] = []
    lock = threading.Lock()
    t_start = time.monotonic()

    def one(req):
        try:
            t_sub = time.monotonic()
            pending = submit_fn(req)
            n_tok = collect_fn(pending, req)
            dt = time.monotonic() - t_sub
            with lock:
                lat.append(dt)
                toks.append(n_tok)
        except BaseException as e:
            with lock:
                errs.append(e)

    threads = []
    for req in workload:
        delay = t_start + req["arrival_s"] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=one, args=(req,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    if errs:
        raise errs[0]
    return {"latency": _percentiles(lat),
            "tokens_per_sec": round(sum(toks) / max(wall, 1e-9), 2),
            "wall_s": round(wall, 3), "requests": len(workload)}


def shared_prefix_workload(n_requests: int, rate_hz: float, vocab: int,
                           n_templates: int = 4, template_len: int = 96,
                           tail_lens=(4, 12), decode_lens=(4, 16),
                           seed: int = 0,
                           template_order: str = "random") -> List[dict]:
    """Sample a PREFIX-HEAVY open-loop workload: every prompt is one of
    ``n_templates`` shared heads (a system prompt / few-shot template)
    followed by a short random tail — the traffic shape the engine's
    prefix cache exists for. Same arrival/replay semantics as
    :func:`poisson_workload`. ``template_order="cycle"`` visits the
    templates round-robin instead of uniformly at random — the LRU
    worst case (every revisit is exactly ``n_templates`` requests
    away), which the working-set sweep uses to expose the device-only
    hit-rate cliff."""
    if template_order not in ("random", "cycle"):
        raise ValueError(
            f"template_order must be 'random' or 'cycle', "
            f"got {template_order!r}")
    r = np.random.RandomState(seed)
    templates = [r.randint(0, vocab, (template_len,)).astype(np.int32)
                 for _ in range(n_templates)]
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        ti = (i % n_templates if template_order == "cycle"
              else int(r.randint(0, n_templates)))
        tail = r.randint(0, vocab, (int(r.randint(
            tail_lens[0], tail_lens[1] + 1)),)).astype(np.int32)
        out.append({
            "arrival_s": float(at[i]),
            "prompt": np.concatenate([templates[ti], tail]),
            "n": int(r.randint(decode_lens[0], decode_lens[1] + 1)),
            # one tenant per template — the usage table then shows
            # which shared prompt is eating the device
            "tenant": f"tpl-{ti}",
        })
    return out


def repeated_text_workload(n_requests: int, rate_hz: float, vocab: int,
                           motif_len: int = 4, prompt_lens=(8, 16),
                           decode_lens=(8, 24), seed: int = 0,
                           tenants=("tenant-a", "tenant-b", "tenant-c")
                           ) -> List[dict]:
    """Sample a REPEATED-TEXT open-loop workload: each prompt tiles a
    short random motif (boilerplate, markup, table rows — the
    self-similar traffic a draft model predicts well), so speculative
    decoding gets a fair shot at a high acceptance rate while prompts
    stay distinct enough that the prefix cache is not the thing being
    measured. Same arrival/replay semantics as
    :func:`poisson_workload`."""
    r = np.random.RandomState(seed)
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        motif = r.randint(0, vocab, (motif_len,)).astype(np.int32)
        t0 = int(r.randint(prompt_lens[0], prompt_lens[1] + 1))
        reps = -(-t0 // motif_len)
        out.append({
            "arrival_s": float(at[i]),
            "prompt": np.tile(motif, reps)[:t0],
            "n": int(r.randint(decode_lens[0], decode_lens[1] + 1)),
            "tenant": tenants[i % len(tenants)] if tenants else None,
        })
    return out


def run_speculative_comparison(model, draft=None, n_requests: int = 24,
                               rate_hz: float = 30.0,
                               max_slots: int = 4,
                               prefill_chunk: int = 8,
                               prefill_rows: int = 2,
                               gamma: int = 4,
                               eos_id: Optional[int] = None,
                               seed: int = 0, registry=None,
                               log=None) -> dict:
    """Replay ONE repeated-text Poisson workload through the engine
    twice — speculative decoding ON (``draft`` proposing ``gamma``
    tokens per fused round; default: the int8-quantized clone of
    ``model``, PERF.md's draft construction) vs OFF, everything else
    identical — and report inter-token/TTFT/latency percentiles for
    both, the speculative run's acceptance rate, the inter-token
    p50/p99 speedups, and whether the two paths produced
    token-identical greedy outputs (they must: a draft changes dispatch
    count, never tokens). This is the decode-throughput claim of
    speculative serving, measured."""
    log = log or (lambda *a, **k: None)
    if draft is None:
        from bigdl_tpu.nn.quantized import Quantizer

        log("[serving-bench] quantizing the int8 draft clone...")
        draft = Quantizer.quantize(model)
        draft.evaluate()
    vocab = model.vocab_size
    window = (model.max_len // prefill_chunk) * prefill_chunk
    decode_hi = max(8, min(24, window // 2 - 16))
    wl = repeated_text_workload(
        n_requests, rate_hz, vocab,
        prompt_lens=(8, min(16, window - decode_hi - 1)),
        decode_lens=(min(8, decode_hi), decode_hi), seed=seed)
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(0, vocab, (12,)),
        np.int32)

    def run_path(name: str, **engine_kw) -> dict:
        return _engine_replay(
            model, wl, warm_prompt, 4,
            ("speculation", "jit_compiles"), log, "speculative",
            max_slots=max_slots, prefill_chunk=prefill_chunk,
            prefill_rows=prefill_rows, eos_id=eos_id,
            registry=registry, service_name=name, **engine_kw)

    spec = run_path("bench_spec_on", draft=draft, spec_gamma=gamma)
    nospec = run_path("bench_spec_off")
    parity = all(
        np.array_equal(spec["rows"][id(req)], nospec["rows"][id(req)])
        for req in wl)
    for r in (spec, nospec):
        del r["rows"]

    def ratio(key):
        a, b = nospec["inter_token"][key], spec["inter_token"][key]
        return round(a / b, 4) if a and b else None

    return {"spec": spec, "nospec": nospec,
            "inter_token_p50_speedup": ratio("p50"),
            "inter_token_p99_speedup": ratio("p99"),
            "acceptance_rate":
                spec["speculation"].get("acceptance_rate"),
            "token_parity": bool(parity),
            "workload": {"kind": "speculative",
                         "requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "prefill_rows": prefill_rows,
                         "gamma": gamma}}


def quantized_quality_report(model, prompts=None, horizon: int = 16,
                             kv_dtype: str = "int8",
                             weights_dtype: Optional[str] = "int8",
                             n_prompts: int = 6, prompt_len: int = 8,
                             seed: int = 0) -> dict:
    """Per-token numerics gate for quantized serving: roll the FLOAT
    model greedily for ``horizon`` tokens per prompt, then (a)
    teacher-force the quantized path (int8 KV cache via
    ``kv_dtype``, optionally the int8 ``Quantizer`` weight clone) down
    the SAME trajectory and measure per-token logit divergence, and
    (b) free-run the quantized path greedily and measure how long its
    output prefix agrees with the float rollout. Deterministic per
    (model, prompts, horizon) — :func:`quantize_kv` rounds the same
    floats to the same bytes every time — so the figures gate cleanly
    run-to-run in ``perf_gate.py``.

    Returns ``logit_div_max`` / ``logit_div_mean`` (absolute),
    ``logit_div_rel`` (max divergence over the float run's own max
    |logit| — the scale-free ceiling the gate reads), and
    ``greedy_match_fraction`` (mean common-prefix length / horizon)."""
    import jax.numpy as jnp

    model.evaluate()
    if weights_dtype is not None and str(weights_dtype) == "int8":
        from bigdl_tpu.nn.quantized import Quantizer

        qmodel = Quantizer.quantize(model)
    else:
        qmodel = model
    qmodel.evaluate()
    vocab = model.vocab_size
    window = model.max_len
    horizon = max(2, min(horizon, window - prompt_len - 1))
    if prompts is None:
        r = np.random.RandomState(seed)
        prompts = [r.randint(0, vocab, (prompt_len,)).astype(np.int32)
                   for _ in range(n_prompts)]

    def greedy_roll(m, ids, kv, forced=None):
        """Greedy rollout (or teacher-forced when ``forced`` is the
        token list to feed) returning (tokens, per-step logits)."""
        c = m.init_cache(1, window, kv_dtype=kv)
        lg, c = m.prefill(ids, c)
        logits = [np.asarray(lg).reshape(-1)]
        toks = [int(np.argmax(logits[-1]))]
        pos = ids.shape[1]
        for i in range(horizon - 1):
            nxt = forced[i] if forced is not None else toks[-1]
            lg, c = m.decode_step(jnp.asarray([nxt]), jnp.int32(pos), c)
            logits.append(np.asarray(lg).reshape(-1))
            toks.append(int(np.argmax(logits[-1])))
            pos += 1
        return toks, logits

    div_max, fp_scale = 0.0, 0.0
    div_means, match = [], []
    for p in prompts:
        ids = jnp.asarray(np.asarray(p, np.int32))[None]
        fp_toks, fp_logits = greedy_roll(model, ids, None)
        fp_scale = max(fp_scale,
                       max(float(np.max(np.abs(l))) for l in fp_logits))
        _, q_logits = greedy_roll(qmodel, ids, kv_dtype,
                                  forced=fp_toks)
        d = [float(np.max(np.abs(a - b)))
             for a, b in zip(fp_logits, q_logits)]
        div_max = max(div_max, max(d))
        div_means.append(float(np.mean(d)))
        q_toks, _ = greedy_roll(qmodel, ids, kv_dtype)
        k = 0
        for a, b in zip(fp_toks, q_toks):
            if a != b:
                break
            k += 1
        match.append(k / len(fp_toks))
    return {
        "kv_dtype": kv_dtype,
        "weights_dtype": (weights_dtype or "fp"),
        "prompts": len(prompts), "horizon": horizon,
        "vocab": vocab,
        "logit_div_max": round(div_max, 6),
        "logit_div_mean": round(float(np.mean(div_means)), 6),
        "logit_div_rel": (round(div_max / fp_scale, 6)
                          if fp_scale else 0.0),
        "greedy_match_fraction": round(float(np.mean(match)), 4),
    }


def run_quantized_comparison(model, n_requests: int = 24,
                             rate_hz: float = 30.0,
                             max_slots: int = 4,
                             prefill_chunk: int = 8,
                             prefill_rows: int = 2,
                             gamma: int = 4,
                             eos_id: Optional[int] = None,
                             seed: int = 0, registry=None,
                             log=None) -> dict:
    """Replay ONE repeated-text Poisson workload through the engine
    twice — int8 KV pools + int8 weights (``kv_dtype=weights_dtype=
    "int8"``) vs full precision, everything else identical — and
    report inter-token/TTFT/latency percentiles for both, the
    membw-utilization pair the cost model attributes (decode is
    memory-bound, so halving the streamed bytes is exactly what this
    row must show), the capacity block (physical row bytes both ways),
    and the QUALITY gate: the per-token logit-divergence report
    (:func:`quantized_quality_report`, deterministic) plus the
    speculative acceptance-rate delta measured by replaying the same
    workload under an int8 draft with fp vs int8 KV (the draft must
    keep agreeing with the target when the cache quantizes). Token
    parity is asserted WITHIN each numerics regime — speculation must
    not change tokens whether the cache is fp or int8 — never across
    regimes (int8 rounds differently; the quality report bounds that
    drift instead)."""
    log = log or (lambda *a, **k: None)
    from bigdl_tpu.nn.quantized import Quantizer

    vocab = model.vocab_size
    window = (model.max_len // prefill_chunk) * prefill_chunk
    decode_hi = max(8, min(24, window // 2 - 16))
    wl = repeated_text_workload(
        n_requests, rate_hz, vocab,
        prompt_lens=(8, min(16, window - decode_hi - 1)),
        decode_lens=(min(8, decode_hi), decode_hi), seed=seed)
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(0, vocab, (12,)),
        np.int32)
    log("[serving-bench] quantizing the int8 draft clone...")
    draft = Quantizer.quantize(model)
    draft.evaluate()

    def run_path(name: str, **engine_kw) -> dict:
        return _engine_replay(
            model, wl, warm_prompt, 4,
            ("speculation", "quantization", "jit_compiles"), log,
            "quantized", max_slots=max_slots,
            prefill_chunk=prefill_chunk, prefill_rows=prefill_rows,
            eos_id=eos_id, registry=registry, service_name=name,
            **engine_kw)

    quant = run_path("bench_quant_on", kv_dtype="int8",
                     weights_dtype="int8")
    fp = run_path("bench_quant_off")
    # acceptance-delta probe: the SAME draft over the SAME workload,
    # fp KV vs int8 KV (weights fp in both, so the cache is the ONLY
    # thing that moves) — quantizing the cache must not change how
    # often the target agrees with its draft (delta ~ 0). The plain
    # kv-only leg exists so each spec leg has a same-numerics
    # non-speculative twin to assert token parity against.
    kv8 = run_path("bench_quant_kv_only", kv_dtype="int8")
    spec_fp = run_path("bench_quant_spec_fp", draft=draft,
                       spec_gamma=gamma)
    spec_q = run_path("bench_quant_spec_int8", draft=draft,
                      spec_gamma=gamma, kv_dtype="int8")
    parity_fp = all(
        np.array_equal(fp["rows"][id(r)], spec_fp["rows"][id(r)])
        for r in wl)
    parity_q = all(
        np.array_equal(kv8["rows"][id(r)], spec_q["rows"][id(r)])
        for r in wl)
    for r in (quant, fp, kv8, spec_fp, spec_q):
        del r["rows"]
    log("[serving-bench] quantized quality report "
        "(teacher-forced logit divergence)...")
    quality = quantized_quality_report(model, horizon=min(16, window // 2))
    acc_fp = spec_fp["speculation"].get("acceptance_rate")
    acc_q = spec_q["speculation"].get("acceptance_rate")
    quality["acceptance_rate_fp"] = acc_fp
    quality["acceptance_rate_int8"] = acc_q
    # SIGNED, positive = the int8 cache LOST acceptance. One-sided by
    # design: shared rounding noise correlates the int8 draft with an
    # int8-cached target, so acceptance typically RISES under
    # quantization — a throughput win the gate must not punish; only a
    # drop (the draft disagreeing with what it will serve) is a
    # regression
    quality["acceptance_delta"] = (round(acc_fp - acc_q, 4)
                                   if acc_fp is not None
                                   and acc_q is not None else None)

    def ratio(key, base=None, new=None):
        a = (base or fp)["inter_token"][key]
        b = (new or quant)["inter_token"][key]
        return round(a / b, 4) if a and b else None

    def membw(leg):
        return ((leg.get("cost") or {}).get("overall")
                or {}).get("membw_util")

    qz = quant["quantization"]
    return {"quantized": quant, "fp_baseline": fp, "kv_only": kv8,
            "spec_fp": spec_fp, "spec_int8": spec_q,
            "inter_token_p50_speedup": ratio("p50"),
            "inter_token_p99_speedup": ratio("p99"),
            # the full quantized stack under its draft vs the fp stack
            # under the same draft: a risen acceptance rate turns into
            # longer accepted bursts, so the int8 cache can improve the
            # inter-token TAIL even where raw int8 math doesn't pay
            # (CPU)
            "spec_inter_token_p50_speedup":
                ratio("p50", base=spec_fp, new=spec_q),
            "spec_inter_token_p99_speedup":
                ratio("p99", base=spec_fp, new=spec_q),
            "membw_util": {"fp": membw(fp), "quantized": membw(quant)},
            "capacity": {
                "kv_row_bytes": qz["kv_row_bytes"],
                "fp_row_bytes": qz["fp_row_bytes"],
                "row_bytes_ratio": qz["row_bytes_ratio"],
                "capacity_multiplier":
                    (round(qz["fp_row_bytes"] / qz["kv_row_bytes"], 4)
                     if qz["kv_row_bytes"] else None)},
            "quality": quality,
            "token_parity_spec_fp": bool(parity_fp),
            "token_parity_spec_int8": bool(parity_q),
            "workload": {"kind": "quantized",
                         "requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "prefill_rows": prefill_rows,
                         "gamma": gamma}}


def run_shared_prefix_comparison(model, n_requests: int = 24,
                                 rate_hz: float = 30.0,
                                 max_slots: int = 4,
                                 prefill_chunk: int = 8,
                                 prefill_rows: int = 2,
                                 n_templates: int = 4,
                                 template_len: int = 96,
                                 eos_id: Optional[int] = None,
                                 seed: int = 0, registry=None,
                                 log=None) -> dict:
    """Replay ONE shared-prefix Poisson workload through the engine
    twice — prefix cache ENABLED vs DISABLED, everything else identical
    — and report TTFT/latency percentiles for both, the cached run's
    hit-rate block, the p50/p99 TTFT speedups, and whether the two
    paths produced token-identical greedy outputs (they must). This is
    the O(prompt) → O(novel-suffix) TTFT claim, measured."""
    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    # fit tail + decode inside the ENGINE's serving window: a sampled
    # prompt of template + tail_hi plus decode_hi tokens must never
    # overflow it (engine.submit would reject it mid-replay). The
    # window is the model context rounded DOWN to a chunk multiple
    # when it doesn't divide evenly — mirror engine.__init__'s cap.
    window = (model.max_len // prefill_chunk) * prefill_chunk
    room = window - template_len
    if room < 2:
        raise ValueError(
            f"template_len {template_len} leaves only {room} of the "
            f"engine's {window}-token serving window for tail + decode")
    tail_hi = max(1, min(12, room // 2))
    decode_hi = max(1, min(16, room - tail_hi))
    wl = shared_prefix_workload(
        n_requests, rate_hz, vocab, n_templates=n_templates,
        template_len=template_len,
        tail_lens=(min(4, tail_hi), tail_hi),
        decode_lens=(min(4, decode_hi), decode_hi),
        seed=seed)
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(
            0, vocab, (template_len,)), np.int32)

    def run_path(name: str, **engine_kw) -> dict:
        # the warm prompt is a NON-template one, so the compile cost
        # lands outside the measurement and the template cache starts
        # cold for both paths
        return _engine_replay(
            model, wl, warm_prompt, 2, ("prefix_cache",), log,
            "shared-prefix",
            max_slots=max_slots, prefill_chunk=prefill_chunk,
            prefill_rows=prefill_rows, eos_id=eos_id,
            registry=registry, service_name=name, **engine_kw)

    cached = run_path("bench_prefix_on")
    uncached = run_path("bench_prefix_off", prefix_cache_bytes=0)
    parity = all(
        np.array_equal(cached["rows"][id(req)], uncached["rows"][id(req)])
        for req in wl)
    for r in (cached, uncached):
        del r["rows"]

    def ratio(key):
        a, b = uncached["ttft"][key], cached["ttft"][key]
        return round(a / b, 4) if a and b else None

    return {"cached": cached, "uncached": uncached,
            "ttft_p50_speedup": ratio("p50"),
            "ttft_p99_speedup": ratio("p99"),
            "token_parity": bool(parity),
            "workload": {"kind": "shared_prefix",
                         "requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "prefill_rows": prefill_rows,
                         "n_templates": n_templates,
                         "template_len": template_len}}


def run_working_set_sweep(model, working_sets=(2, 8),
                          device_rows: int = 2,
                          requests_per_template: int = 3,
                          rate_hz: float = 40.0, max_slots: int = 4,
                          prefill_chunk: int = 8,
                          prefill_rows: int = 2,
                          template_len: int = 16,
                          eos_id: Optional[int] = None, seed: int = 0,
                          registry=None, log=None) -> dict:
    """Sweep the shared-prefix WORKING SET past the device budget and
    measure where each cache tier's hit rate falls off. Each point
    replays one round-robin template workload (``working_set``
    templates ≫ ``device_rows`` pool rows is the LRU worst case: every
    revisit is exactly ``working_set`` requests away) through THREE
    engines — host tier sized to the working set, device-only, and
    cache-disabled — everything else identical. The device-only leg
    collapses once the working set exceeds ``device_rows`` (LRU
    thrashes: a template is always evicted before its revisit); the
    tiered leg holds the hit rate because evictions demote to host RAM
    and revisits promote back. Per point the sweep also checks the
    invariants the tiers must not bend: token parity of both cached
    legs against the cache-disabled oracle, the jit-compile gauge flat
    from warmup through every demote/promote, and usage-ledger
    device-seconds conservation (per-tenant sums == measured dispatch
    total) with promotions in flight."""
    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    window = (model.max_len // prefill_chunk) * prefill_chunk
    room = window - template_len
    if room < 2:
        raise ValueError(
            f"template_len {template_len} leaves only {room} of the "
            f"engine's {window}-token serving window for tail + decode")
    tail_hi = max(1, min(4, room // 2))
    decode_hi = max(1, min(8, room - tail_hi))
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(
            0, vocab, (template_len,)), np.int32)

    def leg(name, wl, probe, **engine_kw):
        res = _engine_replay(
            model, wl, warm_prompt, 2,
            ("prefix_cache", "jit_compiles"), log, name,
            after_warm=probe, max_slots=max_slots,
            prefill_chunk=prefill_chunk, prefill_rows=prefill_rows,
            eos_id=eos_id, registry=registry, service_name=name,
            **engine_kw)
        tenant_s = sum(t["device_s"] for t in res["tenants"].values())
        total_s = res["goodput"]["device_seconds"]["total"]
        res["ledger_conserved"] = bool(
            abs(tenant_s - total_s) <= 1e-6 * max(total_s, 1e-9))
        return res

    points = []
    for ws in working_sets:
        n_req = max(int(ws) * max(2, requests_per_template), 8)
        wl = shared_prefix_workload(
            n_req, rate_hz, vocab, n_templates=int(ws),
            template_len=template_len,
            tail_lens=(min(2, tail_hi), tail_hi),
            decode_lens=(min(4, decode_hi), decode_hi),
            seed=seed + int(ws), template_order="cycle")
        baseline = {}

        def probe(eng, _b=baseline):
            _b["jit"] = eng.stats()["jit_compiles"]

        legs = {}
        # the host tier absorbs the DONATION working set: every request
        # donates its own template+tail entry (the trie matches revisits
        # against any same-template predecessor's head), so the hot set
        # is the request count, not the template count
        for name, kw in (
                ("tiered", {"prefix_cache_rows": device_rows,
                            "prefix_host_rows": n_req}),
                ("device_only", {"prefix_cache_rows": device_rows}),
                ("disabled", {"prefix_cache_bytes": 0})):
            baseline.clear()
            r = leg(f"ws{ws}_{name}", wl, probe, **kw)
            r["jit_flat"] = bool(r["jit_compiles"] == baseline["jit"])
            legs[name] = r
        parity = all(
            np.array_equal(legs[a]["rows"][id(req)],
                           legs["disabled"]["rows"][id(req)])
            for a in ("tiered", "device_only") for req in wl)
        for r in legs.values():
            del r["rows"]

        def trim(r):
            pc = r["prefix_cache"]
            out = {"ttft": r["ttft"], "latency": r["latency"],
                   "tokens_per_sec": r["tokens_per_sec"],
                   "jit_flat": r["jit_flat"],
                   "ledger_conserved": r["ledger_conserved"]}
            if pc.get("enabled"):
                out.update(
                    hit_rate=pc["hit_rate"], hits=pc["hits"],
                    misses=pc["misses"],
                    reused_tokens=pc["reused_tokens"],
                    capacity_bytes=pc["capacity_bytes"])
                if pc.get("host_rows"):
                    out.update(
                        host_hits=pc["host_hits"],
                        demotions=pc["demotions"],
                        promotions=pc["promotions"],
                        host_evictions=pc["host_evictions"],
                        host_capacity_bytes=pc["host_capacity_bytes"])
            return out

        points.append({
            "working_set": int(ws),
            "ws_to_budget": round(int(ws) / device_rows, 2),
            "requests": n_req,
            "token_parity": bool(parity),
            "tiered": trim(legs["tiered"]),
            "device_only": trim(legs["device_only"]),
            "disabled": trim(legs["disabled"]),
            # full blocks the headline promotes (cost classification,
            # goodput, steady-state gap) — per-point only the trims
            "_tiered_full": {k: legs["tiered"][k] for k in
                             ("cost", "loop", "goodput", "inter_token")},
        })
        log(f"[serving-bench] working-set {ws}: tiered hit-rate "
            f"{points[-1]['tiered'].get('hit_rate')} vs device-only "
            f"{points[-1]['device_only'].get('hit_rate')}")

    # headline = the deepest point past the budget (the cliff the host
    # tier exists to hold); falls back to the last point
    past = [p for p in points if p["ws_to_budget"] >= 4.0]
    head = (past or points)[-1]
    dev_hr = head["device_only"].get("hit_rate") or 0.0
    tier_hr = head["tiered"].get("hit_rate") or 0.0
    tiered_full = {**head["tiered"], **head.pop("_tiered_full")}
    for p in points:
        p.pop("_tiered_full", None)
    return {
        "points": points,
        # the headline point's legs at top level: perf_gate reads
        # detail.tiered.{ttft,inter_token,goodput} like any other
        # engine leg, detail.headline.tiered_hit_rate for the
        # higher-is-better gate
        "tiered": tiered_full,
        "device_only": head["device_only"],
        "headline": {
            "working_set": head["working_set"],
            "ws_to_budget": head["ws_to_budget"],
            "tiered_hit_rate": tier_hr,
            "device_only_hit_rate": dev_hr,
            "hit_rate_gain": (round(tier_hr / dev_hr, 2)
                              if dev_hr > 0 else None),
            "tiered_ttft_p50_s": head["tiered"]["ttft"]["p50"],
            "device_only_ttft_p50_s": head["device_only"]["ttft"]["p50"],
            "token_parity": head["token_parity"],
            "jit_flat": bool(head["tiered"]["jit_flat"]),
            "ledger_conserved": bool(
                head["tiered"]["ledger_conserved"]),
        },
        "workload": {"kind": "working_set_sweep",
                     "device_rows": device_rows,
                     "working_sets": [int(w) for w in working_sets],
                     # scalars for perf_gate's signature (it ignores
                     # the list): two sweeps compare only when they
                     # sweep the same depth
                     "max_working_set": int(max(working_sets)),
                     "n_points": len(list(working_sets)),
                     "requests_per_template": requests_per_template,
                     "rate_hz": rate_hz, "seed": seed,
                     "max_slots": max_slots,
                     "prefill_rows": prefill_rows,
                     "template_len": template_len}}


def run_tp_comparison(model, tp: int = 2, n_requests: int = 16,
                      rate_hz: float = 30.0, max_slots: int = 4,
                      prefill_chunk: int = 8, prefill_rows: int = 2,
                      eos_id: Optional[int] = None, seed: int = 0,
                      registry=None, log=None, mesh=None,
                      model_axis: str = "model") -> dict:
    """Replay ONE Poisson workload through the engine twice — SHARDED
    over a ``tp``-way model-axis device mesh (params Megatron-split,
    KV pools sharded on heads, SPMD dispatches) vs the plain
    single-device engine, everything else identical — and report
    TTFT / inter-token / latency percentiles for both, the sharded
    run's mesh block and jit-compile count, and whether the two paths
    produced token-identical greedy outputs (they must: a mesh changes
    WHERE the math runs, never the tokens). On a CPU host this is the
    hermetic host-device-mesh A/B ``bench.py --serving --tp`` emits;
    on real hardware the same call measures actual ICI scaling."""
    import jax

    from bigdl_tpu.parallel.engine import Engine

    log = log or (lambda *a, **k: None)
    if mesh is None:
        devices = jax.devices()
        if len(devices) < tp:
            try:
                devices = jax.devices("cpu")
            except RuntimeError:
                pass
        if len(devices) < tp:
            raise ValueError(
                f"tp={tp} needs {tp} devices but only {len(devices)} "
                f"are visible; on CPU set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={tp}")
        mesh = Engine.create_mesh([(model_axis, tp)],
                                  devices=devices[:tp])
    vocab = model.vocab_size
    wl = poisson_workload(n_requests, rate_hz, vocab,
                          decode_lens=(4, min(24, model.max_len // 2)),
                          seed=seed)
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(0, vocab, (12,)),
        np.int32)

    def run_path(name: str, **engine_kw) -> dict:
        return _engine_replay(
            model, wl, warm_prompt, 4, ("mesh", "jit_compiles"), log,
            "tensor-parallel",
            max_slots=max_slots, prefill_chunk=prefill_chunk,
            prefill_rows=prefill_rows, eos_id=eos_id,
            registry=registry, service_name=name, **engine_kw)

    sharded = run_path("bench_tp_sharded", mesh=mesh,
                       model_axis=model_axis)
    unsharded = run_path("bench_tp_unsharded")
    parity = all(
        np.array_equal(sharded["rows"][id(req)],
                       unsharded["rows"][id(req)])
        for req in wl)
    for r in (sharded, unsharded):
        del r["rows"]

    def ratio(block, key):
        a, b = unsharded[block][key], sharded[block][key]
        return round(a / b, 4) if a and b else None

    return {"sharded": sharded, "unsharded": unsharded,
            "ttft_p50_ratio": ratio("ttft", "p50"),
            "inter_token_p50_ratio": ratio("inter_token", "p50"),
            "inter_token_p99_ratio": ratio("inter_token", "p99"),
            "token_parity": bool(parity),
            "workload": {"kind": "tensor_parallel", "tp": int(tp),
                         "requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "prefill_rows": prefill_rows}}


def run_poisson_comparison(model, n_requests: int = 16,
                           rate_hz: float = 20.0, max_slots: int = 4,
                           prefill_chunk: int = 8, max_batch: int = 4,
                           batch_timeout_ms: float = 10.0,
                           eos_id: Optional[int] = None, seed: int = 0,
                           registry=None, log=None) -> dict:
    """Run the same Poisson workload through the continuous-batching
    engine and through ``GenerationService``; return both result dicts
    plus the engine's TTFT percentiles and the p99 speedup ratio
    (> 1.0: the engine's tail is shorter)."""
    from bigdl_tpu.optim import GenerationService
    from bigdl_tpu.serving import ContinuousBatchingEngine

    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    wl = poisson_workload(n_requests, rate_hz, vocab,
                          decode_lens=(4, min(24, model.max_len // 2)),
                          seed=seed)

    engine = ContinuousBatchingEngine(
        model, max_slots=max_slots, prefill_chunk=prefill_chunk,
        eos_id=eos_id, registry=registry, service_name="bench_engine")
    ttft: List[float] = []
    itl: List[float] = []
    tlock = threading.Lock()

    def collect_engine(handle, req):
        row = handle.result()
        with tlock:
            if handle.first_token_at is not None:
                ttft.append(handle.first_token_at - handle.submitted_at)
            _append_itl(itl, handle)
        return row.shape[0] - req["prompt"].shape[0]

    log("[serving-bench] engine replay...")
    with engine:
        eng = _replay(
            wl, lambda req: engine.submit(req["prompt"], req["n"],
                                          tenant=req.get("tenant")),
            collect_engine)
        stats = engine.stats()
        eng["alerts"] = stats["alerts"]
        eng.update(_usage_blocks(stats))
        eng["cost"] = stats.get("cost")
        eng["loop"] = stats.get("loop")
        # calm-storm incident gate: a healthy Poisson replay must
        # record ZERO incidents (perf_gate fails the build otherwise)
        inc = stats.get("incidents") or {}
        incidents = {"count": inc.get("count", 0),
                     "by_kind": inc.get("by_kind", {}), "calm": True}
    eng["ttft"] = _percentiles(ttft)
    eng["inter_token"] = _percentiles(itl)

    svc = GenerationService(model, max_batch=max_batch,
                            batch_timeout_ms=batch_timeout_ms,
                            bucket_tokens=8, prompt_bucket=8,
                            eos_id=eos_id, registry=registry,
                            service_name="bench_generation")
    log("[serving-bench] GenerationService replay...")
    gen = _replay(
        wl, lambda req: svc.generate(req["prompt"], req["n"]),
        lambda row, req: row.shape[0] - req["prompt"].shape[0])

    p99_ratio = (round(gen["latency"]["p99"] / eng["latency"]["p99"], 4)
                 if eng["latency"]["p99"] else None)
    return {"engine": eng, "generation_service": gen,
            "p99_speedup": p99_ratio,
            "incidents": incidents,
            "workload": {"requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "max_batch": max_batch}}


# --------------------------------------------------------------- QoS storm

#: priority assignment cycle for the storm mix: half the traffic is
#: low-class batch work (long decodes that hold slots), a quarter
#: latency-sensitive high-class interactive traffic (long prompts,
#: short decodes), a quarter normal. The cycle leads with TWO lows so
#: the storm opens with every slot held by batch work — the first
#: high-class arrival then exercises the preemption path, not a free
#: slot
_QOS_MIX = ("low", "low", "high", "normal")

#: tenant names by class — the ledger's fair-share breakdown needs the
#: classes billed apart; the over-budget tenant is added on top
_QOS_TENANTS = {"high": "interactive", "normal": "standard",
                "low": "batch"}


def qos_storm_workload(n_requests: int, rate_hz: float, vocab: int,
                       n_greedy: int = 3, seed: int = 0) -> List[dict]:
    """Sample the MIXED-PRIORITY storm: Poisson arrivals cycling
    through ``_QOS_MIX`` — high-class requests get LONG prompts and
    short decodes (interactive: TTFT is the product), low/normal get
    short prompts and LONG decodes (batch: they hold slots, which is
    what makes them preemption victims) — plus ``n_greedy`` extra
    high-class requests under the ``greedy`` tenant spread across the
    storm span (the token-bucket's prey: even the top class cannot buy
    unmetered device time). Each request carries ``priority`` and
    ``tenant`` next to the usual arrival/prompt/n fields."""
    r = np.random.RandomState(seed)
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        cls = _QOS_MIX[i % len(_QOS_MIX)]
        if cls == "high":
            # interactive prompts are LONG (12-14 prefill chunks):
            # TTFT is then dominated by real prefill work, so the
            # fixed few-ms cost of a preemption reads as the small
            # fraction it is, not as a 2x on a trivial baseline
            t0 = int(r.randint(96, 113))
            n = int(r.randint(4, 9))
        else:
            t0 = int(r.randint(8, 17))
            n = int(r.randint(56, 81))
        out.append({
            "arrival_s": float(at[i]),
            "prompt": r.randint(0, vocab, (t0,)).astype(np.int32),
            "n": n,
            "priority": cls,
            "tenant": _QOS_TENANTS[cls],
        })
    # pin the storm's opening: the second batch job lands 10ms behind
    # the first and the first interactive request 20ms behind that —
    # DETERMINISTICALLY, both slots are held by mid-decode batch work
    # when the first high-class request arrives, so the preemption
    # path runs on every seed, not just unlucky ones
    if n_requests > 2:
        out[1]["arrival_s"] = out[0]["arrival_s"] + 0.01
        out[2]["arrival_s"] = out[1]["arrival_s"] + 0.02
    span = float(at[-1])
    for k in range(n_greedy):
        out.append({
            # the first greedy request lands early enough to ADMIT and
            # drain the bucket (16 decode tokens bill well past the
            # bucket's burst); the rest arrive after it has finished
            # and been billed, so they meet an exhausted bucket
            "arrival_s": span * (0.2 + 0.65 * k / max(1, n_greedy - 1)),
            "prompt": r.randint(0, vocab, (24,)).astype(np.int32),
            "n": 16,
            "priority": "high",
            "tenant": "greedy",
        })
    out.sort(key=lambda q: q["arrival_s"])
    return out


def _qos_replay(engine, workload, timeout_s: float = 300.0) -> dict:
    """Open-loop replay with OUTCOME accounting: structured QoS
    rejections (shed / rate-limited) are expected results here, not
    errors — every submit is tallied into exactly one terminal outcome
    and the TTFT samples are kept PER CLASS (the storm's headline is
    the high class's tail, measured apart from the traffic being
    sacrificed for it)."""
    from bigdl_tpu.serving.streams import (
        RequestCancelled, RequestRateLimited, RequestShed,
        RequestTimedOut,
    )

    outcomes = {"finished": 0, "shed": 0, "rate_limited": 0,
                "cancelled": 0, "timed_out": 0}
    # the greedy tenant is high-CLASS but not the headline: its TTFTs
    # land in their own bucket so the interactive tail stays clean
    ttft_by_class = {"high": [], "normal": [], "low": [], "greedy": []}
    itl_high: List[float] = []
    lat: List[float] = []
    toks: List[int] = []
    retry_hints: List[float] = []
    errs: List[BaseException] = []
    lock = threading.Lock()
    t_start = time.monotonic()

    def one(req):
        try:
            t_sub = time.monotonic()
            try:
                h = engine.submit(req["prompt"], req["n"],
                                  tenant=req["tenant"],
                                  priority=req["priority"])
            except (RequestShed, RequestRateLimited) as e:
                kind = ("shed" if isinstance(e, RequestShed)
                        else "rate_limited")
                with lock:
                    outcomes[kind] += 1
                    retry_hints.append(float(e.retry_after_s))
                return
            try:
                row = h.result(timeout=timeout_s)
            except RequestTimedOut:
                with lock:
                    outcomes["timed_out"] += 1
                return
            except RequestCancelled:
                with lock:
                    outcomes["cancelled"] += 1
                return
            dt = time.monotonic() - t_sub
            cls = ("greedy" if req["tenant"] == "greedy"
                   else req["priority"])
            with lock:
                outcomes["finished"] += 1
                lat.append(dt)
                toks.append(row.shape[0] - req["prompt"].shape[0])
                if h.first_token_at is not None:
                    ttft_by_class[cls].append(
                        h.first_token_at - h.submitted_at)
                if cls == "high":
                    _append_itl(itl_high, h)
        except BaseException as e:
            with lock:
                errs.append(e)

    threads = []
    for req in workload:
        delay = t_start + req["arrival_s"] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=one, args=(req,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    if errs:
        raise errs[0]
    return {"latency": _percentiles(lat),
            "ttft_by_class": {c: _percentiles(v)
                              for c, v in ttft_by_class.items()},
            # the leg's headline percentile blocks are the HIGH class's
            # — the class the SLO is written for, and what perf_gate
            # reads as detail.qos.{ttft,inter_token}
            "ttft": _percentiles(ttft_by_class["high"]),
            "inter_token": _percentiles(itl_high),
            "tokens_per_sec": round(sum(toks) / max(wall, 1e-9), 2),
            "wall_s": round(wall, 3),
            "submitted": len(workload),
            "outcomes": outcomes,
            "retry_after_s_max": (round(max(retry_hints), 3)
                                  if retry_hints else None)}


def run_qos_storm(model, n_requests: int = 24, rate_hz: float = 20.0,
                  max_slots: int = 2, prefill_chunk: int = 8,
                  prefill_rows: int = 2, n_greedy: int = 3,
                  eos_id: Optional[int] = None, seed: int = 0,
                  registry=None, log=None) -> dict:
    """Replay ONE mixed-priority Poisson storm into a deliberately
    undersized engine (``max_slots`` far below the offered load) wired
    with the full QoS stack — a hair-trigger TTFT SLO objective so the
    burn-rate shedder fires on the real watchdog, zero preemption
    slack so waiting high-class requests evict batch slots through the
    KV-donation path, ``shed_classes=("low", "normal")`` so a severe
    burn widens the shed set, and a starved token bucket for the
    ``greedy`` tenant — then replay ONLY the high-class interactive
    requests through the SAME engine config as the uncontended
    baseline.

    The headline is ``high_ttft_p99_ratio`` (storm / uncontended high-
    class p99 TTFT; the acceptance bar is <= 1.25x — under a storm
    that sheds and preempts everything else, the top class's tail must
    stay within a quarter of its uncontended self). The row also
    carries the shed / preempted / rate-limited counts (all must be
    > 0: a storm that never fired the machinery measured nothing), the
    outcome-conservation verdict (client-side outcome tally == submits
    AND == the engine's own finished+shed+rate_limited accounting — no
    silent drops), and the per-tenant ledger breakdown."""
    from bigdl_tpu.serving import ContinuousBatchingEngine

    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    wl = qos_storm_workload(n_requests, rate_hz, vocab,
                            n_greedy=n_greedy, seed=seed)
    # the uncontended baseline is the HIGH-PRIORITY traffic alone —
    # interactive AND greedy, at the same arrival offsets, under the
    # same rate limits — so any high-vs-high collision lands in both
    # legs identically and the ratio isolates what the STORM adds
    high_only = [q for q in wl if q["priority"] == "high"]
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(0, vocab, (12,)),
        np.int32)
    engine_kw = dict(
        max_slots=max_slots, prefill_chunk=prefill_chunk,
        prefill_rows=prefill_rows, eos_id=eos_id, registry=registry,
        # the burn objective is a tripwire, not a target: every real
        # TTFT lands over 0.1ms, so the storm's traffic itself drives
        # the watchdog into a SEVERE burn (burn 10 >= 2x threshold)
        # within min_count observations — shedding activates on the
        # same machinery production would use, just tuned to fire
        # min_count 3 = warm + the two leading lows: the slot-holding
        # batch work ADMITS before the burn trips, so the first high
        # arrival preempts a live victim; everything low/normal after
        # the trip sheds at submit
        slo_objectives=[{"name": "ttft_burn", "metric": "ttft",
                         "threshold_s": 1e-4, "target": 0.9,
                         "window_s": 30.0, "min_count": 3,
                         "burn_threshold": 2.0}],
        shed_classes=("low", "normal"),
        preempt_slack_s=0.0,
        tenant_rate_limits={"greedy": (0.01, 0.001)})

    def leg(name: str, work) -> dict:
        log(f"[serving-bench] qos {name} replay...")
        with ContinuousBatchingEngine(model, service_name=name,
                                      **engine_kw) as eng:
            eng.submit(warm_prompt, 4).result(timeout=300)
            res = _qos_replay(eng, work)
            stats = eng.stats()
        res["qos_state"] = stats["qos"]
        res.update(_usage_blocks(stats))
        res["cost"] = stats.get("cost")
        res["loop"] = stats.get("loop")
        res["alerts"] = stats["alerts"]
        # conservation against the ENGINE's own books, not just the
        # client's: every submit the engine saw must have landed in
        # exactly one terminal outcome counter
        qc = stats["qos"]
        eng_terminal = (stats["finished"] + qc["shed"]
                        + qc["rate_limited"] + stats["cancelled"]
                        + stats["timed_out"])
        client_terminal = sum(res["outcomes"].values())
        res["conservation_ok"] = bool(
            client_terminal == res["submitted"]
            # +1: the warm request finished outside the tally
            and eng_terminal == res["submitted"] + 1)
        return res

    storm = leg("bench_qos_storm", wl)
    uncont = leg("bench_qos_uncontended", high_only)

    def ratio(key):
        a = storm["ttft"][key]
        b = uncont["ttft"][key]
        return round(a / b, 4) if a and b else None

    qc = storm["qos_state"]
    return {
        "qos": storm, "uncontended": uncont,
        "high_ttft_p50_ratio": ratio("p50"),
        "high_ttft_p99_ratio": ratio("p99"),
        "shed": qc["shed"], "preempted": qc["preempted"],
        "rate_limited": qc["rate_limited"],
        "conservation_ok": bool(storm["conservation_ok"]
                                and uncont["conservation_ok"]),
        "workload": {"kind": "qos_storm", "requests": n_requests,
                     "n_greedy": n_greedy, "rate_hz": rate_hz,
                     "seed": seed, "max_slots": max_slots,
                     "prefill_rows": prefill_rows}}


def mixed_length_workload(n_requests: int, rate_hz: float, vocab: int,
                          short_prompt=(4, 12), short_decode=(4, 12),
                          long_prompt: int = 32, long_decode: int = 8,
                          long_every: int = 6, seed: int = 0,
                          tenants=("tenant-a", "tenant-b", "tenant-c")
                          ) -> List[dict]:
    """Sample a MIXED short/long open-loop workload: mostly short
    interactive requests with every ``long_every``-th a long-prompt
    batch job — the traffic shape where full-window slot reservation
    wastes the most KV (a 10-token chat holds a whole context row)
    and page-granular reservation buys the most concurrency. Long
    requests use FIXED lengths so their page footprint is
    deterministic across seeds. Same arrival/replay semantics as
    :func:`poisson_workload`."""
    r = np.random.RandomState(seed)
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        if long_every and i % long_every == long_every - 1:
            t0, n = int(long_prompt), int(long_decode)
        else:
            t0 = int(r.randint(short_prompt[0], short_prompt[1] + 1))
            n = int(r.randint(short_decode[0], short_decode[1] + 1))
        out.append({
            "arrival_s": float(at[i]),
            "prompt": r.randint(0, vocab, (t0,)).astype(np.int32),
            "n": n,
            "tenant": tenants[i % len(tenants)] if tenants else None,
        })
    return out


def _peak_concurrency(spans) -> int:
    """Max number of overlapping ``(start, end)`` intervals — the peak
    count of requests simultaneously HOLDING a slot (admitted, not yet
    finished), computed offline from the handles' lifecycle stamps so
    no sampler races the loop. A release at exactly another's admit
    counts as a handoff, not an overlap."""
    events = []
    for a, b in spans:
        events.append((a, 1))
        events.append((b, -1))
    events.sort()  # (t, -1) orders before (t, +1): handoff, not overlap
    cur = peak = 0
    for _, d in events:
        cur += d
        peak = max(peak, cur)
    return peak


def run_paged_comparison(model, n_requests: int = 32,
                         rate_hz: float = 200.0, dense_slots: int = 2,
                         paged_slots: int = 8, page_size: int = 4,
                         prefill_chunk: int = 8, prefill_rows: int = 2,
                         eos_id: Optional[int] = None, seed: int = 0,
                         registry=None, log=None) -> dict:
    """Replay ONE mixed short/long Poisson storm through the engine
    twice at an EQUAL device KV byte budget — paged mode
    (``page_size``-token block pool sized to exactly the dense leg's
    slot-row bytes, ``paged_slots`` slots sharing it page-granular) vs
    dense mode (``dense_slots`` full serving-window rows) — and report
    the peak admitted concurrency both ways (the capacity claim:
    page-granular reservation admits >= 3x the requests from the same
    bytes on short-heavy traffic), TTFT/latency percentiles both ways,
    the paged leg's pool/fragmentation block, and whether the two
    paths produced token-identical greedy outputs (they must: paging
    changes where KV bytes live, never the tokens)."""
    from bigdl_tpu.serving import ContinuousBatchingEngine

    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    window = (model.max_len // prefill_chunk) * prefill_chunk
    table_len = -(-window // page_size)
    # EQUAL BYTE BUDGET: the paged pool gets exactly the bytes the
    # dense leg spends on its slot rows (dense_slots full windows),
    # plus the one reserved scratch page every pool carries
    max_pages = 1 + dense_slots * table_len
    # size the long jobs inside the serving window, and the short ones
    # so a full house of paged_slots worst-case-short requests still
    # fits the shared budget (each reserves pages for t0 + n tokens
    # at admission — see engine._start_admission_paged)
    long_prompt = min(32, window // 2)
    long_decode = min(16, max(1, window - long_prompt))
    # the storm must be DENSE enough to queue: arrivals far outpace
    # service, decodes long enough that early slots are still held
    # while admission fills the rest — otherwise neither leg ever
    # reaches its concurrency ceiling and the ratio measures pacing,
    # not capacity
    wl = mixed_length_workload(
        n_requests, rate_hz, vocab,
        short_prompt=(4, min(12, window // 4)),
        short_decode=(8, min(16, window // 4)),
        long_prompt=long_prompt, long_decode=long_decode, seed=seed)
    warm_prompt = np.asarray(
        np.random.RandomState(seed + 1).randint(0, vocab, (12,)),
        np.int32)

    def leg(name: str, stats_keys, **engine_kw) -> dict:
        engine = ContinuousBatchingEngine(
            model, prefill_chunk=prefill_chunk,
            prefill_rows=prefill_rows, eos_id=eos_id,
            registry=registry, service_name=name,
            # both legs cache-disabled: the A/B isolates the
            # reservation granularity, not prefix reuse
            prefix_cache_rows=0, **engine_kw)
        ttft: List[float] = []
        itl: List[float] = []
        rows: dict = {}
        spans: List[tuple] = []
        tlock = threading.Lock()

        def collect(handle, req):
            row = handle.result()
            with tlock:
                rows[id(req)] = row
                if handle.first_token_at is not None:
                    ttft.append(handle.first_token_at
                                - handle.submitted_at)
                _append_itl(itl, handle)
                if (handle.admitted_at is not None
                        and handle.finished_at is not None):
                    spans.append((handle.admitted_at,
                                  handle.finished_at))
            return row.shape[0] - req["prompt"].shape[0]

        log(f"[serving-bench] paged A/B {name} replay...")
        with engine:
            engine.submit(warm_prompt, 4).result(timeout=300)
            res = _replay(
                wl,
                lambda req: engine.submit(req["prompt"], req["n"],
                                          tenant=req.get("tenant")),
                collect)
            stats = engine.stats()
        res["ttft"] = _percentiles(ttft)
        res["inter_token"] = _percentiles(itl)
        res["peak_admitted_concurrency"] = _peak_concurrency(spans)
        for key in stats_keys:
            res[key] = stats.get(key)
        res.update(_usage_blocks(stats))
        res["cost"] = stats.get("cost")
        res["loop"] = stats.get("loop")
        res["alerts"] = stats["alerts"]
        res["rows"] = rows
        return res

    paged = leg("bench_paged", ("paging", "jit_compiles"),
                max_slots=paged_slots, page_size=page_size,
                max_pages=max_pages)
    dense = leg("bench_dense", ("jit_compiles",),
                max_slots=dense_slots)
    parity = all(
        np.array_equal(paged["rows"][id(req)], dense["rows"][id(req)])
        for req in wl)
    for r in (paged, dense):
        del r["rows"]

    def ttft_ratio(key):
        a, b = dense["ttft"][key], paged["ttft"][key]
        return round(a / b, 4) if a and b else None

    pool = (paged.get("paging") or {}).get("pool") or {}
    page_bytes = pool.get("page_bytes", 0)
    peak_p = paged["peak_admitted_concurrency"]
    peak_d = dense["peak_admitted_concurrency"]
    return {
        "paged": paged, "dense": dense,
        "admitted_concurrency_ratio":
            round(peak_p / peak_d, 4) if peak_d else None,
        "ttft_p50_speedup": ttft_ratio("p50"),
        "ttft_p99_speedup": ttft_ratio("p99"),
        "token_parity": bool(parity),
        "kv_budget": {
            # what each leg could spend on request KV: identical by
            # construction (the scratch page is pool overhead, not
            # request capacity)
            "dense_bytes": dense_slots * table_len * page_bytes,
            "paged_bytes": (max_pages - 1) * page_bytes,
            "page_bytes": page_bytes,
            "max_pages": max_pages,
            "table_len": table_len},
        "workload": {"kind": "paged", "requests": n_requests,
                     "rate_hz": rate_hz, "seed": seed,
                     "dense_slots": dense_slots,
                     "paged_slots": paged_slots,
                     "page_size": page_size,
                     "prefill_rows": prefill_rows}}
