"""Poisson-arrival serving benchmark: engine vs ``GenerationService``.

Replays ONE sampled open-loop workload (exponential inter-arrival gaps,
mixed prompt/decode lengths) against both serving paths and reports the
numbers a serving SLO is written in: per-request latency p50/p99, TTFT
p50/p99 (engine only — the batch service has no streaming), and
aggregate delivered tokens/sec. ``bench.py --serving`` emits the result
into ``bench_history.jsonl`` and the Prometheus snapshot so the serving
perf trajectory is tracked alongside the training headline.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


def poisson_workload(n_requests: int, rate_hz: float, vocab: int,
                     prompt_lens=(4, 16), decode_lens=(4, 24),
                     seed: int = 0) -> List[dict]:
    """Sample an open-loop workload: each request gets an arrival OFFSET
    (cumulative exponential gaps at ``rate_hz``), a random prompt, and a
    random decode length — the same list replays against every serving
    path under comparison."""
    r = np.random.RandomState(seed)
    at = np.cumsum(r.exponential(1.0 / rate_hz, n_requests))
    out = []
    for i in range(n_requests):
        t0 = int(r.randint(prompt_lens[0], prompt_lens[1] + 1))
        out.append({
            "arrival_s": float(at[i]),
            "prompt": r.randint(0, vocab, (t0,)).astype(np.int32),
            "n": int(r.randint(decode_lens[0], decode_lens[1] + 1)),
        })
    return out


def _percentiles(xs) -> dict:
    if not xs:
        return {"p50": None, "p99": None}
    return {"p50": round(float(np.percentile(xs, 50)), 6),
            "p99": round(float(np.percentile(xs, 99)), 6)}


def _replay(workload, submit_fn, collect_fn) -> dict:
    """Open-loop replay: a pacer thread submits each request at its
    arrival offset (late submissions go immediately — arrival times are
    an offered load, not a synchronization barrier); ``collect_fn``
    blocks per request and returns delivered token count."""
    lat: List[float] = []
    toks: List[int] = []
    errs: List[BaseException] = []
    lock = threading.Lock()
    t_start = time.monotonic()

    def one(req):
        try:
            t_sub = time.monotonic()
            pending = submit_fn(req)
            n_tok = collect_fn(pending, req)
            dt = time.monotonic() - t_sub
            with lock:
                lat.append(dt)
                toks.append(n_tok)
        except BaseException as e:
            with lock:
                errs.append(e)

    threads = []
    for req in workload:
        delay = t_start + req["arrival_s"] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=one, args=(req,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    if errs:
        raise errs[0]
    return {"latency": _percentiles(lat),
            "tokens_per_sec": round(sum(toks) / max(wall, 1e-9), 2),
            "wall_s": round(wall, 3), "requests": len(workload)}


def run_poisson_comparison(model, n_requests: int = 16,
                           rate_hz: float = 20.0, max_slots: int = 4,
                           prefill_chunk: int = 8, max_batch: int = 4,
                           batch_timeout_ms: float = 10.0,
                           eos_id: Optional[int] = None, seed: int = 0,
                           registry=None, log=None) -> dict:
    """Run the same Poisson workload through the continuous-batching
    engine and through ``GenerationService``; return both result dicts
    plus the engine's TTFT percentiles and the p99 speedup ratio
    (> 1.0: the engine's tail is shorter)."""
    from bigdl_tpu.optim import GenerationService
    from bigdl_tpu.serving import ContinuousBatchingEngine

    log = log or (lambda *a, **k: None)
    vocab = model.vocab_size
    wl = poisson_workload(n_requests, rate_hz, vocab,
                          decode_lens=(4, min(24, model.max_len // 2)),
                          seed=seed)

    engine = ContinuousBatchingEngine(
        model, max_slots=max_slots, prefill_chunk=prefill_chunk,
        eos_id=eos_id, registry=registry, service_name="bench_engine")
    ttft: List[float] = []
    tlock = threading.Lock()

    def collect_engine(handle, req):
        row = handle.result()
        if handle.first_token_at is not None:
            with tlock:
                ttft.append(handle.first_token_at - handle.submitted_at)
        return row.shape[0] - req["prompt"].shape[0]

    log("[serving-bench] engine replay...")
    with engine:
        eng = _replay(
            wl, lambda req: engine.submit(req["prompt"], req["n"]),
            collect_engine)
    eng["ttft"] = _percentiles(ttft)

    svc = GenerationService(model, max_batch=max_batch,
                            batch_timeout_ms=batch_timeout_ms,
                            bucket_tokens=8, prompt_bucket=8,
                            eos_id=eos_id, registry=registry,
                            service_name="bench_generation")
    log("[serving-bench] GenerationService replay...")
    gen = _replay(
        wl, lambda req: svc.generate(req["prompt"], req["n"]),
        lambda row, req: row.shape[0] - req["prompt"].shape[0])

    p99_ratio = (round(gen["latency"]["p99"] / eng["latency"]["p99"], 4)
                 if eng["latency"]["p99"] else None)
    return {"engine": eng, "generation_service": gen,
            "p99_speedup": p99_ratio,
            "workload": {"requests": n_requests, "rate_hz": rate_hz,
                         "seed": seed, "max_slots": max_slots,
                         "max_batch": max_batch}}
