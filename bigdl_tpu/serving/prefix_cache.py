"""Host-side prefix index over retained KV-cache rows.

Real serving traffic is prefix-heavy: system prompts, few-shot
templates, and multi-turn conversations share long identical prompt
heads. Recomputing those heads through chunked prefill makes TTFT scale
with FULL prompt length no matter how redundant the work is — the same
redundant-hot-path sin BigDL's design (Dai et al., 2018, arxiv
1804.05839) existed to eliminate for data movement. ``PrefixCache`` is
the serving engine's fix: a **radix trie** over token-id prefixes whose
entries point at rows of a device-resident KV *pool*. A new request
whose prompt shares a cached prefix copies the pool row into its
staging slot (one jitted program) and chunk-prefills only the novel
tail — O(novel-suffix) TTFT instead of O(prompt).

This module is pure HOST bookkeeping: token keys, trie structure, LRU /
ref-count accounting, and pool-row allocation. The device copies
(pool→staging on a hit, slot→pool on donation) live in
``engine.ContinuousBatchingEngine``; correctness of reuse rests on KV
causality — the KV row at position ``i`` depends only on tokens ``0..i``
— so any entry sharing the first ``m`` tokens with a prompt yields
``m`` valid positions, even when the entry diverges afterwards
(partial match) or extends past the prompt (truncated match).

Eviction: entries are LRU-ordered; ``donate`` reclaims the
least-recently-used entry with ``refs == 0`` when every pool row is
occupied. An entry is pinned (``acquire``/``release``) for the lifetime
of any admission staging from it, so a row is never overwritten while a
copy consumer may still be in flight. The byte budget is enforced as a
row budget (``rows * row_bytes``) fixed at construction — compiled
shapes stay load-independent.

**Host tier** (``host_rows > 0``): instead of dropping the device-pool
LRU victim outright, eviction *demotes* it — the entry stays in the
trie, flips ``tier`` to ``"host"``, and parks its KV in a pinned host
buffer (one bulk device-to-host copy the ENGINE performs via
``pop_pending_demotion`` / ``complete_demotion`` before the pool row is
reused). The host tier has its own row budget and its own LRU; a
lookup landing on a host entry is the engine's cue to start an async
``device_put`` promotion that overlaps the request's queue wait, then
``allocate_row`` + ``promote`` flip the entry back to device residency
so the unchanged chunk-aligned reuse path consumes it. The total
retained prefix set thus scales with host RAM, not HBM — BigDL's
spill-to-block-manager memory hierarchy recast for KV. Every tier
transition (demote, host-evict, promote) bumps ``generation``, so the
stale-probe guard covers host rows exactly like device rows.

Thread contract: the engine's loop thread is the only mutator;
``stats()`` / ``snapshot()`` may be called from HTTP/debug threads (an
internal lock covers the races).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


class PrefixEntry:
    """One retained prefix: ``tokens`` (the exact token ids whose KV the
    pool row holds, positions ``0..length-1``), the pool ``row`` that
    holds them, and the LRU/ref-count bookkeeping. ``tier`` says where
    the KV currently lives: ``"device"`` (a pool row) or ``"host"``
    (``host_buf``, an engine-opaque pinned host copy of the row;
    ``row`` is ``-1`` while demoted so stale use fails loudly)."""

    __slots__ = ("tokens", "row", "refs", "last_used", "hits", "tier",
                 "host_buf", "pages")

    def __init__(self, tokens: np.ndarray, row: int, stamp: int):
        self.tokens = tokens
        self.row = row
        self.refs = 0
        self.last_used = stamp
        self.hits = 0
        self.tier = "device"
        self.host_buf = None
        #: paged mode (``serving.paging.PagedPrefixIndex``): the page-pool
        #: page ids holding this prefix's KV, in position order; ``row``
        #: stays ``-1`` so any dense-path use of a paged entry fails loudly
        self.pages: Tuple[int, ...] = ()

    @property
    def length(self) -> int:
        return int(self.tokens.shape[0])

    def __repr__(self):
        return (f"PrefixEntry(len={self.length}, row={self.row}, "
                f"tier={self.tier}, refs={self.refs}, "
                f"hits={self.hits})")


class _Node:
    """Radix-trie node: edge-compressed children keyed by first token;
    ``entry`` marks a retained prefix ending exactly here."""

    __slots__ = ("children", "entry")

    def __init__(self):
        # first_token -> (edge_tokens np.ndarray, child _Node)
        self.children: Dict[int, Tuple[np.ndarray, "_Node"]] = {}
        self.entry: Optional[PrefixEntry] = None


def _common_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.shape[0], b.shape[0])
    if n == 0:
        return 0
    neq = np.flatnonzero(a[:n] != b[:n])
    return int(neq[0]) if neq.size else n


class PrefixCache:
    """Radix-trie index over token-id prefixes → device KV pool rows.

    ``rows`` is the pool capacity (0 disables the cache entirely);
    ``row_bytes`` is the device footprint of one pool row across every
    layer's (k, v) buffers — ``capacity_bytes = rows * row_bytes`` is
    the configured byte budget, ``bytes_in_use`` the occupied part.

    The engine-facing flow per admission: ``lookup(prompt)`` → best
    ``(entry, matched)``; on a hit ``acquire(entry)`` pins it while the
    staged copy is consumed, ``release(entry)`` unpins. Per finished
    request: ``donate(tokens)`` returns the pool row to copy the slot's
    KV into (or None when covered / unevictable), possibly evicting an
    LRU ``refs == 0`` entry to make room.

    With ``host_rows > 0`` the evicted victim is DEMOTED instead of
    dropped: ``donate`` (or ``allocate_row``) parks it as a host-tier
    entry and records a pending demotion the engine must resolve —
    ``pop_pending_demotion()`` names the entry and the pool row still
    holding its KV, the engine bulk-copies that row to host, and
    ``complete_demotion(entry, host_buf)`` attaches the buffer (or
    drops the entry when the copy failed). The reverse move is
    ``allocate_row()`` + ``promote(entry, row)`` after the engine has
    ``device_put`` the host buffer back into the pool row.
    """

    def __init__(self, rows: int, row_bytes: int,
                 min_tokens: int = 1, token_bytes: float = 0.0,
                 devices: int = 1, host_rows: int = 0):
        if rows < 0:
            raise ValueError(f"rows must be >= 0, got {rows}")
        if host_rows < 0:
            raise ValueError(
                f"host_rows must be >= 0, got {host_rows}")
        if min_tokens < 1:
            raise ValueError(
                f"min_tokens must be >= 1, got {min_tokens}")
        if devices < 1:
            raise ValueError(f"devices must be >= 1, got {devices}")
        self.rows = rows
        self.row_bytes = int(row_bytes)
        #: devices the pool's rows are sharded across (the serving
        #: mesh size; 1 unsharded) — ``row_bytes`` stays the LOGICAL
        #: per-row footprint, ``stats()`` derives the per-device share
        #: one chip's HBM pays for the occupied rows
        self.devices = int(devices)
        #: prefixes shorter than this are never matched or donated —
        #: a few shared tokens are not worth a row or a copy dispatch
        self.min_tokens = min_tokens
        #: device KV bytes one cached token position occupies
        #: (row_bytes / cache_len — the engine passes it); the
        #: exchange rate behind the ``bytes_saved`` savings credit
        self.token_bytes = float(token_bytes)
        #: host-tier row budget (0 disables the tier: eviction drops)
        self.host_rows = int(host_rows) if rows > 0 else 0
        self._root = _Node()
        self._entries: List[PrefixEntry] = []
        self._host_entries: List[PrefixEntry] = []
        self._free_rows = list(range(rows))
        #: the one demotion ``donate``/``allocate_row`` may leave open:
        #: ``(entry, pool_row)`` — the engine MUST resolve it (bulk d2h
        #: copy of ``pool_row`` + ``complete_demotion``) before the row
        #: is overwritten by the copy the allocation was made for
        self._pending_demotion: Optional[
            Tuple[PrefixEntry, int]] = None
        self._stamp = 0
        self._lock = threading.Lock()
        #: bumped on every structural change (insert/evict/demote/
        #: promote/host-evict) — lets a caller validate a cached
        #: ``lookup`` result before acting on it (a stale entry may
        #: have been evicted and its row reused, or changed tier)
        self.generation = 0
        # cumulative flow (monotonic, for stats deltas)
        self.hits = 0
        self.misses = 0
        #: subset of ``hits`` served out of the host tier (the entry
        #: needed a promotion before its row was consumable)
        self.host_hits = 0
        self.reused_tokens = 0
        #: device KV bytes reuse avoided recomputing + rewriting —
        #: the cache's cumulative savings credit (reused positions x
        #: token_bytes), per-request shares ledgered by the engine's
        #: usage accounting
        self.bytes_saved = 0
        self.donations = 0
        self.evictions = 0
        # host-tier flow
        self.demotions = 0
        self.promotions = 0
        self.host_evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def capacity_bytes(self) -> int:
        return self.rows * self.row_bytes

    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return len(self._entries) * self.row_bytes

    @property
    def host_capacity_bytes(self) -> int:
        return self.host_rows * self.row_bytes

    @property
    def host_bytes_in_use(self) -> int:
        """Host RAM the demoted rows occupy (buffers actually attached
        — a demotion pending its d2h copy holds no host bytes yet)."""
        with self._lock:
            return sum(self.row_bytes for e in self._host_entries
                       if e.host_buf is not None)

    # ------------------------------------------------------------ match
    def lookup(self, prompt: np.ndarray
               ) -> Tuple[Optional[PrefixEntry], int]:
        """Best cached prefix for ``prompt``: walk the trie as deep as
        the prompt's tokens agree, then take the better of (a) the
        deepest entry ENDING on the walked path (a full-entry match —
        every one of its tokens is a prefix of the prompt) and (b) any
        entry in the subtree below the divergence point (a PARTIAL
        match: the entry shares exactly the walked depth, then
        diverges or extends — its KV is still valid for the shared
        head, by causality). Returns ``(entry, matched_tokens)`` with
        ``matched >= min_tokens``, else ``(None, 0)``.

        PURE: no counters move and no LRU stamp is touched — the
        engine uses ``lookup`` both to probe admissions and to SCORE
        queued candidates for prefix-aware ordering, and scoring must
        not pollute the hit-rate. The engine's admission decision
        lands via ``record_hit`` / ``record_miss``."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            best: Optional[PrefixEntry] = None
            best_len = 0

            def consider(cand: Optional[PrefixEntry], ln: int):
                nonlocal best, best_len
                if cand is not None and ln > best_len:
                    best, best_len = cand, ln

            node, depth, off = self._root, 0, prompt
            while True:
                if node.entry is not None:
                    consider(node.entry, node.entry.length)
                if off.shape[0] == 0:
                    # prompt exhausted AT a node: entries extending
                    # below all share the full walked depth
                    consider(self._mru_below(node), depth)
                    break
                nxt = node.children.get(int(off[0]))
                if nxt is None:
                    # no child continues the prompt, but every entry
                    # below this node still shares `depth` tokens
                    consider(self._mru_below(node), depth)
                    break
                edge, child = nxt
                m = _common_len(edge, off)
                depth += m
                if m < edge.shape[0]:
                    # diverged (or prompt exhausted) mid-edge: every
                    # entry below shares exactly `depth` tokens
                    consider(self._mru_below(child), depth)
                    break
                node, off = child, off[m:]
            if best is None or best_len < self.min_tokens:
                return None, 0
            return best, best_len

    def record_hit(self, entry: PrefixEntry, reused_tokens: int,
                   host: bool = False) -> None:
        """Commit an admission's hit: LRU touch, per-entry and global
        hit counts, and the chunk-aligned reused-token figure the
        engine actually skipped prefill for. ``host=True`` marks a hit
        the engine served via a host-tier promotion — the tier split
        behind the ``bigdl_serving_prefix_host_hits_total`` counter."""
        with self._lock:
            self._stamp += 1
            entry.last_used = self._stamp
            entry.hits += 1
            self.hits += 1
            if host:
                self.host_hits += 1
            self.reused_tokens += int(reused_tokens)
            self.bytes_saved += int(reused_tokens * self.token_bytes)

    def record_miss(self) -> None:
        with self._lock:
            self.misses += 1

    def _mru_below(self, node: _Node) -> Optional[PrefixEntry]:
        """Most-recently-used entry in ``node``'s subtree (entry count
        is bounded by pool rows, so the DFS is trivially cheap)."""
        best = node.entry
        for edge, child in node.children.values():
            c = self._mru_below(child)
            if c is not None and (best is None
                                  or c.last_used > best.last_used):
                best = c
        return best

    # -------------------------------------------------------- pin/unpin
    def acquire(self, entry: PrefixEntry) -> None:
        """Pin ``entry`` while an admission consumes its pool row — a
        pinned entry is never evicted, so the row cannot be overwritten
        under an in-flight copy consumer."""
        with self._lock:
            entry.refs += 1

    def release(self, entry: PrefixEntry) -> None:
        with self._lock:
            if entry.refs <= 0:
                raise RuntimeError(
                    f"release() without matching acquire(): {entry!r}")
            entry.refs -= 1

    def pin_covering(self, tokens: np.ndarray
                     ) -> Optional[PrefixEntry]:
        """Find an entry of which ``tokens`` is a (non-strict) prefix
        and PIN it (caller must ``release``); None when no such entry
        exists. The preemption path pins the entry it just donated so
        LRU pressure cannot evict — and the demote sweep cannot spill
        — the victim's KV before its automatic resume consumes it."""
        with self._lock:
            entry = self._covering_entry(
                np.asarray(tokens, np.int32))
            if entry is not None:
                entry.refs += 1
            return entry

    # --------------------------------------------------------- donation
    def donate(self, tokens: np.ndarray) -> Optional[int]:
        """Offer a finished request's cached tokens to the pool.
        Returns the pool row the caller must copy the KV into, or None
        when the donation is declined: too short, already covered by an
        existing entry (which gets an LRU touch instead), or no free
        row and every entry pinned. May evict (and reuse the row of)
        the LRU ``refs == 0`` entry — byte pressure resolves by
        recency, never by silently dropping pinned entries."""
        # own the key: np.asarray would ALIAS an int32 caller buffer,
        # and a client reusing one preallocated prompt array across
        # requests would then rewrite the trie key under an entry
        # whose pool row still holds the OLD tokens' KV — a silent
        # wrong-prefix hit later
        tokens = np.array(tokens, np.int32, copy=True)
        with self._lock:
            if self.rows == 0 or tokens.shape[0] < self.min_tokens:
                return None
            covered = self._covering_entry(tokens)
            if covered is not None:
                self._stamp += 1
                covered.last_used = self._stamp
                return None
            row = self._take_row()
            if row is None:
                return None
            self._stamp += 1
            self.generation += 1
            entry = PrefixEntry(tokens, row, self._stamp)
            self._insert(entry)
            self._entries.append(entry)
            self.donations += 1
            return row

    def _take_row(self) -> Optional[int]:
        """Claim a device pool row (lock held): a free row, else the
        LRU ``refs == 0`` device entry's — demoting the victim into
        the host tier when it has room (the entry stays in the trie,
        its d2h copy left pending for the engine), dropping it
        otherwise. Returns None when every entry is pinned."""
        if self._free_rows:
            return self._free_rows.pop()
        victim = self._lru_unpinned()
        if victim is None:
            return None
        row = victim.row
        self.evictions += 1
        if self.host_rows > 0 and self._make_host_room():
            # demote: same trie node, new tier; the engine owes the
            # bulk device->host copy of `row` before reusing it
            self._entries.remove(victim)
            victim.tier = "host"
            victim.row = -1
            victim.host_buf = None
            self._host_entries.append(victim)
            self._pending_demotion = (victim, row)
        else:
            self._remove(victim)
        return row

    def _make_host_room(self) -> bool:
        """Ensure the host tier can absorb one more entry (lock held),
        evicting host-LRU ``refs == 0`` entries past the budget.
        False when the tier is full of pinned entries — the demotion
        then degrades to a plain drop, never an over-budget spill."""
        while len(self._host_entries) >= self.host_rows:
            cand = [e for e in self._host_entries if e.refs == 0]
            if not cand:
                return False
            hv = min(cand, key=lambda e: e.last_used)
            self._host_entries.remove(hv)
            self._trie_remove(hv)
            hv.host_buf = None
            self.host_evictions += 1
            # a probe (or in-flight promotion) that captured `hv`
            # re-validates and resolves to a clean miss
            self.generation += 1
        return True

    # ------------------------------------------------- tier transitions
    def pop_pending_demotion(
            self) -> Optional[Tuple[PrefixEntry, int]]:
        """The demotion the last ``donate``/``allocate_row`` left open:
        ``(entry, pool_row)`` — ``pool_row`` still holds the demoted
        entry's KV and is about to be overwritten, so the caller must
        d2h-copy it NOW and then ``complete_demotion``. Clears the
        pending slot."""
        with self._lock:
            pend, self._pending_demotion = self._pending_demotion, None
            return pend

    def complete_demotion(self, entry: PrefixEntry,
                          host_buf) -> None:
        """Attach the bulk-copied host buffer to a demoted entry. A
        ``None`` buffer means the copy was not performed (transfer
        failed / tier raced away) — the entry is dropped so a later
        promotion can never read uninitialized host memory."""
        with self._lock:
            if host_buf is None:
                if entry in self._host_entries:
                    self._host_entries.remove(entry)
                    self._trie_remove(entry)
                    self.generation += 1
                return
            if entry not in self._host_entries:
                return  # host-evicted (or promoted) since the demote
            entry.host_buf = host_buf
            self.demotions += 1

    def allocate_row(self) -> Optional[int]:
        """Claim a device pool row for a promotion (free row, else
        evict-or-demote the device LRU — exactly ``donate``'s row
        discipline, without inserting anything). May leave a pending
        demotion the caller must resolve; bumps ``generation`` so any
        probe taken before the eviction re-validates."""
        with self._lock:
            if self.rows == 0:
                return None
            row = self._take_row()
            if row is not None:
                self.generation += 1
            return row

    def promote(self, entry: PrefixEntry, row: int) -> None:
        """Flip a host-tier entry back to device residency in pool row
        ``row`` (the caller has already ``device_put`` the host buffer
        into that row). Drops the host buffer, LRU-touches the entry,
        and bumps ``generation`` — probes that captured the entry as
        host-tier re-validate before acting."""
        with self._lock:
            if entry.tier != "host" or entry not in self._host_entries:
                raise RuntimeError(
                    f"promote() of a non-host entry: {entry!r}")
            self._host_entries.remove(entry)
            entry.tier = "device"
            entry.row = int(row)
            entry.host_buf = None
            self._entries.append(entry)
            self._stamp += 1
            entry.last_used = self._stamp
            self.promotions += 1
            self.generation += 1

    def release_row(self, row: int) -> None:
        """Return an ``allocate_row`` row unused (the promotion it was
        claimed for fell through after the claim)."""
        with self._lock:
            self._free_rows.append(int(row))

    def _covering_entry(self, tokens: np.ndarray
                        ) -> Optional[PrefixEntry]:
        """An existing entry of which ``tokens`` is a (non-strict)
        prefix — any future prompt matches it at least as deeply as it
        would match ``tokens``, so the donation adds nothing."""
        node, off = self._root, tokens
        while True:
            if off.shape[0] == 0:
                return self._mru_below(node)
            nxt = node.children.get(int(off[0]))
            if nxt is None:
                return None
            edge, child = nxt
            m = _common_len(edge, off)
            if m == off.shape[0]:
                return self._mru_below(child)
            if m < edge.shape[0]:
                return None
            node, off = child, off[m:]

    def _lru_unpinned(self) -> Optional[PrefixEntry]:
        cand = [e for e in self._entries if e.refs == 0]
        return min(cand, key=lambda e: e.last_used) if cand else None

    # ---------------------------------------------------- trie plumbing
    def _insert(self, entry: PrefixEntry) -> None:
        node, off = self._root, entry.tokens
        while off.shape[0] > 0:
            nxt = node.children.get(int(off[0]))
            if nxt is None:
                child = _Node()
                node.children[int(off[0])] = (off, child)
                child.entry = entry
                return
            edge, child = nxt
            m = _common_len(edge, off)
            if m < edge.shape[0]:
                # split the edge at the divergence point
                mid = _Node()
                node.children[int(off[0])] = (edge[:m], mid)
                mid.children[int(edge[m])] = (edge[m:], child)
                node, off = mid, off[m:]
            else:
                node, off = child, off[m:]
        node.entry = entry

    def _remove(self, entry: PrefixEntry) -> None:
        self._entries.remove(entry)
        self._trie_remove(entry)

    def _trie_remove(self, entry: PrefixEntry) -> None:
        # walk to the entry's node, clearing the marker; structural
        # merge of pass-through nodes is skipped — the trie is bounded
        # by rows * key-length and rebuilt nodes are reused by the next
        # insert along the same path
        node, off = self._root, entry.tokens
        path: List[Tuple[_Node, int]] = []
        while off.shape[0] > 0:
            nxt = node.children.get(int(off[0]))
            if nxt is None:
                return
            edge, child = nxt
            m = _common_len(edge, off)
            if m < edge.shape[0]:
                return
            path.append((node, int(off[0])))
            node, off = child, off[m:]
        if node.entry is entry:
            node.entry = None
        # prune now-empty leaf chains so the trie cannot grow without
        # bound across many donate/evict cycles
        while path:
            parent, first = path.pop()
            edge, child = parent.children[first]
            if child.entry is None and not child.children:
                del parent.children[first]
            else:
                break

    # --------------------------------------------------- memory account
    def register_memory_pool(self, name: str) -> str:
        """Register this cache's OCCUPIED pool bytes as a named device-
        memory pool (``observability.memory``) — the `/debug/memory`
        attribution line that separates "prefix KV actually retained"
        from the pool's fixed capacity (which the engine registers
        alongside). Weakly referenced: the registration never keeps the
        cache (or, transitively, its engine) alive. Returns the pool
        name (the unregistration token)."""
        from bigdl_tpu.observability import memory as obs_memory

        names = obs_memory.register_owned_pools(
            self, {name: lambda c: c.bytes_in_use})
        return names[0]

    def register_host_memory_pool(self, name: str) -> str:
        """Same attribution for the HOST tier: the pinned host-RAM
        bytes the demoted rows occupy, alongside the device pools in
        the one registry — ``/debug/memory`` answers "who owns the
        spill" exactly like "who owns the HBM"."""
        from bigdl_tpu.observability import memory as obs_memory

        names = obs_memory.register_owned_pools(
            self, {name: lambda c: c.host_bytes_in_use})
        return names[0]

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        """Operational snapshot: occupancy, byte budget, and cumulative
        hit/reuse/eviction flow (the engine's ``stats()['prefix_cache']``
        and ``/debug/requests`` both render this)."""
        with self._lock:
            looked = self.hits + self.misses
            host_bytes = sum(self.row_bytes for e in self._host_entries
                             if e.host_buf is not None)
            return {
                "entries": len(self._entries),
                "rows": self.rows,
                "bytes": len(self._entries) * self.row_bytes,
                "capacity_bytes": self.rows * self.row_bytes,
                "devices": self.devices,
                "bytes_per_device": (len(self._entries)
                                     * self.row_bytes) // self.devices,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / looked, 4) if looked else 0.0,
                "reused_tokens": self.reused_tokens,
                "bytes_saved": self.bytes_saved,
                "donations": self.donations,
                "evictions": self.evictions,
                # host tier
                "host_rows": self.host_rows,
                "host_entries": len(self._host_entries),
                "host_bytes": host_bytes,
                "host_capacity_bytes": self.host_rows * self.row_bytes,
                "host_hits": self.host_hits,
                "device_hits": self.hits - self.host_hits,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "host_evictions": self.host_evictions,
            }

    def snapshot(self) -> List[dict]:
        """Per-entry debug view, both tiers (LRU order, oldest
        first)."""
        with self._lock:
            return [{"length": e.length, "row": e.row, "tier": e.tier,
                     "refs": e.refs, "hits": e.hits,
                     "last_used": e.last_used}
                    for e in sorted(self._entries + self._host_entries,
                                    key=lambda e: e.last_used)]
