"""Continuous-batching decode engine over a slot-pooled KV cache.

The batch-at-a-time services (``GenerationService``'s micro-batcher,
≙ the reference's instance-queue in optim/PredictionService.scala) run
each batch TO COMPLETION: one long request strands the MXU and every
co-batched short request. This engine replaces request/response batch
dispatch with a persistent device-resident decode loop (the inference
analog of the RDMA paper's persistent dataflow, arxiv 1805.08430):

- ONE pooled KV cache of shape ``(max_slots, H_kv, cache_len, D)`` per
  layer lives on device for the engine's whole life. Every compiled
  program's shape depends only on ``max_slots`` / ``cache_len`` /
  ``prefill_rows`` / the prefix-pool row count — never on load — so
  steady state runs a FIXED executable set (decode step, ragged
  prefill chunk, row copy, first-token sample) no matter what traffic
  does.
- a dedicated loop thread runs one fused ``decode_step`` over ALL
  slots per iteration (rows at their own depths — the ragged per-row
  position vector path), so requests join and leave the batch at token
  granularity.
- admission happens MID-FLIGHT: queued requests prefill in fixed
  chunks into a ``prefill_rows``-wide staging cache under a
  per-iteration token budget (``PrefillPolicy``) — each prefill round
  advances EVERY staged admission by one chunk through one ragged
  dispatch (each row at its own offset), then finished stagings are
  scattered into free slots by a donated row copy. Decode never waits
  for more than one iteration's prefill budget.
- prompts are PREFIX-CACHED: a host-side radix trie
  (``prefix_cache.PrefixCache``) indexes retained KV pool rows by
  token-id prefix. An admission whose prompt shares a cached prefix
  copies the pool row into its staging row (one program) and
  chunk-prefills only the novel tail — O(novel-suffix) TTFT instead
  of O(prompt). Finished slots donate their KV back to the pool under
  an LRU/ref-count policy with a configurable byte budget.
- rows finish at their OWN eos/token budget and their slot frees
  immediately for the next queued request (eviction ≡ slot reuse; the
  stale KV is overwritten before it can ever be attended — decode
  writes position p before masking attention to ``<= p``).

Greedy output is token-identical to a lone ``model.generate`` call per
request — with the prefix cache COLD or WARM (tested): cached KV rows
are bitwise the values prefill would recompute (the reuse offset is
chunk-aligned, so chunk geometry matches; KV at position i depends
only on tokens 0..i), same per-row ragged decode step, same argmax
tie-breaking.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.scheduler import AdmissionQueue, PrefillPolicy
from bigdl_tpu.serving.streams import (
    EngineStopped, RequestCancelled, RequestHandle, RequestTimedOut,
)


class _Admission:
    """Host-side progress of one chunked prefill. Up to
    ``prefill_rows`` of these are in flight at once, each owning one
    staging-cache row and one reserved slot; every prefill round
    advances all of them together through one ragged dispatch."""

    __slots__ = ("handle", "slot", "row", "ids", "t0", "base", "tail",
                 "n_chunks", "next_chunk", "entry")

    def __init__(self, handle: RequestHandle, slot: int, row: int,
                 ids: np.ndarray, t0: int, base: int, n_chunks: int,
                 entry=None):
        self.handle = handle
        self.slot = slot          # reserved pool slot (insert target)
        self.row = row            # staging-cache row this prefill owns
        self.ids = ids            # (n_chunks * chunk,) right-padded TAIL
        self.t0 = t0              # full prompt length
        self.base = base          # chunk-aligned cached-prefix offset
        self.tail = t0 - base     # tokens actually prefilled
        self.n_chunks = n_chunks
        self.next_chunk = 0
        self.entry = entry        # pinned PrefixEntry on a hit, else None


class _SlotState:
    """Host-side view of one occupied KV slot."""

    __slots__ = ("handle", "pos", "last_token", "last_token_at",
                 "delivered")

    def __init__(self, handle: RequestHandle, pos: int, last_token: int,
                 now: float):
        self.handle = handle
        #: cache position the NEXT decode step writes (= prompt length
        #: + delivered - 1: the last sampled token's KV is not yet
        #: cached, exactly generate()'s host-loop invariant)
        self.pos = pos
        self.last_token = last_token
        self.last_token_at = now
        self.delivered = 1


def _compile_count(fn):
    """Compiled-signature count of one jitted wrapper, or None when
    this jax build lacks the private ``_cache_size`` probe."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class ContinuousBatchingEngine:
    """Token-granular continuous batching over ``TransformerLM``'s
    incremental-decoding API (``init_cache`` / ``prefill_chunk`` /
    ``decode_step``), with prefix-cached, batched multi-row prefill.

    ``submit()`` returns a ``RequestHandle`` immediately (bounded FCFS
    queue — ``QueueFull`` is the backpressure signal); the loop thread
    streams tokens into it as they decode. Sampling config is fixed per
    engine (it is part of the compiled program), exactly like
    ``GenerationService``; the default is greedy, whose output is
    token-identical to per-request ``model.generate``.

    PREFIX CACHE: on by default. ``prefix_cache_bytes`` sets the byte
    budget for the device-resident KV pool the cache retains (None =
    auto, two pool rows per slot; 0 disables the cache entirely —
    admission then always prefills the full prompt).
    ``prefix_cache_rows`` overrides the row count directly;
    ``prefix_min_tokens`` (default: one prefill chunk) is the floor
    under which a shared head is not worth a copy dispatch. Reuse is
    chunk-aligned, so matched lengths round down to a multiple of
    ``prefill_chunk``. ``admission_window > 1`` additionally lets the
    scheduler pop the queued request with the LONGEST cached prefix
    from the first ``admission_window`` candidates (FCFS on ties, with
    a hard starvation bound — see ``AdmissionQueue.pop_ready``).

    BATCHED PREFILL: ``prefill_rows`` widens the staging cache so that
    many queued admissions chunk-prefill TOGETHER through one ragged
    dispatch per round instead of one admission at a time.

    When to prefer this over ``GenerationService``: mixed or long
    decode lengths under concurrent load (no head-of-line blocking on
    batch completion, slots recycle per token), streaming clients
    (tokens surface per iteration, not per finished batch), and
    prefix-heavy traffic (system prompts, few-shot templates,
    multi-turn) — TTFT scales with the NOVEL suffix, not the prompt.

    Every lifecycle transition (submitted → queued → admitted [+
    ``prefix_hit``] → each prefill chunk → first token → per-token
    decode → finished / cancelled / timed-out / stopped / crashed)
    lands in the flight recorder under the handle's ``request_id``;
    ``debug_requests()`` feeds ``GET /debug/requests``, ``healthz()``
    feeds the liveness probe (503 once the loop crashes), and a loop
    crash writes a postmortem JSON (``postmortem_path`` /
    ``$BIGDL_POSTMORTEM_PATH``, default ``bigdl_postmortem.json``)
    before failing the handles.

    RESOURCE OBSERVABILITY: the engine registers its persistent device
    buffers (KV slot pool, prefill staging, prefix pool + occupied
    prefix bytes, params) as named memory pools
    (``observability.memory.register_pool``) so ``/debug/memory``
    attributes HBM by owner; a ``RecompileWatchdog`` samples the
    compile counter every iteration (post-warmup growth — a shape leak
    — raises the recompile-storm alert), and ``slo_objectives`` (a
    list of ``observability.SloObjective`` or kwargs dicts, bound to
    the ``ttft`` / ``inter_token`` / ``queue_wait`` histograms by
    their ``metric`` field) drives an ``SloWatchdog``. Active alerts
    surface in ``stats()["alerts"]`` and flip the ``/healthz`` body to
    ``status: degraded`` while staying HTTP 200.

    USAGE ACCOUNTING: every request is metered by a ``UsageLedger``
    (``observability.accounting``) under the ``tenant=`` it was
    submitted for — queue seconds, prefilled vs prefix-reused prompt
    tokens (and the KV bytes reuse saved), delivered tokens, KV
    byte-seconds held (staging/slot row bytes x residency), and
    device-seconds attributed pro-rata from every ragged prefill round
    and fused decode step across the rows each dispatch advanced.
    ``usage_tenants`` caps tenant-label cardinality (overflow folds
    into ``"other"``); ``usage_recent`` bounds the finished-record
    ring behind top-N queries. Surfaces: ``handle.usage()``,
    ``stats()["usage"]``, ``debug_usage()`` / ``GET /debug/usage``,
    ``request/usage_final`` recorder events, and the
    ``bigdl_serving_tenant_*`` counters. Pure host bookkeeping — the
    jit-compile gauge stays flat with accounting on.
    """

    def __init__(self, model, max_slots: int = 4,
                 max_len: Optional[int] = None, prefill_chunk: int = 16,
                 prefill_budget_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k=None, top_p=None, queue_capacity: int = 64,
                 seed: int = 0, registry=None,
                 service_name: str = "engine",
                 idle_wait_s: float = 0.5, recorder=None,
                 postmortem_path: Optional[str] = None,
                 recent_timelines: int = 256,
                 prefill_rows: int = 1,
                 prefix_cache_bytes: Optional[int] = None,
                 prefix_cache_rows: Optional[int] = None,
                 prefix_min_tokens: Optional[int] = None,
                 admission_window: int = 4,
                 slo_objectives=None,
                 usage_tenants: int = 32,
                 usage_recent: int = 256):
        from bigdl_tpu.models.transformer import _validate_sampling
        from bigdl_tpu.observability import serving_engine_instruments
        from bigdl_tpu.observability import memory as obs_memory
        from bigdl_tpu.observability.accounting import UsageLedger
        from bigdl_tpu.observability.events import default_recorder
        from bigdl_tpu.observability.watchdog import (
            RecompileWatchdog, SloObjective, SloWatchdog,
        )

        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if admission_window < 1:
            raise ValueError(
                f"admission_window must be >= 1, got {admission_window}")
        _validate_sampling(temperature > 0.0, top_k, top_p)
        model.evaluate()
        self.model = model
        self.max_slots = max_slots
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.idle_wait_s = idle_wait_s
        self.service_name = service_name
        self.admission_window = admission_window
        #: flight recorder fed by every lifecycle transition (captured
        #: at construction, like the instruments — swap the default
        #: BEFORE building the engine, or pass one explicitly)
        self._rec = recorder if recorder is not None \
            else default_recorder()
        self._registry = registry
        #: crash black-box destination; resolved at crash time
        #: ($BIGDL_POSTMORTEM_PATH, else ./bigdl_postmortem.json)
        self.postmortem_path = postmortem_path
        #: bounded ring of finished-request timeline summaries — the
        #: source for stats() percentiles and /debug/requests "recent".
        #: The lock covers append vs. snapshot: iterating a deque that
        #: another thread appends to raises RuntimeError in CPython,
        #: and /debug readers run on HTTP threads while the loop writes
        self._timelines: collections.deque = collections.deque(
            maxlen=recent_timelines)
        self._timelines_lock = threading.Lock()
        self._policy = PrefillPolicy(prefill_chunk, prefill_budget_tokens,
                                     prefill_rows)
        c = self._policy.chunk
        # the cache length rounds the serving window UP to a chunk
        # multiple (the last prefill chunk is padded, and forward_chunk's
        # caller contract is pos0 + chunk <= cache length); if that
        # overflows the model's own context, the window rounds DOWN
        # instead — admission then caps t0 + n at the reduced window.
        cap = min(max_len or model.max_len, model.max_len)
        cache_len = -(-cap // c) * c
        if cache_len > model.max_len:
            cache_len = (model.max_len // c) * c
            cap = cache_len
        if cache_len < c:
            raise ValueError(
                f"prefill_chunk {c} exceeds the usable context {cap}")
        self.max_len = cap
        self._cache_len = cache_len

        self._params = jax.tree.map(jnp.asarray, model.params_dict())
        self._buffers = jax.tree.map(jnp.asarray, model.buffers_dict())
        dtype = model.tok_embed.dtype
        # THE pooled cache: one persistent (max_slots, ...) buffer set,
        # donated through every step — updates are in-place for the
        # engine's whole life
        self._caches = model.init_cache(max_slots, cache_len, dtype=dtype)
        # prefill_rows-wide staging cache for chunked prefill; rows are
        # reused across admissions (stale tail KV is position-masked,
        # never attended)
        self._staging = model.init_cache(self._policy.prefill_rows,
                                         cache_len, dtype=dtype)
        # prefix-cache KV pool: a third persistent buffer set holding
        # the retained prefixes, plus its host-side radix-trie index.
        # The byte budget is enforced as a row budget fixed here, so
        # every compiled shape stays load-independent.
        row_bytes = sum(int(leaf.nbytes) // max_slots
                        for leaf in jax.tree.leaves(self._caches))
        self._row_bytes = row_bytes
        #: device KV bytes one cached token position costs — the
        #: exchange rate prefix-reuse savings are credited at
        self._token_bytes = row_bytes / cache_len
        if prefix_cache_rows is not None:
            pool_rows = max(0, int(prefix_cache_rows))
        elif prefix_cache_bytes is None:
            pool_rows = 2 * max_slots
        else:
            pool_rows = max(0, int(prefix_cache_bytes) // row_bytes)
        if pool_rows > 0:
            self._pool = model.init_cache(pool_rows, cache_len,
                                          dtype=dtype)
            self._prefix = PrefixCache(
                pool_rows, row_bytes,
                min_tokens=(prefix_min_tokens
                            if prefix_min_tokens is not None else c),
                token_bytes=self._token_bytes)
        else:
            self._pool = None
            self._prefix = None
        self._prefix_evictions_seen = 0
        #: host-side prompt-token tally actually prefilled by THIS
        #: engine (the reused-fraction denominator — per-instance
        #: exact, unlike the shared-label registry counter)
        self._prefilled_tokens = 0
        #: programs that have run at least once — the jit_compiles
        #: fallback when jax's _cache_size probe is unavailable
        self._warm = set()
        self._build_fns()

        self._ins = serving_engine_instruments(service_name, registry)
        #: per-request / per-tenant usage meter: queue wait, prefill
        #: vs prefix-reused tokens, delivered tokens, KV byte-seconds
        #: held, and device-seconds attributed pro-rata per dispatch.
        #: Pure host bookkeeping — zero device programs, so the
        #: jit-compile gauge stays flat with accounting on.
        self._usage = UsageLedger(
            service=service_name, registry=registry, recorder=self._rec,
            instruments=self._ins, max_tenants=usage_tenants,
            recent=usage_recent, slot_row_bytes=row_bytes,
            staging_row_bytes=row_bytes, token_bytes=self._token_bytes)
        self._queue = AdmissionQueue(
            queue_capacity, recorder=self._rec,
            wait_histogram=self._ins.queue_wait_seconds)
        self._slots: List[Optional[_SlotState]] = [None] * max_slots
        self._adms: List[_Admission] = []
        self._key = jax.random.PRNGKey(seed)
        self._zero_key = jax.random.PRNGKey(0)

        self._ins.slots.set(max_slots, force=True)

        # ---- resource observability -----------------------------------
        # per-pool HBM attribution: every persistent device buffer set
        # this engine owns, registered under weakrefs (the monitor must
        # never keep a dead engine's KV pools alive). Names are keyed
        # by service_name; a same-named successor engine takes them over.
        pools = {
            f"serving/{service_name}/kv_slots":
                lambda e: obs_memory.tree_bytes(e._caches),
            f"serving/{service_name}/prefill_staging":
                lambda e: obs_memory.tree_bytes(e._staging),
            f"serving/{service_name}/params":
                lambda e: obs_memory.tree_bytes(e._params),
        }
        if self._pool is not None:
            pools[f"serving/{service_name}/prefix_pool"] = \
                lambda e: obs_memory.tree_bytes(e._pool)
        self._memory_pools = obs_memory.register_owned_pools(self, pools)
        if self._prefix is not None:
            self._memory_pools.append(self._prefix.register_memory_pool(
                f"serving/{service_name}/prefix_kv_in_use"))

        # watchdogs, sampled once per loop iteration: compiles that keep
        # growing after warmup break the engine's shape-stability
        # contract (storm alert); SLO objectives burn against the TTFT /
        # inter-token / queue-wait histograms. Alerts surface through
        # stats()["alerts"] and a degraded (but 200) /healthz body.
        self._recompile_wd = RecompileWatchdog(
            self._compile_total, service=service_name,
            registry=registry, recorder=self._rec)
        self._slo_wd = SloWatchdog(service=service_name,
                                   registry=registry, recorder=self._rec)
        slo_children = {"ttft": self._ins.ttft_seconds,
                        "inter_token": self._ins.inter_token_seconds,
                        "queue_wait": self._ins.queue_wait_seconds}
        for obj in (slo_objectives or ()):
            if isinstance(obj, dict):
                obj = SloObjective(**obj)
            if obj.metric not in slo_children:
                raise ValueError(
                    f"SloObjective {obj.name!r} names unknown engine "
                    f"metric {obj.metric!r}; expected one of "
                    f"{sorted(slo_children)}")
            self._slo_wd.watch(obj, slo_children[obj.metric])
        # stats() reports the DELTA since construction (the same
        # registry-façade convention as OccupancyStats): two engines
        # sharing a service_name share the series, so each instance
        # snapshots its own baseline
        self._stats_base = {k: self._counter(k).get()
                            for k in ("admitted", "finished", "evicted",
                                      "timed_out", "cancelled")}

        self._wake = threading.Condition()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()
        self._crashed: Optional[BaseException] = None

    # ------------------------------------------------- compiled programs
    def _build_fns(self):
        from bigdl_tpu.models.transformer import _filter_logits
        from bigdl_tpu.nn.module import bind

        model = self.model
        sampled = self.temperature > 0.0
        top_k, top_p = self.top_k, self.top_p

        def step(p, bufs, tok, pos, caches, rng, temperature):
            # one fused decode over ALL slots: (S,) tokens at (S,)
            # per-row positions (free slots ride along at pos 0 — their
            # junk write is overwritten by the next admission's insert)
            with bind(model, p, bufs, False, None):
                logits, caches = model.decode_step(tok, pos, caches)
            if sampled:
                nxt = jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k, top_p),
                    axis=-1).astype(jnp.int32)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, caches

        def chunk(p, bufs, ids, caches, pos0, last_idx):
            # one RAGGED prefill round over the whole staging cache:
            # row r writes its chunk at its own traced offset pos0[r]
            # (rows without an active admission ride along at offset 0
            # — their junk write lands in their own idle row and is
            # overwritten by that row's next occupant before it can
            # ever be attended); last_idx gathers each row's true last
            # prompt position's logits (the final chunk is
            # right-padded, so "last position of the chunk" would be a
            # pad)
            with bind(model, p, bufs, False, None):
                return model.prefill_chunk_at(ids, caches, pos0,
                                              last_idx)

        def copy_row(dst, src, dst_row, src_row):
            # copy row src_row of cache-tree src into row dst_row of
            # cache-tree dst (dst donated — in place for the engine's
            # life). ONE program, three compiled signatures, all
            # load-independent: staging→pool-slot insert, prefix-pool→
            # staging on a hit, pool-slot→prefix-pool on donation.
            return jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(
                    d,
                    jax.lax.dynamic_slice(
                        s, (src_row,) + (0,) * (s.ndim - 1),
                        (1,) + s.shape[1:]).astype(d.dtype),
                    (dst_row,) + (jnp.int32(0),) * (d.ndim - 1)),
                dst, src)

        def sample0(logits, rng, temperature):
            if sampled:
                return jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k, top_p),
                    axis=-1).astype(jnp.int32)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._step_jit = jax.jit(step, donate_argnums=(4,))
        self._chunk_jit = jax.jit(chunk, donate_argnums=(3,))
        self._copy_row_jit = jax.jit(copy_row, donate_argnums=(0,))
        self._sample0_jit = jax.jit(sample0)
        # warm the copy signatures NOW (zero rows copied onto zero rows
        # — harmless): the insert/stage/donate copies first fire at a
        # request's FINISH or at the first cache hit, and a compile
        # there would show up as a post-warmup jit_compiles bump — the
        # exact flatness contract the gauge exists to police.
        z = jnp.int32(0)
        self._caches = self._copy_row_jit(self._caches, self._staging,
                                          z, z)
        self._warm.add("copy:insert")
        if self._pool is not None:
            self._staging = self._copy_row_jit(self._staging, self._pool,
                                               z, z)
            self._pool = self._copy_row_jit(self._pool, self._caches,
                                            z, z)
            self._warm.update(("copy:stage", "copy:donate"))

    def _compile_total(self) -> int:
        counts = [_compile_count(f) for f in
                  (self._step_jit, self._chunk_jit, self._copy_row_jit,
                   self._sample0_jit)]
        if all(c is None for c in counts):
            # _cache_size absent in this jax build: approximate with
            # the warmed-program count (each program compiles exactly
            # once — shapes are load-independent, which is exactly the
            # flatness contract the gauge exists to expose)
            return len(self._warm)
        return sum(c or 0 for c in counts)

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ContinuousBatchingEngine":
        """Start the loop thread (idempotent; ``submit`` auto-starts)."""
        with self._lifecycle:
            if self._crashed is not None:
                raise EngineStopped(
                    "engine loop crashed; construct a new engine"
                ) from self._crashed
            if self._thread is None or not self._thread.is_alive():
                self._stop_evt.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="serving-engine", daemon=True)
                self._thread.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Stop the loop thread. ``drain=True`` first waits (up to
        ``timeout``) for queued + running requests to finish; any
        request still unfinished when the loop halts fails with
        ``EngineStopped``."""
        if drain and self._thread is not None and self._thread.is_alive():
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while self._has_work():
                if self._crashed is not None or (
                        deadline is not None
                        and time.monotonic() > deadline):
                    break
                time.sleep(0.002)
        self._stop_evt.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # the loop is wedged inside a device dispatch: leave
                # its slot/admission state alone (mutating it under a
                # live loop would crash the loop on resume) — it will
                # observe _stop_evt and exit when the dispatch returns;
                # call stop() again then to fail the leftovers
                return
        err = EngineStopped("engine stopped before the request finished")
        for h in self._queue.drain():
            self._finish_handle(h, err, "stopped")
        for a in self._adms:
            if a.entry is not None:
                self._prefix.release(a.entry)
                a.entry = None
            self._finish_handle(a.handle, err, "stopped")
        self._adms = []
        for sid, st in enumerate(self._slots):
            if st is not None:
                self._finish_handle(st.handle, err, "stopped")
                self._slots[sid] = None

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop(drain=exc_type is None)

    def _has_work(self) -> bool:
        return (len(self._queue) > 0 or len(self._adms) > 0
                or any(s is not None for s in self._slots))

    # ---------------------------------------------------------- client
    def submit(self, prompt_ids, max_new_tokens: int,
               timeout_s: Optional[float] = None, block: bool = True,
               queue_timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Queue one request (1-D prompt). Returns its handle
        immediately; stream with ``handle.tokens()`` or block on
        ``handle.result()``. ``timeout_s`` is a wall deadline covering
        queue + prefill + decode (expiry raises ``RequestTimedOut`` from
        the handle — including while blocked on a full queue); a full
        admission queue blocks (``block=True``, up to
        ``queue_timeout_s``) or raises ``QueueFull``.

        ``tenant`` names the workload the request's usage is billed to
        (the usage ledger's attribution key and the
        ``bigdl_serving_tenant_*`` label; ``None`` bills to
        ``"default"``). The first ``usage_tenants`` distinct names get
        their own series; later new names fold into ``"other"`` — the
        cardinality cap that keeps the label space bounded no matter
        what clients send. ``handle.usage()`` returns the request's
        metered consumption."""
        if self._crashed is not None:
            raise EngineStopped("engine loop crashed") from self._crashed
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError("submit takes ONE request (1-D prompt), "
                             f"got shape {prompt.shape}")
        t0, n = prompt.shape[0], int(max_new_tokens)
        if n < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if t0 < 1 or t0 + n > self.max_len:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({n}) exceeds the "
                f"engine's serving window {self.max_len}")
        self.start()
        h = RequestHandle(prompt, n, timeout_s)
        h._usage = self._usage.begin(h.request_id, tenant, t0, n,
                                     submitted_at=h.submitted_at)
        h.tenant = h._usage.tenant
        self._rec.record("request/submitted", h.request_id,
                         service=self.service_name, prompt_tokens=t0,
                         max_new_tokens=n, tenant=h.tenant)
        try:
            self._queue.put(h, block=block, timeout=queue_timeout_s)
        except Exception as e:
            # close the ledger, then the timeline — a backpressure
            # rejection must not read as a request that vanished
            # mid-flight, and the outcome event stays the LAST event
            # of the request's recorded arc (same order as
            # _finish_handle)
            self._usage.finalize(h._usage, "rejected",
                                 time.monotonic())
            self._rec.record("request/rejected", h.request_id,
                             service=self.service_name,
                             error=type(e).__name__)
            if isinstance(e, RequestTimedOut):
                self._ins.timed_out_total.inc()
            raise
        with self._wake:
            self._wake.notify_all()
        # submit can race stop() or a loop crash: if the loop died
        # between our start() and the put (both paths drain the queue
        # from the dying side, so a put landing after that drain would
        # otherwise strand the handle forever), drain-and-fail now
        # rather than hand back a handle nobody will ever finish
        if self._crashed is not None or (
                self._stop_evt.is_set()
                and (self._thread is None
                     or not self._thread.is_alive())):
            err = EngineStopped("engine stopped while the request was "
                                "being submitted")
            if self._crashed is not None:
                err.__cause__ = self._crashed
            for dropped in self._queue.drain():
                self._finish_handle(dropped, err, "stopped")
            self._finish_handle(h, err, "stopped")
            raise err
        return h

    def _finish_handle(self, h: RequestHandle,
                       err: Optional[BaseException],
                       outcome: str) -> None:
        """Terminal bookkeeping for ONE request — recorder event,
        stream sentinel, finished-timeline ring entry. Every lifecycle
        exit (finished / cancelled / timed_out / stopped / crashed)
        funnels through here so the flight recorder and the stats()
        percentiles can never disagree with the handles. ``_finish``
        arbitrates racing finishers (a stopping submitter vs. the
        crashing loop) — only the winner records."""
        if not h._finish(err):
            return
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # the usage ledger's terminal funnel shares _finish's
            # arbitration: exactly one finalizer closes residencies,
            # bills the tenant, and records request/usage_final —
            # BEFORE the outcome event, which stays the last event of
            # every request's recorded timeline (tested contract)
            self._usage.finalize(rec, outcome, h.finished_at)
        self._rec.record("request/" + outcome, h.request_id,
                         service=self.service_name,
                         tokens=len(h._tokens),
                         tenant=getattr(h, "tenant", None))
        tl = h.timeline()
        tl["request_id"] = h.request_id
        tl["outcome"] = outcome
        tl["tenant"] = getattr(h, "tenant", None)
        with self._timelines_lock:
            self._timelines.append(tl)

    def _counter(self, key: str):
        return getattr(self._ins, key + "_total")

    def stats(self) -> dict:
        """Operational façade over the registry series (same pattern —
        and same shared-``service_name`` caveat — as the batch
        services' ``stats()``): flow counters are the delta since THIS
        engine was constructed. ``latency`` adds per-phase percentile
        summaries (queue wait / prefill / TTFT / decode / total,
        each ``{count, mean, p50, p90, p99}``) computed from the
        engine's recent finished-request timelines; ``prefix_cache``
        adds the cache's hit rate, reused-token fraction, and current
        byte occupancy (per-instance exact — the cache object belongs
        to this engine); ``usage`` adds the ledger's per-tenant
        attribution table and the engine goodput block (device
        seconds by kind, occupancy-weighted utilization, padding
        waste, tokens per device-second)."""
        out = {k: int(self._counter(k).get() - base)
               for k, base in self._stats_base.items()}
        out["active_slots"] = sum(s is not None for s in self._slots)
        out["queue_depth"] = len(self._queue)
        out["jit_compiles"] = self._compile_total()
        out["latency"] = self._latency_summary()
        out["prefix_cache"] = self._prefix_summary()
        out["usage"] = self._usage.summary()
        out["alerts"] = self.alerts()
        return out

    def alerts(self) -> List[dict]:
        """The active watchdog alerts (recompile storm, SLO burns) as
        plain dicts — empty while the engine is healthy. The same list
        rides in ``stats()["alerts"]`` and the ``/healthz`` body."""
        out = []
        storm = self._recompile_wd.alert()
        if storm is not None:
            out.append(storm)
        out.extend(self._slo_wd.alerts())
        return out

    def _prefix_summary(self) -> dict:
        if self._prefix is None:
            return {"enabled": False}
        ps = self._prefix.stats()
        prefilled = self._prefilled_tokens
        denom = ps["reused_tokens"] + prefilled
        return {
            "enabled": True,
            **ps,
            "prefilled_tokens": prefilled,
            "reused_fraction": (round(ps["reused_tokens"] / denom, 4)
                                if denom else 0.0),
        }

    def _latency_summary(self) -> dict:
        from bigdl_tpu.observability.events import percentile_summary

        with self._timelines_lock:
            snap = list(self._timelines)
        tls = [t for t in snap if t.get("outcome") == "finished"]
        return {phase: percentile_summary(
                    t[phase + "_s"] for t in tls)
                for phase in ("queue_wait", "prefill", "ttft",
                              "decode", "total")}

    def healthz(self) -> dict:
        """Liveness probe for ``MetricsHTTPServer(healthz=...)``: a
        status dict while the engine is serviceable, raising
        ``EngineStopped`` once the loop thread has crashed — the
        endpoint then flips to 503 instead of reporting a dead decode
        loop as healthy. While a watchdog alert is active the body
        carries ``status: degraded`` plus the alert list — still HTTP
        200 (the engine serves; 503 remains the crashed-loop signal),
        so orchestrators keep routing while operators see the fire."""
        if self._crashed is not None:
            raise EngineStopped(
                f"engine loop crashed: {self._crashed!r}"
            ) from self._crashed
        alerts = self.alerts()
        return {
            # always present: direct callers key on it, not only the
            # HTTP handler (which would merge in an "ok" of its own)
            "status": "degraded" if alerts else "ok",
            "engine": self.service_name,
            "loop_alive": bool(self._thread is not None
                               and self._thread.is_alive()),
            "active_slots": sum(s is not None for s in self._slots),
            "queue_depth": len(self._queue),
            "alerts": alerts,
        }

    def debug_requests(self) -> dict:
        """The ``/debug/requests`` payload: every in-flight request's
        id, phase, and progress, the recent finished timelines with
        their queue-wait/prefill/TTFT/decode breakdown (now including
        per-request ``prefix_tokens``), the percentile summary over
        them, and the prefix-cache occupancy/hit-rate block. Snapshot
        semantics — safe to call from an HTTP thread while the loop
        runs."""
        now = time.monotonic()
        in_flight = []
        for h in self._queue.snapshot():
            in_flight.append({
                "request_id": h.request_id, "state": "queued",
                "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
            })
        for adm in list(self._adms):
            h = adm.handle
            in_flight.append({
                "request_id": h.request_id, "state": "prefill",
                "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
                "chunks_done": adm.next_chunk,
                "chunks_total": adm.n_chunks,
                "staging_row": adm.row,
                "prefix_tokens": adm.base,
            })
        for sid, st in enumerate(list(self._slots)):
            if st is None:
                continue
            h = st.handle
            in_flight.append({
                "request_id": h.request_id, "state": "decoding",
                "slot": sid, "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
                "tokens_delivered": st.delivered,
            })
        with self._timelines_lock:
            recent = list(self._timelines)[-50:]
        return {"service": self.service_name,
                "in_flight": in_flight,
                "recent": recent,
                "latency": self._latency_summary(),
                "prefix_cache": self._prefix_summary(),
                "alerts": self.alerts()}

    def debug_usage(self, top_n: int = 10) -> dict:
        """The ``GET /debug/usage`` payload: the per-tenant usage
        table (tokens, queue seconds, device-seconds, KV
        byte-seconds, prefix savings), engine-wide totals, the
        goodput block, and the top-``top_n`` recently finished
        requests by attributed device-seconds. Snapshot semantics —
        safe from HTTP threads while the loop runs."""
        return {"service": self.service_name,
                **self._usage.summary(top_n=top_n)}

    # ------------------------------------------------------- loop body
    def _loop(self):
        from bigdl_tpu.observability import trace

        try:
            while not self._stop_evt.is_set():
                # idle engines BLOCK (submit/stop notify the condition;
                # idle_wait_s is only a lost-wakeup safety net) instead
                # of spinning no-op iterations that would burn CPU and
                # flood the tracer/iteration metrics. An empty engine
                # has no deadlines to sweep — queued deadlines imply
                # _has_work() and a live loop.
                with self._wake:
                    while (not self._stop_evt.is_set()
                           and not self._has_work()):
                        self._wake.wait(self.idle_wait_s)
                if self._stop_evt.is_set():
                    break
                with trace.span("serving/iteration",
                                histogram=self._ins.iteration_seconds):
                    self._iterate()
                self._ins.iterations_total.inc()
        except BaseException as e:  # donated buffers may be gone: crash
            self._crash(e)

    def _crash(self, e: BaseException) -> None:
        self._crashed = e
        self._rec.record("engine/crash", service=self.service_name,
                         error=repr(e))
        # capture the in-flight picture BEFORE failing the handles —
        # the postmortem must show what the engine was doing when it
        # died, not the already-cleaned-up aftermath
        try:
            states = self.debug_requests()["in_flight"]
        except Exception:
            states = []
        self._write_postmortem(e, states)
        err = EngineStopped(f"engine loop crashed: {e!r}")
        err.__cause__ = e
        for a in self._adms:
            if a.entry is not None:
                self._prefix.release(a.entry)
                a.entry = None
            self._finish_handle(a.handle, err, "crashed")
        self._adms = []
        for sid, st in enumerate(self._slots):
            if st is not None:
                self._finish_handle(st.handle, err, "crashed")
                self._slots[sid] = None
        for h in self._queue.drain():
            self._finish_handle(h, err, "crashed")

    def _write_postmortem(self, e: BaseException,
                          states: List[dict]) -> None:
        """Best-effort crash black box — the crash path must never
        raise (donated buffers are already gone; all that is left is
        to preserve the evidence)."""
        import os

        from bigdl_tpu.observability.postmortem import write_postmortem

        path = (self.postmortem_path
                or os.environ.get("BIGDL_POSTMORTEM_PATH")
                or "bigdl_postmortem.json")
        try:
            write_postmortem(
                path, error=e, requests=states, recorder=self._rec,
                registry=self._registry,
                context={"service": self.service_name,
                         "max_slots": self.max_slots,
                         "max_len": self.max_len,
                         "queue_depth": len(self._queue),
                         "stats": {k: int(self._counter(k).get() - b)
                                   for k, b in
                                   self._stats_base.items()}})
            print(f"[bigdl_tpu.serving] engine {self.service_name!r} "
                  f"crashed: {e!r}; postmortem -> {path}",
                  file=sys.stderr)
        except Exception as pe:
            print(f"[bigdl_tpu.serving] postmortem write failed: "
                  f"{pe!r} (crash: {e!r})", file=sys.stderr)

    def _iterate(self) -> bool:
        now = time.monotonic()
        worked = False

        # 1. running slots: cancellation + deadline eviction
        for sid, st in enumerate(self._slots):
            if st is None:
                continue
            h = st.handle
            if h.cancelled:
                self._release(sid, RequestCancelled(
                    f"cancelled after {st.delivered} tokens"),
                    "cancelled")
            elif h.deadline is not None and now > h.deadline:
                self._release(sid, RequestTimedOut(
                    f"deadline passed mid-decode after {st.delivered} "
                    "tokens (partial output in tokens_so_far())"),
                    "timed_out")
        # ... and the admissions in progress
        for a in list(self._adms):
            h = a.handle
            err = kind = None
            if h.cancelled:
                err, kind = RequestCancelled(
                    "cancelled during prefill"), "cancelled"
            elif h.deadline is not None and now > h.deadline:
                err, kind = RequestTimedOut(
                    "deadline passed during prefill"), "timed_out"
            if err is not None:
                self._abort_admission(a, err, kind)

        # 2. queued requests: mid-queue deadline/cancel sweep
        for h, err in self._queue.sweep(now):
            self._finish_dropped(h, err)

        # 3. admission: prefix-aware intake + batched chunked-prefill
        #    rounds under this iteration's budget — every round
        #    advances ALL staged admissions together through one
        #    ragged dispatch
        self._policy.begin_iteration()
        while True:
            self._fill_admissions(now)
            if not self._adms or not self._policy.take_chunk():
                break
            self._prefill_round()
            worked = True

        # 4. one fused decode step over every occupied slot
        active = [sid for sid, st in enumerate(self._slots)
                  if st is not None]
        if active:
            self._decode_all(active)
            worked = True

        # 5. load gauges + watchdog sampling (one probe read and one
        #    histogram snapshot per objective — iteration-rate cheap)
        ins = self._ins
        ins.active_slots.set(sum(s is not None for s in self._slots))
        ins.queue_depth.set(len(self._queue))
        ins.jit_compiles.set(self._compile_total())
        self._recompile_wd.sample()
        self._slo_wd.sample()
        return worked

    # ------------------------------------------------ admission stages
    def _free_slot(self) -> Optional[int]:
        # a slot is free when no running request occupies it AND no
        # in-flight admission has reserved it as its insert target
        reserved = {a.slot for a in self._adms}
        for sid, st in enumerate(self._slots):
            if st is None and sid not in reserved:
                return sid
        return None

    def _free_staging_row(self) -> Optional[int]:
        used = {a.row for a in self._adms}
        for r in range(self._policy.prefill_rows):
            if r not in used:
                return r
        return None

    def _fill_admissions(self, now: float) -> None:
        """Start new admissions until the staging cache is full, the
        slot pool is exhausted, or the queue runs dry. With a prefix
        cache and ``admission_window > 1``, the pop prefers the queued
        candidate with the longest cached prefix (bounded bypass —
        see AdmissionQueue.pop_ready)."""
        scorer = None
        if self._prefix is not None and self.admission_window > 1:
            c = self._policy.chunk

            def scorer(h):
                # score by the USABLE (capped, chunk-aligned) reuse —
                # exactly what _start_admission will skip — so a match
                # that alignment reduces to zero never bypasses the
                # FCFS head for nothing. The raw lookup is stamped on
                # the handle (generation-guarded) so the winner's
                # admission doesn't re-walk the trie.
                e, m = self._prefix.lookup(h.prompt)
                h._prefix_probe = (e, m, self._prefix.generation)
                return (min(m, h.prompt.shape[0] - 1) // c) * c
        while len(self._adms) < self._policy.prefill_rows:
            slot = self._free_slot()
            if slot is None:
                return
            row = self._free_staging_row()
            if row is None:
                return
            h, dropped = self._queue.pop_ready(
                now, scorer=scorer, window=self.admission_window)
            for hd, err in dropped:
                self._finish_dropped(hd, err)
            if h is None:
                return
            self._start_admission(h, slot, row)

    def _start_admission(self, h: RequestHandle, slot: int,
                         row: int) -> None:
        c = self._policy.chunk
        t0 = h.prompt.shape[0]
        base, entry = 0, None
        if self._prefix is not None:
            # reuse the pop_ready scorer's lookup when it is still
            # valid — the generation guard rejects probes that predate
            # any donation/eviction (a stale entry's pool row may
            # already hold different tokens' KV)
            probe = h.__dict__.pop("_prefix_probe", None)
            if probe is not None and probe[2] == self._prefix.generation:
                e, matched = probe[0], probe[1]
            else:
                e, matched = self._prefix.lookup(h.prompt)
            if e is not None:
                # cap at t0-1 (the last prompt position must be
                # COMPUTED — its logits seed the first token), then
                # chunk-align DOWN so the tail's chunk geometry — and
                # with it the numerics — matches a cold prefill's, and
                # the padded tail write can never overflow the cache
                base = (min(matched, t0 - 1) // c) * c
            if base > 0:
                entry = e
                self._prefix.record_hit(entry, base)
                self._prefix.acquire(entry)
                self._staging = self._copy_row_jit(
                    self._staging, self._pool, jnp.int32(row),
                    jnp.int32(entry.row))
                self._warm.add("copy:stage")
                self._ins.prefix_hits_total.inc()
                self._ins.prefix_reused_tokens_total.inc(base)
                self._rec.record("request/prefix_hit", h.request_id,
                                 service=self.service_name,
                                 matched_tokens=base,
                                 raw_matched_tokens=matched,
                                 tail_tokens=t0 - base)
            else:
                self._prefix.record_miss()
                self._ins.prefix_misses_total.inc()
        tail = t0 - base
        n_chunks = self._policy.n_chunks(tail)
        ids = np.zeros((n_chunks * c,), np.int32)  # right-pad final chunk
        ids[:tail] = h.prompt[base:]
        self._adms.append(_Admission(h, slot, row, ids, t0, base,
                                     n_chunks, entry))
        h.prefix_tokens = base
        h.admitted_at = time.monotonic()
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # queue wait closes, staging-row residency opens, and the
            # chunk-aligned reuse is credited as tokens + bytes saved
            self._usage.admitted(rec, h.admitted_at,
                                 reused_tokens=base)
        self._rec.record("request/admitted", h.request_id,
                         service=self.service_name, slot=slot,
                         staging_row=row, n_chunks=n_chunks,
                         prefix_tokens=base)
        self._ins.admitted_total.inc()

    def _prefill_round(self) -> None:
        """Advance EVERY in-flight admission by one chunk through one
        ragged dispatch, then complete the ones whose prompt is fully
        staged (slot insert + first-token sample)."""
        c = self._policy.chunk
        rows = self._policy.prefill_rows
        ids = np.zeros((rows, c), np.int32)
        pos0 = np.zeros((rows,), np.int32)
        last = np.full((rows,), c - 1, np.int32)
        finals: List[_Admission] = []
        for a in self._adms:
            k = a.next_chunk
            ids[a.row] = a.ids[k * c:(k + 1) * c]
            pos0[a.row] = a.base + k * c
            if k == a.n_chunks - 1:
                # the true last prompt position within the final chunk
                # — pad positions behind it are written but never
                # attended (causal mask within the chunk; decode
                # overwrites position p before attending <= p)
                last[a.row] = a.tail - 1 - k * c
                finals.append(a)
        # a COLD dispatch's wall is dominated by its one-time compile —
        # billing that to whichever tenants happen to arrive first
        # would poison their device-seconds forever, so warmup rounds
        # are excluded from attribution AND the busy tally (both sides
        # skip: conservation holds, goodput reads the warm engine)
        was_warm = "chunk" in self._warm and (
            not finals or "sample0" in self._warm)
        t_disp = time.monotonic()
        logits, self._staging = self._chunk_jit(
            self._params, self._buffers, jnp.asarray(ids), self._staging,
            jnp.asarray(pos0), jnp.asarray(last))
        self._warm.add("chunk")
        toks = None
        if finals:
            # the host-side transfer blocks on the sampled tokens —
            # which depend on the chunk's logits, so the measured wall
            # covers the real dispatch on rounds that finish a prompt
            toks = np.asarray(self._sample0_jit(
                logits, self._next_key(), self._temp()))
            self._warm.add("sample0")
        wall = time.monotonic() - t_disp
        # pro-rata attribution by REAL tokens each row advanced (the
        # padded tail of a final chunk is engine overhead, not billable
        # work); weights sum to 1 — the round's full wall is conserved
        done_by = [(a, min(c, a.tail - a.next_chunk * c))
                   for a in self._adms]
        if was_warm:
            total_done = sum(d for _, d in done_by) or 1
            self._usage.charge_dispatch(
                "prefill", wall,
                [(getattr(a.handle, "_usage", None), d / total_done)
                 for a, d in done_by],
                rows_advanced=len(self._adms),
                capacity_rows=self._policy.prefill_rows)
        for a, done in done_by:
            k = a.next_chunk
            self._prefilled_tokens += done
            self._ins.prefill_tokens_total.inc(done)
            rec = getattr(a.handle, "_usage", None)
            if rec is not None:
                self._usage.add_prefill(rec, done)
            self._rec.record("request/prefill_chunk",
                             a.handle.request_id,
                             service=self.service_name, chunk=k,
                             n_chunks=a.n_chunks, tokens=done)
            a.next_chunk += 1
        for a in finals:
            self._complete_admission(a, int(toks[a.row]))

    def _complete_admission(self, a: _Admission, tok: int) -> None:
        # prompt fully staged: scatter the staging row into the
        # reserved pool slot, release the prefix pin (the staged copy
        # is now independent of the pool row), deliver the first token
        self._caches = self._copy_row_jit(
            self._caches, self._staging, jnp.int32(a.slot),
            jnp.int32(a.row))
        self._warm.add("copy:insert")
        if a.entry is not None:
            self._prefix.release(a.entry)
            a.entry = None
        self._adms.remove(a)
        now = time.monotonic()
        h = a.handle
        h._deliver(tok, now)
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # staging residency closes into kv_byte_seconds, the slot
            # row's opens; the first token counts as delivered
            self._usage.slot_acquired(rec, now)
            self._usage.delivered(rec, 1)
        self._ins.ttft_seconds.observe(now - h.submitted_at)
        self._rec.record("request/first_token", h.request_id,
                         service=self.service_name, token=tok,
                         ttft_s=now - h.submitted_at)
        if (self.eos_id is not None and tok == self.eos_id) \
                or h.max_new_tokens == 1:
            # instant finisher: the slot row still holds the full
            # prompt's KV — donate it before the slot identity is lost
            self._maybe_donate(a.slot, h.prompt, h.request_id)
            self._finish_handle(h, None, "finished")
            self._ins.finished_total.inc()
            return
        self._slots[a.slot] = _SlotState(h, a.t0, tok, now)

    def _abort_admission(self, a: _Admission, err: Exception,
                         kind: str) -> None:
        if a.entry is not None:
            self._prefix.release(a.entry)
            a.entry = None
        self._adms.remove(a)
        self._count_drop(kind)
        self._finish_handle(a.handle, err, kind)

    # --------------------------------------------------- prefix donation
    def _maybe_donate(self, sid: int, tokens: np.ndarray,
                      request_id: str) -> None:
        """Offer a finishing slot's KV to the prefix pool. ``tokens``
        are exactly the ids whose KV the slot holds (positions
        ``0..len-1``); the index decides (covered / LRU-evict /
        decline) and the accepted row is filled by one donated copy."""
        if self._prefix is None:
            return
        row = self._prefix.donate(tokens)
        if row is not None:
            self._pool = self._copy_row_jit(
                self._pool, self._caches, jnp.int32(row),
                jnp.int32(sid))
            self._warm.add("copy:donate")
            self._rec.record("request/prefix_donated", request_id,
                             service=self.service_name,
                             tokens=int(tokens.shape[0]), pool_row=row)
        ev = self._prefix.evictions
        if ev > self._prefix_evictions_seen:
            self._ins.prefix_evicted_total.inc(
                ev - self._prefix_evictions_seen)
            self._prefix_evictions_seen = ev
        self._ins.prefix_cache_bytes.set(self._prefix.bytes_in_use)
        self._ins.prefix_cache_entries.set(len(self._prefix))

    # --------------------------------------------------------- decode
    def _decode_all(self, active: List[int]) -> None:
        tok = np.zeros((self.max_slots,), np.int32)
        pos = np.zeros((self.max_slots,), np.int32)
        for sid in active:
            st = self._slots[sid]
            tok[sid] = st.last_token
            pos[sid] = st.pos
        was_warm = "step" in self._warm   # cold = compile in the wall
        t_disp = time.monotonic()
        nxt, self._caches = self._step_jit(
            self._params, self._buffers, jnp.asarray(tok),
            jnp.asarray(pos), self._caches, self._next_key(),
            self._temp())
        self._warm.add("step")
        nxt_np = np.asarray(nxt)   # blocks on the fused step
        now = time.monotonic()
        # every advanced row got exactly one token: the step's wall
        # splits evenly across them (idle slots ride along as padding
        # — their share is the dispatch's padding waste, not billed).
        # Warmup steps are excluded like cold prefill rounds above.
        if was_warm:
            w = 1.0 / len(active)
            self._usage.charge_dispatch(
                "decode", now - t_disp,
                [(getattr(self._slots[sid].handle, "_usage", None), w)
                 for sid in active],
                rows_advanced=len(active), capacity_rows=self.max_slots)
        for sid in active:
            st = self._slots[sid]
            t = int(nxt_np[sid])
            st.delivered += 1
            st.pos += 1
            st.last_token = t
            self._ins.inter_token_seconds.observe(now - st.last_token_at)
            st.last_token_at = now
            h = st.handle
            h._deliver(t, now)
            rec = getattr(h, "_usage", None)
            if rec is not None:
                self._usage.delivered(rec, 1)
            self._ins.decode_tokens_total.inc()
            self._rec.record("request/decode_token", h.request_id,
                             service=self.service_name, slot=sid,
                             token=t, n=st.delivered)
            if (self.eos_id is not None and t == self.eos_id) \
                    or st.delivered >= h.max_new_tokens:
                self._release(sid, None, "finished")

    # ------------------------------------------------------- plumbing
    def _temp(self):
        return jnp.float32(self.temperature
                           if self.temperature > 0.0 else 1.0)

    def _next_key(self):
        if self.temperature <= 0.0:
            return self._zero_key  # greedy: the key is never consumed
        self._key, sub = jax.random.split(self._key)
        return sub

    def _release(self, sid: int, error: Optional[Exception],
                 reason: str) -> None:
        st = self._slots[sid]
        # donate BEFORE the slot is surrendered: the slot's KV covers
        # positions [0, st.pos) — the prompt plus every delivered token
        # except the last (whose KV the next decode step would have
        # written), so the donated key is exactly prompt +
        # generated[:-1]. Cancelled/timed-out slots donate too: their
        # KV satisfies the same invariant, and a timed-out long prompt
        # is exactly the request most likely to be RETRIED — the retry
        # then pays O(novel-suffix), not a second full prefill.
        tokens = np.concatenate(
            [st.handle.prompt,
             np.asarray(st.handle._tokens[:-1], np.int32)])
        self._maybe_donate(sid, tokens, st.handle.request_id)
        self._slots[sid] = None
        self._ins.evicted_total.inc()
        if reason == "finished":
            self._ins.finished_total.inc()
        else:
            self._count_drop(reason)
        self._finish_handle(st.handle, error, reason)

    def _finish_dropped(self, h: RequestHandle, err: Exception) -> None:
        kind = ("cancelled" if isinstance(err, RequestCancelled)
                else "timed_out")
        self._count_drop(kind)
        self._finish_handle(h, err, kind)

    def _count_drop(self, kind: str) -> None:
        (self._ins.cancelled_total if kind == "cancelled"
         else self._ins.timed_out_total).inc()
