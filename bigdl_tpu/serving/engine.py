"""Continuous-batching decode engine over a slot-pooled KV cache.

The batch-at-a-time services (``GenerationService``'s micro-batcher,
≙ the reference's instance-queue in optim/PredictionService.scala) run
each batch TO COMPLETION: one long request strands the MXU and every
co-batched short request. This engine replaces request/response batch
dispatch with a persistent device-resident decode loop (the inference
analog of the RDMA paper's persistent dataflow, arxiv 1805.08430):

- ONE pooled KV cache of shape ``(max_slots, H_kv, cache_len, D)`` per
  layer lives on device for the engine's whole life. Every compiled
  program's shape depends only on ``max_slots`` / ``cache_len`` /
  ``prefill_rows`` / the prefix-pool row count — never on load — so
  steady state runs a FIXED executable set (decode step, ragged
  prefill chunk, row copy, first-token sample) no matter what traffic
  does.
- a dedicated loop thread runs one fused ``decode_step`` over ALL
  slots per iteration (rows at their own depths — the ragged per-row
  position vector path), so requests join and leave the batch at token
  granularity.
- admission happens MID-FLIGHT: queued requests prefill in fixed
  chunks into a ``prefill_rows``-wide staging cache under a
  per-iteration token budget (``PrefillPolicy``) — each prefill round
  advances EVERY staged admission by one chunk through one ragged
  dispatch (each row at its own offset), then finished stagings are
  scattered into free slots by a donated row copy. Decode never waits
  for more than one iteration's prefill budget.
- prompts are PREFIX-CACHED: a host-side radix trie
  (``prefix_cache.PrefixCache``) indexes retained KV pool rows by
  token-id prefix. An admission whose prompt shares a cached prefix
  copies the pool row into its staging row (one program) and
  chunk-prefills only the novel tail — O(novel-suffix) TTFT instead
  of O(prompt). Finished slots donate their KV back to the pool under
  an LRU/ref-count policy with a configurable byte budget.
- rows finish at their OWN eos/token budget and their slot frees
  immediately for the next queued request (eviction ≡ slot reuse; the
  stale KV is overwritten before it can ever be attended — decode
  writes position p before masking attention to ``<= p``).
- the engine optionally runs TENSOR-PARALLEL (``mesh=``): params are
  Megatron-sharded over the mesh's model axis
  (``parallel.tp.transformer_tp_rules`` / ``shard_params``), all four
  device pools shard their KV-heads dimension along the same axis,
  and every compiled program above becomes ONE SPMD dispatch with
  jit-inserted collectives — models larger than one device's HBM
  serve at full interconnect bandwidth while the host-side control
  flow stays mesh-oblivious.
- decode is optionally SPECULATIVE (``draft=``): per iteration a
  cheaper draft model proposes ``spec_gamma`` tokens for ALL live
  slots in one ``lax.scan`` dispatch (its own slot-pooled KV cache,
  allocated/recycled in lockstep with the target's), the target
  scores every proposal through ONE ragged ``verify_chunk`` dispatch,
  and each row accepts a VARIABLE-length extension (1..gamma+1
  tokens) into its slot — per-row position advance, per-row
  eos/budget truncation mid-extension, streaming handles emitting the
  burst in order. Compiled shapes depend only on
  ``(max_slots, spec_gamma)``, so the jit gauge stays flat.

Greedy output is token-identical to a lone ``model.generate`` call per
request — with the prefix cache COLD or WARM, and with speculation ON
or OFF (tested): cached KV rows are bitwise the values prefill would
recompute (the reuse offset is chunk-aligned, so chunk geometry
matches; KV at position i depends only on tokens 0..i), same per-row
ragged decode step, same argmax tie-breaking; a draft only ever
changes HOW MANY target dispatches an output costs, never the output
(rejected proposals are replaced by the target's own argmax).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.observability.costmodel import (
    DispatchCostModel, LoopPhaseAccumulator, device_peaks, program_cost,
)
from bigdl_tpu.observability.timeseries import (
    TimeSeriesSampler, render_dashboard,
)
from bigdl_tpu.serving.paging import (
    BlockTable, PagedPrefixIndex, PagePool,
)
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.scheduler import (
    AdmissionQueue, PrefillPolicy, SpeculationPolicy, TokenBucket,
    page_fit_score, pages_needed,
)
from bigdl_tpu.serving.streams import (
    PRIORITY_RANK, EngineDraining, EngineStopped, RequestCancelled,
    RequestHandle, RequestRateLimited, RequestShed, RequestTimedOut,
)


class _Admission:
    """Host-side progress of one chunked prefill. Up to
    ``prefill_rows`` of these are in flight at once, each owning one
    staging-cache row and one reserved slot; every prefill round
    advances all of them together through one ragged dispatch."""

    __slots__ = ("handle", "slot", "row", "ids", "t0", "base", "tail",
                 "n_chunks", "next_chunk", "entry", "d_ids",
                 "d_n_chunks", "d_next_chunk", "table", "d_table")

    def __init__(self, handle: RequestHandle, slot: int, row: int,
                 ids: np.ndarray, t0: int, base: int, n_chunks: int,
                 entry=None, d_ids=None, d_n_chunks: int = 0):
        self.handle = handle
        self.slot = slot          # reserved pool slot (insert target)
        self.row = row            # staging-cache row this prefill owns
        self.ids = ids            # (n_chunks * chunk,) right-padded TAIL
        self.t0 = t0              # full prompt length
        self.base = base          # chunk-aligned cached-prefix offset
        self.tail = t0 - base     # tokens actually prefilled
        self.n_chunks = n_chunks
        self.next_chunk = 0
        self.entry = entry        # pinned PrefixEntry on a hit, else None
        #: speculative decoding: the DRAFT model prefills the FULL
        #: prompt into its own staging row (a prefix-cache hit skips
        #: target work only — the draft pool holds no reusable prefix),
        #: so its cursor can lag the target's on a hit; the admission
        #: completes when BOTH caches hold the prompt
        self.d_ids = d_ids        # (d_n_chunks * chunk,) full prompt
        self.d_n_chunks = d_n_chunks
        self.d_next_chunk = 0
        #: paged mode: the BlockTables this admission writes through
        #: (full span reserved at admission; handed to the slot on
        #: completion, freed on abort). None on a dense engine.
        self.table: Optional[BlockTable] = None
        self.d_table: Optional[BlockTable] = None


class _SlotState:
    """Host-side view of one occupied KV slot."""

    __slots__ = ("handle", "pos", "last_token", "last_token_at",
                 "delivered")

    def __init__(self, handle: RequestHandle, pos: int, last_token: int,
                 now: float):
        self.handle = handle
        #: cache position the NEXT decode step writes (= prompt length
        #: + delivered - 1: the last sampled token's KV is not yet
        #: cached, exactly generate()'s host-loop invariant — preserved
        #: under VARIABLE advance too: a speculative round delivering m
        #: tokens moves pos by m, and the slot's KV covers [0, pos)
        #: either way, which is what donation relies on)
        self.pos = pos
        self.last_token = last_token
        self.last_token_at = now
        self.delivered = 1


def _compile_count(fn):
    """Compiled-signature count of one jitted wrapper, or None when
    this jax build lacks the private ``_cache_size`` probe."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class ContinuousBatchingEngine:
    """Token-granular continuous batching over ``TransformerLM``'s
    incremental-decoding API (``init_cache`` / ``prefill_chunk`` /
    ``decode_step``), with prefix-cached, batched multi-row prefill.

    ``submit()`` returns a ``RequestHandle`` immediately (bounded FCFS
    queue — ``QueueFull`` is the backpressure signal); the loop thread
    streams tokens into it as they decode. Sampling config is fixed per
    engine (it is part of the compiled program), exactly like
    ``GenerationService``; the default is greedy, whose output is
    token-identical to per-request ``model.generate``.

    PREFIX CACHE: on by default. ``prefix_cache_bytes`` sets the byte
    budget for the device-resident KV pool the cache retains (None =
    auto, two pool rows per slot; 0 disables the cache entirely —
    admission then always prefills the full prompt).
    ``prefix_cache_rows`` overrides the row count directly;
    ``prefix_min_tokens`` (default: one prefill chunk) is the floor
    under which a shared head is not worth a copy dispatch. Reuse is
    chunk-aligned, so matched lengths round down to a multiple of
    ``prefill_chunk``. ``admission_window > 1`` additionally lets the
    scheduler pop the queued request with the LONGEST cached prefix
    from the first ``admission_window`` candidates (FCFS on ties, with
    a hard starvation bound — see ``AdmissionQueue.pop_ready``).

    BATCHED PREFILL: ``prefill_rows`` widens the staging cache so that
    many queued admissions chunk-prefill TOGETHER through one ragged
    dispatch per round instead of one admission at a time.

    SPECULATIVE DECODING: pass ``draft=`` (a smaller ``TransformerLM``
    over the same vocabulary — ``nn.quantized.Quantizer.quantize(model)``
    builds the int8 clone PERF.md benchmarks) and each decode
    iteration becomes draft-propose/target-verify: the draft proposes
    ``spec_gamma`` tokens for ALL live slots in one ``lax.scan``
    dispatch (``_propose_fn``), the target scores every proposal in
    one ragged ``verify_chunk`` dispatch, and each row accepts its own
    1..gamma+1-token extension (matched proposals plus the target's
    correction/bonus token) — one target forward now yields several
    tokens wherever the draft agrees with the target. The draft owns a
    parallel slot pool + staging cache, allocated and recycled in
    LOCKSTEP with the target's; admission chunk-prefills the draft's
    row alongside the target's (the FULL prompt — a prefix-cache hit
    skips target work only, so on hits the target's final chunk
    replays idempotently while the draft catches up). Greedy output
    stays token-identical to the non-speculative engine (and to lone
    ``model.generate``); with ``temperature > 0`` the engine runs full
    speculative SAMPLING (accept min(1, p/q), residual on rejection —
    Leviathan et al. 2023), distributed exactly as the target's
    tempered softmax, though not bitwise the non-speculative stream
    (the key schedule differs); ``top_k``/``top_p`` are rejected with
    a draft (the acceptance identity needs the unfiltered
    distributions). Compiled shapes depend only on
    ``(max_slots, spec_gamma)`` — the jit gauge stays flat after
    warmup with speculation on (tested). Acceptance telemetry:
    ``stats()["speculation"]``, ``bigdl_serving_spec_*`` instruments,
    and per-burst ``request/decode_token`` events carrying
    ``accepted=``.

    TENSOR-PARALLEL SERVING: pass ``mesh=`` (a ``jax.sharding.Mesh``
    with a ``model_axis`` axis — ``parallel.Engine.create_mesh([(
    "model", N)])``) and the whole engine runs SPMD: params load
    Megatron-sharded (``tp_rules``, default
    ``parallel.tp.transformer_tp_rules(model_axis)``), every device
    pool — KV slots, prefill staging, prefix pool, draft pools —
    shards its KV-heads dimension along the model axis (the layout
    the column-parallel QKV writes with no collective;
    ``num_kv_heads`` must divide the axis size), host inputs enter
    replicated, and jit/GSPMD inserts the row-parallel all-reduces
    into the SAME compiled programs. Host-side control flow
    (scheduler, streams, ledger, recorder) is mesh-oblivious; greedy
    output stays token-identical to the unsharded engine (tested on a
    host-device CPU mesh), the jit gauge stays flat, and usage
    device-seconds scale by the mesh size (one dispatch occupies
    every device). ``stats()["mesh"]`` reports topology plus per-pool
    logical/physical/per-device bytes; ``bigdl_serving_mesh_*``
    gauges carry the same figures.

    When to prefer this over ``GenerationService``: mixed or long
    decode lengths under concurrent load (no head-of-line blocking on
    batch completion, slots recycle per token), streaming clients
    (tokens surface per iteration, not per finished batch), and
    prefix-heavy traffic (system prompts, few-shot templates,
    multi-turn) — TTFT scales with the NOVEL suffix, not the prompt.

    Every lifecycle transition (submitted → queued → admitted [+
    ``prefix_hit``] → each prefill chunk → first token → per-token
    decode → finished / cancelled / timed-out / stopped / crashed)
    lands in the flight recorder under the handle's ``request_id``;
    ``debug_requests()`` feeds ``GET /debug/requests``, ``healthz()``
    feeds the liveness probe (503 once the loop crashes), and a loop
    crash writes a postmortem JSON (``postmortem_path`` /
    ``$BIGDL_POSTMORTEM_PATH``, default ``bigdl_postmortem.json``)
    before failing the handles.

    RESOURCE OBSERVABILITY: the engine registers its persistent device
    buffers (KV slot pool, prefill staging, prefix pool + occupied
    prefix bytes, params) as named memory pools
    (``observability.memory.register_pool``) so ``/debug/memory``
    attributes HBM by owner; a ``RecompileWatchdog`` samples the
    compile counter every iteration (post-warmup growth — a shape leak
    — raises the recompile-storm alert), and ``slo_objectives`` (a
    list of ``observability.SloObjective`` or kwargs dicts, bound to
    the ``ttft`` / ``inter_token`` / ``queue_wait`` histograms by
    their ``metric`` field) drives an ``SloWatchdog``. Active alerts
    surface in ``stats()["alerts"]`` and flip the ``/healthz`` body to
    ``status: degraded`` while staying HTTP 200.

    USAGE ACCOUNTING: every request is metered by a ``UsageLedger``
    (``observability.accounting``) under the ``tenant=`` it was
    submitted for — queue seconds, prefilled vs prefix-reused prompt
    tokens (and the KV bytes reuse saved), delivered tokens, KV
    byte-seconds held (staging/slot row bytes x residency), and
    device-seconds attributed pro-rata from every ragged prefill round
    and fused decode step across the rows each dispatch advanced.
    ``usage_tenants`` caps tenant-label cardinality (overflow folds
    into ``"other"``); ``usage_recent`` bounds the finished-record
    ring behind top-N queries. Surfaces: ``handle.usage()``,
    ``stats()["usage"]``, ``debug_usage()`` / ``GET /debug/usage``,
    ``request/usage_final`` recorder events, and the
    ``bigdl_serving_tenant_*`` counters. Pure host bookkeeping — the
    jit-compile gauge stays flat with accounting on.
    """

    def __init__(self, model, max_slots: int = 4,
                 max_len: Optional[int] = None, prefill_chunk: int = 16,
                 prefill_budget_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_k=None, top_p=None, queue_capacity: int = 64,
                 seed: int = 0, registry=None,
                 service_name: str = "engine",
                 idle_wait_s: float = 0.5, recorder=None,
                 postmortem_path: Optional[str] = None,
                 recent_timelines: int = 256,
                 prefill_rows: int = 1,
                 prefix_cache_bytes: Optional[int] = None,
                 prefix_cache_rows: Optional[int] = None,
                 prefix_host_bytes: Optional[int] = None,
                 prefix_host_rows: Optional[int] = None,
                 prefix_min_tokens: Optional[int] = None,
                 admission_window: int = 4,
                 slo_objectives=None,
                 usage_tenants: int = 32,
                 usage_recent: int = 256,
                 draft=None,
                 spec_gamma: int = 4,
                 mesh=None,
                 tp_rules=None,
                 model_axis: str = "model",
                 timeseries_interval_s: float = 1.0,
                 timeseries_capacity: int = 600,
                 kv_dtype: Optional[str] = None,
                 weights_dtype: Optional[str] = None,
                 preempt_slack_s: Optional[float] = 0.25,
                 shed_classes=("low",),
                 tenant_rate_limits=None,
                 chaos=None,
                 page_size: Optional[int] = None,
                 max_pages: Optional[int] = None,
                 incident_dir: Optional[str] = None,
                 anomaly_detectors=None,
                 incident_cooldown_s: float = 30.0):
        from bigdl_tpu.models.transformer import _validate_sampling
        from bigdl_tpu.observability import serving_engine_instruments
        from bigdl_tpu.observability import memory as obs_memory
        from bigdl_tpu.observability.accounting import UsageLedger
        from bigdl_tpu.observability.anomaly import (
            DetectorBank, default_detector_bank,
        )
        from bigdl_tpu.observability.events import default_recorder
        from bigdl_tpu.observability.incidents import IncidentManager
        from bigdl_tpu.observability.instruments import (
            incident_instruments, qos_instruments,
        )
        from bigdl_tpu.observability.slo_budget import SloBudgetTracker
        from bigdl_tpu.observability.watchdog import (
            RecompileWatchdog, SloObjective, SloWatchdog,
        )

        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if admission_window < 1:
            raise ValueError(
                f"admission_window must be >= 1, got {admission_window}")
        _validate_sampling(temperature > 0.0, top_k, top_p)
        for name, val in (("kv_dtype", kv_dtype),
                          ("weights_dtype", weights_dtype)):
            if val is not None and str(val) != "int8":
                raise ValueError(
                    f"{name} must be None (full precision) or 'int8', "
                    f"got {val!r}")
        self.kv_dtype = "int8" if kv_dtype is not None else None
        self.weights_dtype = "int8" if weights_dtype is not None else None
        if self.weights_dtype == "int8":
            # serve through the int8 clone (nn/quantized Quantizer):
            # Linear weights become int8 codes + per-channel scales in
            # BUFFERS, so the memory-bound decode matmuls stream half
            # the bytes. The clone shares the float source's param
            # paths; under a mesh its int8 buffers replicate (same
            # argument as the int8 draft — correct either way).
            from bigdl_tpu.nn.quantized import Quantizer

            model = Quantizer.quantize(model)
        model.evaluate()
        self.model = model
        self.max_slots = max_slots
        self.eos_id = eos_id
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.draft = draft
        self._spec = None
        if draft is not None:
            if draft.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft.vocab_size}) must match the "
                    f"target's ({model.vocab_size}) — acceptance "
                    "compares distributions token-for-token")
            if temperature > 0.0 and (top_k is not None
                                      or top_p is not None):
                raise ValueError(
                    "speculative sampling accepts with min(1, p/q) "
                    "over the UNFILTERED tempered distributions; "
                    "top_k/top_p would break the acceptance identity "
                    "— drop them or drop the draft")
            draft.evaluate()
            self._spec = SpeculationPolicy(spec_gamma)
        self.idle_wait_s = idle_wait_s
        self.service_name = service_name
        self.admission_window = admission_window
        #: flight recorder fed by every lifecycle transition (captured
        #: at construction, like the instruments — swap the default
        #: BEFORE building the engine, or pass one explicitly)
        self._rec = recorder if recorder is not None \
            else default_recorder()
        self._registry = registry
        #: crash black-box destination; resolved at crash time
        #: ($BIGDL_POSTMORTEM_PATH, else ./bigdl_postmortem.json)
        self.postmortem_path = postmortem_path
        #: bounded ring of finished-request timeline summaries — the
        #: source for stats() percentiles and /debug/requests "recent".
        #: The lock covers append vs. snapshot: iterating a deque that
        #: another thread appends to raises RuntimeError in CPython,
        #: and /debug readers run on HTTP threads while the loop writes
        self._timelines: collections.deque = collections.deque(
            maxlen=recent_timelines)
        self._timelines_lock = threading.Lock()
        self._policy = PrefillPolicy(prefill_chunk, prefill_budget_tokens,
                                     prefill_rows)
        c = self._policy.chunk
        # the cache length rounds the serving window UP to a chunk
        # multiple (the last prefill chunk is padded, and forward_chunk's
        # caller contract is pos0 + chunk <= cache length); if that
        # overflows the model's own context, the window rounds DOWN
        # instead — admission then caps t0 + n at the reduced window.
        cap = min(max_len or model.max_len, model.max_len)
        cache_len = -(-cap // c) * c
        if cache_len > model.max_len:
            cache_len = (model.max_len // c) * c
            cap = cache_len
        if cache_len < c:
            raise ValueError(
                f"prefill_chunk {c} exceeds the usable context {cap}")
        self.max_len = cap
        self._cache_len = cache_len
        # speculation pads every KV row by gamma scratch positions: a
        # verify round launched at the window's last decodable
        # position still writes gamma (possibly rejected) proposal
        # positions past it — headroom instead of a silently-clamping
        # (= prefix-corrupting) dynamic_update_slice. Scratch beyond a
        # row's live prefix is position-masked until overwritten,
        # exactly the slot-reuse argument.
        phys_len = cache_len + (self._spec.kv_headroom
                                if self._spec is not None else 0)
        self._phys_len = phys_len
        if draft is not None and draft.max_len < cap:
            raise ValueError(
                f"draft context ({draft.max_len}) is shorter than the "
                f"engine's serving window ({cap}); shrink max_len or "
                "bring a longer-context draft")

        # ---- paged KV mode ---------------------------------------------
        # page_size switches EVERY KV surface (slot rows, prefill
        # staging, prefix pool, host tier, draft mirrors) from
        # full-length rows to ONE refcounted block pool per model
        # (serving.paging): requests hold fixed page_size-token pages
        # through BlockTables, prefix hits SHARE the aligned pages
        # copy-on-write instead of copying rows, and eviction /
        # host-tier demotion / preemption-donation become refcount
        # moves. Compiled shapes depend only on (max_pages, page_size)
        # — the jit gauge stays flat exactly as in dense mode.
        self.paged = page_size is not None
        if max_pages is not None and not self.paged:
            raise ValueError("max_pages requires page_size (paged mode)")
        self.page_size: Optional[int] = None
        self._pages = self._d_pages = None
        self._kv_pool = self._d_kv_pool = None
        self._tables = self._d_tables = None
        self._table_len = 0
        if self.paged:
            page_size = int(page_size)
            if page_size < 1:
                raise ValueError(
                    f"page_size must be >= 1, got {page_size}")
            if c % page_size != 0:
                raise ValueError(
                    f"prefill_chunk ({c}) must be a multiple of "
                    f"page_size ({page_size}): the chunk-aligned reuse "
                    "boundary must land on a page boundary, or a hit's "
                    "shared pages would be written under a live share "
                    "(the copy-on-write invariant paging.py documents)")
            self.page_size = page_size
            #: fixed device block-table width: every request's table is
            #: padded to the worst-case page count, so compiled shapes
            #: never depend on any one request's length
            self._table_len = -(-phys_len // page_size)
            if max_pages is None:
                # room for every slot at full length plus an equal
                # retained-prefix share — roughly the dense engine's
                # slot-pool + prefix-pool byte budget in page currency
                max_pages = 1 + 2 * max_slots * self._table_len
            max_pages = int(max_pages)
            if max_pages < 1 + self._table_len:
                raise ValueError(
                    f"max_pages ({max_pages}) cannot hold one "
                    f"full-length request ({self._table_len} pages) "
                    "plus the reserved scratch page")

        # ---- tensor-parallel mesh (SPMD serving) -----------------------
        # With a mesh, EVERY compiled program below runs as one SPMD
        # dispatch: params are Megatron-sharded (transformer_tp_rules /
        # shard_params), all four device pools (slot KV, staging,
        # prefix pool, draft pools) shard their KV-HEADS dim along the
        # model axis (the layout the column-parallel QKV writes with
        # no collective), host inputs enter replicated, and jit/GSPMD
        # places the row-parallel all-reduces. Host-side control flow
        # (scheduler, streams, ledger, recorder) stays mesh-oblivious.
        self.mesh = mesh
        self.model_axis = model_axis
        self._kv_shard = self._d_kv_shard = self._repl = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            from bigdl_tpu.parallel.tp import transformer_tp_rules

            self._kv_shard = model.kv_cache_sharding(
                mesh, model_axis=model_axis)
            if draft is not None:
                try:
                    self._d_kv_shard = draft.kv_cache_sharding(
                        mesh, model_axis=model_axis)
                except ValueError as e:
                    raise ValueError(
                        f"draft model cannot shard over this mesh: "
                        f"{e}") from None
            self._repl = NamedSharding(mesh, PartitionSpec())
            if tp_rules is None:
                tp_rules = transformer_tp_rules(model_axis)
        self._tp_rules = tp_rules

        self._params = jax.tree.map(jnp.asarray, model.params_dict())
        self._buffers = jax.tree.map(jnp.asarray, model.buffers_dict())
        if mesh is not None:
            from bigdl_tpu.parallel.tp import replicate, shard_params

            self._params = shard_params(self._params, mesh, tp_rules)
            self._buffers = replicate(self._buffers, mesh)
        dtype = model.tok_embed.dtype
        if self.paged:
            # THE page pool: one persistent (max_pages, page_size, ...)
            # buffer set per layer, donated through every dispatch.
            # There is no separate staging cache — admissions prefill
            # straight through their reserved tables — and no separate
            # prefix pool: retained prefixes are refcounted shares of
            # these same pages.
            self._kv_pool = model.init_page_pool(
                max_pages, page_size, dtype=dtype,
                sharding=self._kv_shard, kv_dtype=self.kv_dtype)
            self._pages = PagePool(self._kv_pool, page_size)
            self._tables = [None] * max_slots
            self._caches = self._staging = None
        else:
            # THE pooled cache: one persistent (max_slots, ...) buffer
            # set, donated through every step — updates are in-place
            # for the engine's whole life
            self._caches = model.init_cache(
                max_slots, phys_len, dtype=dtype,
                sharding=self._kv_shard, kv_dtype=self.kv_dtype)
            # prefill_rows-wide staging cache for chunked prefill; rows
            # are reused across admissions (stale tail KV is
            # position-masked, never attended)
            self._staging = model.init_cache(
                self._policy.prefill_rows, phys_len, dtype=dtype,
                sharding=self._kv_shard, kv_dtype=self.kv_dtype)
        if draft is not None:
            # the draft's slot pool + staging mirror the target's
            # geometry row-for-row (same phys_len so lifecycle stays
            # lockstep even though draft head counts/dims may differ)
            self._d_params = jax.tree.map(jnp.asarray,
                                          draft.params_dict())
            self._d_bufs = jax.tree.map(jnp.asarray,
                                        draft.buffers_dict())
            if mesh is not None:
                from bigdl_tpu.parallel.tp import (
                    replicate, shard_params,
                )

                # same rule set: an int8 clone shares the float
                # source's param paths; unmatched leaves (quantizer
                # scales, layernorms) replicate — correct either way
                self._d_params = shard_params(self._d_params, mesh,
                                              tp_rules)
                self._d_bufs = replicate(self._d_bufs, mesh)
            d_dtype = draft.tok_embed.dtype
            if self.paged:
                # the draft's own page pool: it never shares pages (the
                # prefix index retains target KV only), so at most
                # max_slots concurrent tables — sized to always satisfy
                # a reservation the target pool accepted
                self._d_kv_pool = draft.init_page_pool(
                    1 + max_slots * self._table_len, page_size,
                    dtype=d_dtype, sharding=self._d_kv_shard,
                    kv_dtype=self.kv_dtype)
                self._d_pages = PagePool(self._d_kv_pool, page_size)
                self._d_tables = [None] * max_slots
                self._d_caches = self._d_staging = None
            else:
                self._d_caches = draft.init_cache(
                    max_slots, phys_len, dtype=d_dtype,
                    sharding=self._d_kv_shard, kv_dtype=self.kv_dtype)
                self._d_staging = draft.init_cache(
                    self._policy.prefill_rows, phys_len, dtype=d_dtype,
                    sharding=self._d_kv_shard, kv_dtype=self.kv_dtype)
        else:
            self._d_caches = self._d_staging = None
        # prefix-cache KV pool: a third persistent buffer set holding
        # the retained prefixes, plus its host-side radix-trie index.
        # The byte budget is enforced as a row budget fixed here, so
        # every compiled shape stays load-independent.
        # summed over the LIVE cache leaves, so under kv_dtype="int8"
        # this is the true quantized physical cost — int8 code buffers
        # PLUS the f32 scale sidecars — and everything derived from it
        # (token_bytes, pool/host row budgets, PrefixCache accounting,
        # the ledger's KV byte-seconds and bytes_saved credits) stays
        # honest without a special case
        if self.paged:
            # the full-length-row EQUIVALENT (what one dense slot of
            # this geometry would cost): the exchange rate for pool /
            # host budgets and reuse credits stays comparable across
            # modes, while actual paged billing is per held page
            row_bytes = self._table_len * self._pages.page_bytes
        else:
            row_bytes = sum(int(leaf.nbytes) // max_slots
                            for leaf in jax.tree.leaves(self._caches))
        self._row_bytes = row_bytes
        #: device KV bytes one cached token position costs — the
        #: exchange rate prefix-reuse savings are credited at
        self._token_bytes = row_bytes / phys_len
        if prefix_cache_rows is not None:
            pool_rows = max(0, int(prefix_cache_rows))
        elif prefix_cache_bytes is None:
            pool_rows = 2 * max_slots
        else:
            pool_rows = max(0, int(prefix_cache_bytes) // row_bytes)
        # host tier behind the device pool: evicted rows spill to
        # pinned host buffers instead of dropping (row budget derived
        # from its own byte budget; 0 = tier off, eviction drops)
        if prefix_host_rows is not None:
            host_rows = max(0, int(prefix_host_rows))
        elif prefix_host_bytes is None:
            host_rows = 0
        else:
            host_rows = max(0, int(prefix_host_bytes) // row_bytes)
        if pool_rows > 0 and self.paged:
            # pages as the retention currency: pool_rows bounds ENTRY
            # count (cardinality), the shared page pool bounds bytes;
            # the host budget converts to pages
            self._pool = None
            self._prefix = PagedPrefixIndex(
                self._pages, max_entries=pool_rows,
                min_tokens=(prefix_min_tokens
                            if prefix_min_tokens is not None else c),
                token_bytes=self._token_bytes,
                devices=(int(mesh.shape[model_axis])
                         if mesh is not None else 1),
                host_pages=host_rows * self._table_len)
        elif pool_rows > 0:
            self._pool = model.init_cache(pool_rows, phys_len,
                                          dtype=dtype,
                                          sharding=self._kv_shard,
                                          kv_dtype=self.kv_dtype)
            self._prefix = PrefixCache(
                pool_rows, row_bytes,
                min_tokens=(prefix_min_tokens
                            if prefix_min_tokens is not None else c),
                token_bytes=self._token_bytes,
                # pool rows shard over the MODEL axis only: each
                # device's share is logical / model_shards (a 2-D
                # mesh's data axis replicates them, so mesh.size
                # would undercount)
                devices=(int(mesh.shape[model_axis])
                         if mesh is not None else 1),
                host_rows=host_rows)
        else:
            self._pool = None
            self._prefix = None
        self._prefix_evictions_seen = 0
        self._prefix_demotions_seen = 0
        self._prefix_host_evictions_seen = 0
        #: host->device promotions in flight, keyed by entry identity:
        #: {"entry", "tree" (async device_put result), "touched"
        #: (iteration stamp)} — each record holds a pin on its entry,
        #: so the host buffer can never be evicted mid-transfer
        self._promotions: dict = {}
        self._promotions_max = max(4, 2 * self._policy.prefill_rows)
        #: host-side prompt-token tally actually prefilled by THIS
        #: engine (the reused-fraction denominator — per-instance
        #: exact, unlike the shared-label registry counter)
        self._prefilled_tokens = 0
        #: per-instance speculative tallies (the stats() numerator/
        #: denominator — the registry counters are shared per label)
        self._spec_proposed = 0
        self._spec_accepted = 0
        #: programs that have run at least once — the jit_compiles
        #: fallback when jax's _cache_size probe is unavailable
        self._warm = set()
        #: paged bookkeeping: last KV byte-second accrual stamp, the
        #: page-flow counter baselines behind the delta-published
        #: bigdl_serving_page_* instruments, and the blocked-admission
        #: latch (set when the pool cannot satisfy the queue head's
        #: reservation this iteration — re-probed next iteration
        #: instead of thrashing pop/requeue within one)
        self._last_kv_accrue: Optional[float] = None
        self._page_seen = {"allocated": 0, "shared": 0,
                           "cow_forks": 0, "freed": 0}
        self._adm_blocked = False
        self._build_fns()

        self._ins = serving_engine_instruments(service_name, registry)
        #: per-request / per-tenant usage meter: queue wait, prefill
        #: vs prefix-reused tokens, delivered tokens, KV byte-seconds
        #: held, and device-seconds attributed pro-rata per dispatch.
        #: Pure host bookkeeping — zero device programs, so the
        #: jit-compile gauge stays flat with accounting on.
        self._usage = UsageLedger(
            service=service_name, registry=registry, recorder=self._rec,
            instruments=self._ins, max_tenants=usage_tenants,
            recent=usage_recent,
            # paged mode bills KV byte-seconds per actually-held page
            # (accrue_kv from the loop, holder_bytes pro-rata over
            # shares) — the dense row-residency terms must be zero or
            # a request would be double-billed
            slot_row_bytes=0 if self.paged else row_bytes,
            staging_row_bytes=0 if self.paged else row_bytes,
            token_bytes=self._token_bytes,
            devices=(int(mesh.size) if mesh is not None else 1))
        self._queue = AdmissionQueue(
            queue_capacity, recorder=self._rec,
            wait_histogram=self._ins.queue_wait_seconds)
        self._slots: List[Optional[_SlotState]] = [None] * max_slots
        self._adms: List[_Admission] = []
        self._key = jax.random.PRNGKey(seed)
        self._zero_key = self._h2d(jax.random.PRNGKey(0))
        #: the compiled programs' temperature operand, committed once
        #: (it is fixed per engine) — rebuilding a replicated scalar
        #: per decode iteration would put a host->mesh transfer in the
        #: hot loop for a constant
        self._temp_const = self._h2d(jnp.float32(
            self.temperature if self.temperature > 0.0 else 1.0))

        self._ins.slots.set(max_slots, force=True)
        # numerics telemetry: which dtypes the hot path runs, plus the
        # honest per-row physical bytes (scale sidecars included) next
        # to the full-precision row the same geometry would cost — the
        # before/after pair behind the quantized-capacity claim
        self._fp_row_bytes = int(
            2 * model.num_layers * model.num_kv_heads * phys_len
            * model.block0.attn.head_dim * jnp.dtype(dtype).itemsize)
        self._ins.quantized_kv.set(
            1 if self.kv_dtype else 0, force=True)
        self._ins.quantized_weights.set(
            1 if self.weights_dtype else 0, force=True)
        self._ins.kv_row_bytes.set(row_bytes, force=True)

        # ---- resource observability -----------------------------------
        # per-pool HBM attribution: every persistent device buffer set
        # this engine owns, registered under weakrefs (the monitor must
        # never keep a dead engine's KV pools alive). Names are keyed
        # by service_name; a same-named successor engine takes them over.
        # attribution is PHYSICAL: tree_device_bytes sums every leaf's
        # per-device shards, so a mesh engine's sharded KV pools report
        # their true global footprint while replicated leaves (most of
        # params) count once per device — identical to tree_bytes for
        # an unsharded engine, honest for an SPMD one. Figures are
        # SNAPSHOTTED here, the one moment the donated trees cannot be
        # mid-dispatch (shapes/shardings never change afterwards):
        # walking a live donated tree's shards from a monitor/HTTP
        # thread could observe an already-deleted buffer and raise.
        self._pool_bytes = self._snapshot_pool_bytes()

        def pool_reader(key):
            return lambda e: e._pool_bytes[key]["physical_bytes"]

        pools = {f"serving/{service_name}/{key}": pool_reader(key)
                 for key in self._pool_bytes}
        if self.paged:
            # the page pool's LIVE footprint next to its capacity:
            # bytes of pages something still references (slot tables,
            # in-flight admissions, prefix entries) — /debug/memory
            # then answers "how full is the pool" not just "how big"
            pools[f"serving/{service_name}/kv_pages_in_use"] = (
                lambda e: e._pages.bytes_in_use)
            if self.draft is not None:
                pools[f"serving/{service_name}/draft_pages_in_use"] = (
                    lambda e: e._d_pages.bytes_in_use)
        self._memory_pools = obs_memory.register_owned_pools(self, pools)
        if self._prefix is not None:
            self._memory_pools.append(self._prefix.register_memory_pool(
                f"serving/{service_name}/prefix_kv_in_use"))
            if self._prefix.host_rows > 0:
                self._memory_pools.append(
                    self._prefix.register_host_memory_pool(
                        f"serving/{service_name}/prefix_host_kv"))

        # mesh topology gauges + per-pool per-device footprint
        n_dev = int(mesh.size) if mesh is not None else 1
        shards = (int(mesh.shape[model_axis])
                  if mesh is not None else 1)
        self._ins.mesh_devices.set(n_dev, force=True)
        self._ins.mesh_model_shards.set(shards, force=True)
        for pool_name, summary in self._pool_bytes.items():
            self._ins.mesh_pool_bytes_per_device.labels(
                service_name, pool_name).set(
                    summary["bytes_per_device"], force=True)

        # ---- dispatch cost model / loop-phase attribution --------------
        # static per-kind FLOPs/bytes extracted ONCE here via
        # jitted.lower(...).cost_analysis(): lowering only traces — no
        # compile, no execution, donated buffers stay live — so the
        # extraction adds zero device programs and the jit-compile
        # gauge stays flat. When XLA reports nothing the analytic
        # transformer formulas take over (flops_source: "analytic").
        self._cost = DispatchCostModel(
            device_peaks(self._cost_device()), devices=n_dev)
        self._loop_obs = LoopPhaseAccumulator()
        self._iter_disp = {"prefill": 0.0, "decode": 0.0}
        self._extract_program_costs()
        #: counter children + flushed totals for the per-phase series
        self._loop_phase_counters = {
            p: self._ins.loop_phase_seconds.labels(service_name, p)
            for p in LoopPhaseAccumulator.PHASES}
        self._loop_flushed = {p: 0.0
                              for p in LoopPhaseAccumulator.PHASES}
        # background gauge/rate sampler behind /debug/timeseries and
        # /debug/dashboard; started with the loop thread, joined in
        # stop() — bounded rings, no-op when the registry is disabled
        self._ts = TimeSeriesSampler(
            interval_s=timeseries_interval_s,
            capacity=timeseries_capacity, registry=registry)
        self._ts.add_source("mfu", lambda: self._cost.rates("decode")[0])
        self._ts.add_source(
            "mfu_prefill", lambda: self._cost.rates("prefill")[0])
        self._ts.add_source("tokens_per_sec",
                            self._ins.decode_tokens_total.get, rate=True)
        self._ts.add_source(
            "slot_occupancy",
            lambda: (sum(s is not None for s in self._slots)
                     / max(1, self.max_slots)))
        self._ts.add_source("queue_depth", lambda: len(self._queue))
        if self._spec is not None:
            self._ts.add_source(
                "acceptance_rate",
                lambda: (self._spec_accepted / self._spec_proposed
                         if self._spec_proposed else None))
        if self.paged:
            # PR 17 pool gauges, charted: occupancy (live references
            # over usable pages) and reservation fragmentation
            self._ts.add_source(
                "page_pool_occupancy",
                lambda: (self._pages.pages_in_use
                         / max(1, self._pages.max_pages - 1)))
            self._ts.add_source("page_fragmentation",
                                self._fragmentation)
        self._ts.add_source("alerts", lambda: float(len(self.alerts())))

        # ---- anomaly detection + incident capture ----------------------
        # detectors see every appended sampler point (observer runs on
        # the sampler thread and only RECORDS triggers — the engine
        # loop drains them once per iteration and does the capture
        # work there); watchdog alerts and chaos drills converge on
        # the same trigger stream in _process_triggers. Host-side
        # Python only — the jit gauge stays flat with capture on.
        if anomaly_detectors is None:
            self._bank = default_detector_bank()
        elif isinstance(anomaly_detectors, DetectorBank):
            self._bank = anomaly_detectors
        else:
            self._bank = DetectorBank(anomaly_detectors)
        self._ts.set_observer(self._bank.observe)
        self._incidents = IncidentManager(
            service_name, recorder=self._rec, registry=registry,
            dirpath=incident_dir, cooldown_s=incident_cooldown_s,
            config={"service_name": service_name,
                    "max_slots": max_slots, "max_len": self.max_len,
                    "prefill_chunk": self._policy.chunk,
                    "admission_window": admission_window,
                    "kv_dtype": self.kv_dtype,
                    "weights_dtype": self.weights_dtype,
                    "paged": self.paged,
                    "shed_classes": list(shed_classes or ()),
                    "preempt_slack_s": preempt_slack_s})
        self._inc_ins = incident_instruments(registry)
        self._det_gauges: Dict[str, object] = {}
        self._trig_counters: Dict[str, object] = {}

        # watchdogs, sampled once per loop iteration: compiles that keep
        # growing after warmup break the engine's shape-stability
        # contract (storm alert); SLO objectives burn against the TTFT /
        # inter-token / queue-wait histograms. Alerts surface through
        # stats()["alerts"] and a degraded (but 200) /healthz body.
        self._recompile_wd = RecompileWatchdog(
            self._compile_total, service=service_name,
            registry=registry, recorder=self._rec)
        self._slo_wd = SloWatchdog(service=service_name,
                                   registry=registry, recorder=self._rec)
        slo_children = {"ttft": self._ins.ttft_seconds,
                        "inter_token": self._ins.inter_token_seconds,
                        "queue_wait": self._ins.queue_wait_seconds}
        # the error-budget ledger reads the SAME histogram children as
        # the watchdog: the watchdog answers "burning now?", the
        # tracker answers "how much budget is left / when does it run
        # out" — and chaos burn drills spend it synthetically so the
        # exhaustion path is exercisable
        self._slo_budget = SloBudgetTracker(
            service=service_name, registry=registry, recorder=self._rec)
        for obj in (slo_objectives or ()):
            if isinstance(obj, dict):
                obj = SloObjective(**obj)
            if obj.metric not in slo_children:
                raise ValueError(
                    f"SloObjective {obj.name!r} names unknown engine "
                    f"metric {obj.metric!r}; expected one of "
                    f"{sorted(slo_children)}")
            self._slo_wd.watch(obj, slo_children[obj.metric])
            self._slo_budget.watch(obj, slo_children[obj.metric])
        # stats() reports the DELTA since construction (the same
        # registry-façade convention as OccupancyStats): two engines
        # sharing a service_name share the series, so each instance
        # snapshots its own baseline
        self._stats_base = {k: self._counter(k).get()
                            for k in ("admitted", "finished", "evicted",
                                      "timed_out", "cancelled")}

        # ---- QoS: preemption, burn-rate shedding, token buckets --------
        # preemption: a HIGH-class request queued past this slack with
        # no free slot evicts the lowest-class longest-remaining slot,
        # donating its KV to the prefix pool so the automatic resume
        # re-prefills only the uncached tail (None disables)
        if preempt_slack_s is not None and preempt_slack_s < 0:
            raise ValueError(f"preempt_slack_s must be >= 0 or None, "
                             f"got {preempt_slack_s}")
        self.preempt_slack_s = preempt_slack_s
        # shed set under an active TTFT burn: "low" sheds the moment
        # the alert raises; "normal" (opt-in) sheds only once the burn
        # passes TWICE its alert threshold (severe). "high" is never
        # sheddable — that is what the class buys.
        self.shed_classes = tuple(shed_classes or ())
        for p in self.shed_classes:
            if p not in PRIORITY_RANK or p == "high":
                raise ValueError(
                    f"shed_classes may contain 'low'/'normal', "
                    f"got {p!r}")
        # per-tenant device-second token buckets (post-paid): keys are
        # resolved tenant names, "*" sets the default for every tenant
        # without an explicit entry; values are (rate_per_s, burst)
        # tuples or {"rate": ..., "burst": ...} dicts. None = unlimited.
        self._rate_limits = dict(tenant_rate_limits or {})
        self._buckets: Dict[str, TokenBucket] = {}
        self._buckets_lock = threading.Lock()
        for tenant in self._rate_limits:
            if tenant != "*":
                self._tenant_bucket(self._usage.resolve_tenant(tenant))
        #: scripted fault injector (serving.chaos.ChaosInjector): the
        #: shed decision honors its synthetic burn, the loop honors
        #: its dispatch faults and slot freezes. None = no injection.
        self._chaos = chaos
        self._qos_ins = qos_instruments(registry)
        # host-side QoS tallies (per-instance exact — the registry
        # counters are shared per label and carry dynamic class/tenant
        # labels, so stats() keeps its own figures)
        self._qos_counts = {"preempted": 0, "shed": 0,
                            "rate_limited": 0}

        self._wake = threading.Condition()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()
        self._crashed: Optional[BaseException] = None
        self._draining = False

    # ------------------------------------------------- compiled programs
    def _build_fns(self):
        if self.paged:
            return self._build_fns_paged()
        from bigdl_tpu.models.transformer import (
            _filter_logits, _spec_accept,
        )
        from bigdl_tpu.nn.module import bind

        self._copy_page_jit = None   # paged-only program
        model = self.model
        sampled = self.temperature > 0.0
        top_k, top_p = self.top_k, self.top_p

        def step(p, bufs, tok, pos, caches, rng, temperature):
            # one fused decode over ALL slots: (S,) tokens at (S,)
            # per-row positions (free slots ride along at pos 0 — their
            # junk write is overwritten by the next admission's insert)
            with bind(model, p, bufs, False, None):
                logits, caches = model.decode_step(tok, pos, caches)
            if sampled:
                nxt = jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k, top_p),
                    axis=-1).astype(jnp.int32)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, caches

        def chunk(p, bufs, ids, caches, pos0, last_idx):
            # one RAGGED prefill round over the whole staging cache:
            # row r writes its chunk at its own traced offset pos0[r]
            # (rows without an active admission ride along at offset 0
            # — their junk write lands in their own idle row and is
            # overwritten by that row's next occupant before it can
            # ever be attended); last_idx gathers each row's true last
            # prompt position's logits (the final chunk is
            # right-padded, so "last position of the chunk" would be a
            # pad)
            with bind(model, p, bufs, False, None):
                return model.prefill_chunk_at(ids, caches, pos0,
                                              last_idx)

        def copy_row(dst, src, dst_row, src_row):
            # copy row src_row of cache-tree src into row dst_row of
            # cache-tree dst (dst donated — in place for the engine's
            # life). ONE program, three compiled signatures, all
            # load-independent: staging→pool-slot insert, prefix-pool→
            # staging on a hit, pool-slot→prefix-pool on donation.
            return jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(
                    d,
                    jax.lax.dynamic_slice(
                        s, (src_row,) + (0,) * (s.ndim - 1),
                        (1,) + s.shape[1:]).astype(d.dtype),
                    (dst_row,) + (jnp.int32(0),) * (d.ndim - 1)),
                dst, src)

        def sample0(logits, rng, temperature):
            if sampled:
                return jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k, top_p),
                    axis=-1).astype(jnp.int32)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        # On a mesh, output shardings are PINNED: every program's cache
        # tree leaves with the same NamedSharding it entered with (and
        # scalars/logits leave replicated), so the donated buffers
        # cycle through the loop in ONE stable layout. Left to GSPMD's
        # own choice, a copy/step output can drift (e.g. to
        # replicated), and the next dispatch's changed input sharding
        # compiles a fresh signature — a gauge-visible leak. kv/draft
        # pools share the spec (heads along the model axis), so one
        # prefix broadcast covers every cache tree.
        kv, repl = self._kv_shard, self._repl

        def _jit(fn, donate, out=None):
            if self.mesh is None:
                # graftlint: ok[jit-hazard] — meshless (single-device) branch has no shardings to pin
                return jax.jit(fn, donate_argnums=donate)
            return jax.jit(fn, donate_argnums=donate, out_shardings=out)

        self._step_jit = _jit(step, (4,), (repl, kv))
        self._chunk_jit = _jit(chunk, (3,), (repl, kv))
        self._copy_row_jit = _jit(copy_row, (0,), kv)
        self._sample0_jit = _jit(sample0, (), repl)

        # ---- host-tier transfer program ------------------------------
        # demotion source: ONE jitted slice lifting a pool row out as a
        # (1, ...) tree the engine bulk-copies to host (src NOT donated
        # — the pool lives on). Raw jnp indexing here would compile an
        # anonymous executable per call site; a named program keeps the
        # transfer on a warmed signature like every other copy.
        self._take_row_jit = None
        if self._prefix is not None and self._prefix.host_rows > 0:
            def take_row(src, row):
                return jax.tree.map(
                    lambda s: jax.lax.dynamic_slice(
                        s, (row,) + (0,) * (s.ndim - 1),
                        (1,) + s.shape[1:]), src)

            self._take_row_jit = _jit(take_row, (), kv)

        # ---- speculative-decoding programs --------------------------
        self._propose_jit = self._spec_verify_jit = None
        self._d_chunk_jit = self._d_sync_jit = None
        if self.draft is not None:
            draft = self.draft
            g = self._spec.gamma

            # the draft proposer IS the standalone speculative path's
            # cached per-(model, batch, gamma) lax.scan
            # (transformer._propose_fn): (max_slots,) tokens at
            # (max_slots,) per-row positions, gamma draft steps, ONE
            # dispatch, draft KV written as it goes
            self._propose_jit = draft._propose_fn(
                self.max_slots, g, sampled=sampled,
                cache_sharding=self._d_kv_shard,
                repl_sharding=self._repl)

            def d_chunk(p, bufs, ids, caches, pos0, last_idx):
                # the draft's mirror of the ragged admission prefill:
                # same chunk geometry, its own staging cache; the
                # gathered logits are discarded (the first token always
                # samples from the TARGET's prefill logits)
                with bind(draft, p, bufs, False, None):
                    return draft.prefill_chunk_at(ids, caches, pos0,
                                                  last_idx)

            def d_sync(p, bufs, tok, pos, caches):
                # one ragged draft step re-writing each row's LAST
                # accepted token's KV at its own position: for rows
                # that accepted everything this fills the one position
                # the propose scan never wrote (the gamma-th proposal's
                # KV); for every other row it rewrites identical values
                # in place (same token, same position -> same KV), so
                # one fixed-shape dispatch serves all rows
                with bind(draft, p, bufs, False, None):
                    _, caches = draft.decode_step(tok, pos, caches)
                return caches

            def spec_verify(p, bufs, tok, props, qlogits, pos, caches,
                            rng, temperature):
                # ONE ragged target forward scores every row's
                # proposals (the verify_chunk path): chunk column 0 is
                # the row's pending token (its KV is written first),
                # columns 1..g its proposals; logits column j predicts
                # the token at position pos+j+1. Acceptance is decided
                # per ROW in-graph so the host transfer is just the
                # (S, g+1) emit matrix + (S,) accepted counts.
                chunk = jnp.concatenate(
                    [tok[:, None], jnp.swapaxes(props, 0, 1)], axis=1)
                with bind(model, p, bufs, False, None):
                    logits, caches = model.verify_chunk(chunk, caches,
                                                        pos)
                if sampled:
                    accept, resid, bonus = _spec_accept(
                        logits, jnp.swapaxes(qlogits, 0, 1),
                        chunk[:, 1:], temperature, rng)
                    n_acc = jnp.sum(jnp.cumprod(
                        accept.astype(jnp.int32), axis=1), axis=1)
                    # emit column j: the proposal while accepted; at
                    # the first rejection the residual draw, on full
                    # acceptance the bonus draw (columns past n_acc
                    # are never read by the host)
                    fix = jnp.take_along_axis(
                        jnp.concatenate([resid, bonus[:, None]],
                                        axis=1),
                        n_acc[:, None], axis=1)
                    cols = jnp.arange(g + 1)[None, :]
                    padded = jnp.concatenate(
                        [chunk[:, 1:], jnp.zeros_like(tok)[:, None]],
                        axis=1)
                    emit = jnp.where(cols < n_acc[:, None], padded, fix)
                else:
                    v_tok = jnp.argmax(logits, axis=-1).astype(
                        jnp.int32)
                    match = (chunk[:, 1:] == v_tok[:, :g]).astype(
                        jnp.int32)
                    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                    # matched proposals ARE the target argmax, so the
                    # emitted burst is v_tok[:, :n_acc+1] verbatim —
                    # exactly the tokens the non-speculative engine
                    # would have argmaxed one step at a time
                    emit = v_tok
                return emit, n_acc, caches

            self._d_chunk_jit = _jit(d_chunk, (3,), (repl, kv))
            self._d_sync_jit = _jit(d_sync, (4,), kv)
            self._spec_verify_jit = _jit(spec_verify, (6,),
                                         (repl, repl, kv))
        # warm the copy signatures NOW (zero rows copied onto zero rows
        # — harmless): the insert/stage/donate copies first fire at a
        # request's FINISH or at the first cache hit, and a compile
        # there would show up as a post-warmup jit_compiles bump — the
        # exact flatness contract the gauge exists to police.
        z = jnp.int32(0)
        self._caches = self._copy_row_jit(self._caches, self._staging,
                                          z, z)
        self._warm.add("copy:insert")
        if self._pool is not None:
            self._staging = self._copy_row_jit(self._staging, self._pool,
                                               z, z)
            self._pool = self._copy_row_jit(self._pool, self._caches,
                                            z, z)
            self._warm.update(("copy:stage", "copy:donate"))
        if self._take_row_jit is not None:
            # warm the demote slice AND the promote scatter (a fourth
            # copy_row signature: (1, ...) src tree -> pool). The warm
            # promote input is built EXACTLY the way real promotions
            # build theirs — host ndarrays through device_put under the
            # pool's sharding — so the first real promotion lands on
            # this signature instead of compiling a new one.
            from bigdl_tpu.parallel.tp import put_from_host

            _ = self._take_row_jit(self._pool, z)
            host_proto = jax.tree.map(
                lambda s: np.zeros((1,) + s.shape[1:], s.dtype),
                self._pool)
            one_row = put_from_host(host_proto, self._kv_shard)
            self._pool = self._copy_row_jit(self._pool, one_row, z, z)
            self._warm.update(("copy:demote", "copy:promote"))
        if self.draft is not None:
            # the draft staging->slot insert is a fourth copy
            # signature (draft tree shapes)
            self._d_caches = self._copy_row_jit(self._d_caches,
                                                self._d_staging, z, z)
            self._warm.add("copy:d_insert")
            # warm the whole speculative round NOW (zero tokens at
            # position 0 — junk in empty rows, overwritten by every
            # admission's full-row insert): the sync dispatch is
            # CONDITIONAL at runtime (it only fires when some row
            # fully accepts), so left cold it could first compile many
            # iterations after warmup and read as a recompile storm
            # warmed inputs take the SAME layout runtime inputs will
            # (replicated-committed on a mesh, via _h2d): a layout
            # mismatch would make the first real dispatch a second
            # compile — exactly the flatness the gauge polices
            zt = self._h2d(jnp.zeros((self.max_slots,), jnp.int32))
            zk = self._h2d(jax.random.PRNGKey(0))
            t1 = self._h2d(jnp.float32(1.0))
            props, qlogits, self._d_caches = self._propose_jit(
                self._d_params, self._d_bufs, zt, zt, self._d_caches,
                zk, t1)
            _, _, self._caches = self._spec_verify_jit(
                self._params, self._buffers, zt, props, qlogits, zt,
                self._caches, zk, t1)
            self._d_caches = self._d_sync_jit(
                self._d_params, self._d_bufs, zt, zt, self._d_caches)
            self._warm.update(("spec:propose", "spec:verify",
                               "spec:sync"))

    def _build_fns_paged(self):
        """Paged twins of the compiled programs: every KV surface is
        the page pool, gathered/scattered through per-request block
        tables INSIDE the dispatch. Compiled shapes depend only on
        ``(max_pages, page_size)`` and the fixed dispatch widths
        (max_slots / prefill_rows / table_len / gamma) — none on load —
        so the jit gauge stays flat while alloc/share/COW-fork/evict/
        demote/preempt move nothing but host-side refcounts."""
        from bigdl_tpu.models.transformer import (
            _filter_logits, _spec_accept,
        )
        from bigdl_tpu.nn.module import bind

        model = self.model
        sampled = self.temperature > 0.0
        top_k, top_p = self.top_k, self.top_p

        def step(p, bufs, tok, pos, pool, tables, rng, temperature):
            # one fused decode over ALL slots; idle lanes carry the
            # all-scratch table (SCRATCH_PAGE padding) so their junk
            # write lands on page 0, never on a live page
            with bind(model, p, bufs, False, None):
                logits, pool = model.decode_step_paged(tok, pos, pool,
                                                       tables)
            if sampled:
                nxt = jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k,
                                        top_p),
                    axis=-1).astype(jnp.int32)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, pool

        def chunk(p, bufs, ids, pool, tables, pos0, last_idx):
            # the ragged admission prefill, writing through each row's
            # reserved table: a prefix hit's row starts at pos0 = base
            # (page-aligned — see the ctor's chunk/page check), so its
            # writes land only in its FRESH pages while the shared head
            # is read via the gather — zero row copies on the hit leg
            with bind(model, p, bufs, False, None):
                return model.prefill_chunk_at_paged(ids, pool, tables,
                                                    pos0, last_idx)

        def copy_page(pool, dst, src):
            # single-page pool-internal copy — the COW privatization
            # primitive (BlockTable.ensure_writable's copy_page
            # callback) — one compiled signature, load-independent
            return jax.tree.map(
                lambda b: jax.lax.dynamic_update_slice(
                    b,
                    jax.lax.dynamic_slice(
                        b, (src,) + (0,) * (b.ndim - 1),
                        (1,) + b.shape[1:]),
                    (dst,) + (jnp.int32(0),) * (b.ndim - 1)),
                pool)

        def copy_row(dst, src, dst_row, src_row):
            # generic tree row copy, kept for the promote landing:
            # (1, ...) host-transferred page tree -> pool page dst_row
            return jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(
                    d,
                    jax.lax.dynamic_slice(
                        s, (src_row,) + (0,) * (s.ndim - 1),
                        (1,) + s.shape[1:]).astype(d.dtype),
                    (dst_row,) + (jnp.int32(0),) * (d.ndim - 1)),
                dst, src)

        def sample0(logits, rng, temperature):
            if sampled:
                return jax.random.categorical(
                    rng, _filter_logits(logits, temperature, top_k,
                                        top_p),
                    axis=-1).astype(jnp.int32)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

        kv, repl = self._kv_shard, self._repl

        def _jit(fn, donate, out=None):
            if self.mesh is None:
                # graftlint: ok[jit-hazard] — meshless (single-device) branch has no shardings to pin
                return jax.jit(fn, donate_argnums=donate)
            return jax.jit(fn, donate_argnums=donate, out_shardings=out)

        self._step_jit = _jit(step, (4,), (repl, kv))
        self._chunk_jit = _jit(chunk, (3,), (repl, kv))
        self._copy_page_jit = _jit(copy_page, (0,), kv)
        self._copy_row_jit = _jit(copy_row, (0,), kv)
        self._sample0_jit = _jit(sample0, (), repl)

        self._take_row_jit = None
        if self._prefix is not None and self._prefix.host_rows > 0:
            def take_row(src, row):
                # demotion source: one jitted slice lifting page `row`
                # out as a (1, ...) tree the spill bulk-copies to host
                return jax.tree.map(
                    lambda s: jax.lax.dynamic_slice(
                        s, (row,) + (0,) * (s.ndim - 1),
                        (1,) + s.shape[1:]), src)

            self._take_row_jit = _jit(take_row, (), kv)

        # ---- speculative-decoding programs (paged) -------------------
        self._propose_jit = self._spec_verify_jit = None
        self._d_chunk_jit = self._d_sync_jit = None
        if self.draft is not None:
            draft = self.draft
            g = self._spec.gamma

            self._propose_jit = draft._propose_fn_paged(
                self.max_slots, g, self._table_len, sampled=sampled,
                cache_sharding=self._d_kv_shard,
                repl_sharding=self._repl)

            def d_chunk(p, bufs, ids, pool, tables, pos0, last_idx):
                with bind(draft, p, bufs, False, None):
                    return draft.prefill_chunk_at_paged(
                        ids, pool, tables, pos0, last_idx)

            def d_sync(p, bufs, tok, pos, pool, tables):
                with bind(draft, p, bufs, False, None):
                    _, pool = draft.decode_step_paged(tok, pos, pool,
                                                      tables)
                return pool

            def spec_verify(p, bufs, tok, props, qlogits, pos, pool,
                            tables, rng, temperature):
                chunk_ids = jnp.concatenate(
                    [tok[:, None], jnp.swapaxes(props, 0, 1)], axis=1)
                with bind(model, p, bufs, False, None):
                    logits, pool = model.verify_chunk_paged(
                        chunk_ids, pool, tables, pos)
                if sampled:
                    accept, resid, bonus = _spec_accept(
                        logits, jnp.swapaxes(qlogits, 0, 1),
                        chunk_ids[:, 1:], temperature, rng)
                    n_acc = jnp.sum(jnp.cumprod(
                        accept.astype(jnp.int32), axis=1), axis=1)
                    fix = jnp.take_along_axis(
                        jnp.concatenate([resid, bonus[:, None]],
                                        axis=1),
                        n_acc[:, None], axis=1)
                    cols = jnp.arange(g + 1)[None, :]
                    padded = jnp.concatenate(
                        [chunk_ids[:, 1:],
                         jnp.zeros_like(tok)[:, None]], axis=1)
                    emit = jnp.where(cols < n_acc[:, None], padded, fix)
                else:
                    v_tok = jnp.argmax(logits, axis=-1).astype(
                        jnp.int32)
                    match = (chunk_ids[:, 1:] == v_tok[:, :g]).astype(
                        jnp.int32)
                    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                    emit = v_tok
                return emit, n_acc, pool

            self._d_chunk_jit = _jit(d_chunk, (3,), (repl, kv))
            self._d_sync_jit = _jit(d_sync, (4,), kv)
            self._spec_verify_jit = _jit(spec_verify, (6,),
                                         (repl, repl, kv))

        # warm every copy/transfer signature NOW (page 0 onto page 0 —
        # the scratch page, harmless): COW copies, demote slices, and
        # promote scatters first fire deep into steady state, and a
        # compile there would read as a post-warmup jit_compiles bump —
        # the flatness contract the gauge polices
        z = jnp.int32(0)
        self._kv_pool = self._copy_page_jit(self._kv_pool, z, z)
        self._warm.add("copy:page")
        if self._take_row_jit is not None:
            from bigdl_tpu.parallel.tp import put_from_host

            _ = self._take_row_jit(self._kv_pool, z)
            host_proto = jax.tree.map(
                lambda s: np.zeros((1,) + s.shape[1:], s.dtype),
                self._kv_pool)
            one_page = put_from_host(host_proto, self._kv_shard)
            self._kv_pool = self._copy_row_jit(self._kv_pool, one_page,
                                               z, z)
            self._warm.update(("copy:demote", "copy:promote"))
        if self.draft is not None:
            # warm the whole speculative round (all-scratch tables:
            # every junk write lands on page 0) — the sync dispatch is
            # conditional at runtime, exactly the dense argument
            zt = self._h2d(jnp.zeros((self.max_slots,), jnp.int32))
            zT = self._h2d(jnp.zeros(
                (self.max_slots, self._table_len), jnp.int32))
            zk = self._h2d(jax.random.PRNGKey(0))
            t1 = self._h2d(jnp.float32(1.0))
            props, qlogits, self._d_kv_pool = self._propose_jit(
                self._d_params, self._d_bufs, zt, zt,
                self._d_kv_pool, zT, zk, t1)
            _, _, self._kv_pool = self._spec_verify_jit(
                self._params, self._buffers, zt, props, qlogits, zt,
                self._kv_pool, zT, zk, t1)
            self._d_kv_pool = self._d_sync_jit(
                self._d_params, self._d_bufs, zt, zt,
                self._d_kv_pool, zT)
            self._warm.update(("spec:propose", "spec:verify",
                               "spec:sync"))

    def _h2d(self, x):
        """Host value → device array; on a mesh, committed REPLICATED.
        Every per-iteration host input (token/position vectors, chunk
        ids, RNG keys, the temperature scalar) funnels through here so
        compiled signatures see ONE stable input layout — GSPMD never
        has to guess a fresh sharding per call, and the jit gauge
        stays flat."""
        x = jnp.asarray(x)
        if self._repl is not None:
            x = jax.device_put(x, self._repl)
        return x

    def _pool_trees(self) -> dict:
        """Short name → live buffer tree for every persistent device
        pool this engine owns (the mesh-summary / per-device gauge
        enumeration; keys match the ``serving/<name>/<pool>`` registry
        suffixes)."""
        if self.paged:
            out = {"kv_page_pool": self._kv_pool,
                   "params": self._params}
            if self.draft is not None:
                out["draft_page_pool"] = self._d_kv_pool
                out["draft_params"] = self._d_params
            return out
        out = {"kv_slots": self._caches,
               "prefill_staging": self._staging,
               "params": self._params}
        if self._pool is not None:
            out["prefix_pool"] = self._pool
        if self.draft is not None:
            out["draft_kv_slots"] = self._d_caches
            out["draft_staging"] = self._d_staging
            out["draft_params"] = self._d_params
        return out

    def _mesh_summary(self) -> dict:
        """The ``stats()["mesh"]`` block: topology (axis names/sizes,
        device count, which axis shards the model) and per-pool byte
        attribution — logical bytes (the array's global shape),
        physical bytes (shards summed across devices; replicated
        leaves count once per device), and the per-device share one
        chip's HBM actually pays. Pool shapes and shardings are
        load-independent, so the figures are computed ONCE at
        construction (``_snapshot_pool_bytes``) — also why this is
        safe from HTTP/debug threads: reading a live donated tree's
        shards mid-dispatch could observe a deleted buffer."""
        n = int(self.mesh.size) if self.mesh is not None else 1
        out = {"enabled": self.mesh is not None, "devices": n,
               "pools": dict(self._pool_bytes)}
        if self.mesh is not None:
            out["axes"] = {str(a): int(s)
                           for a, s in self.mesh.shape.items()}
            out["model_axis"] = self.model_axis
            out["model_shards"] = int(self.mesh.shape[self.model_axis])
        return out

    def _snapshot_pool_bytes(self) -> dict:
        """Per-pool byte attribution, computed at construction while
        no loop thread can be mid-donation (every later reader serves
        this snapshot — the buffers' shapes and shardings never change
        for the engine's life)."""
        from bigdl_tpu.observability import memory as obs_memory

        n = int(self.mesh.size) if self.mesh is not None else 1
        pools = {}
        for name, tree in self._pool_trees().items():
            logical = obs_memory.tree_bytes(tree)
            physical = obs_memory.tree_device_bytes(tree)
            pools[name] = {
                "logical_bytes": logical,
                "physical_bytes": physical,
                "bytes_per_device": physical // n,
                "sharded": bool(n > 1 and physical < logical * n),
            }
        return pools

    def _compile_total(self) -> int:
        fns = [self._step_jit, self._chunk_jit, self._copy_row_jit,
               self._sample0_jit]
        if self._copy_page_jit is not None:
            fns.append(self._copy_page_jit)
        if self._take_row_jit is not None:
            fns.append(self._take_row_jit)
        if self.draft is not None:
            fns += [self._propose_jit, self._spec_verify_jit,
                    self._d_chunk_jit, self._d_sync_jit]
        counts = [_compile_count(f) for f in fns]
        if all(c is None for c in counts):
            # _cache_size absent in this jax build: approximate with
            # the warmed-program count (each program compiles exactly
            # once — shapes are load-independent, which is exactly the
            # flatness contract the gauge exists to expose)
            return len(self._warm)
        return sum(c or 0 for c in counts)

    # --------------------------------------------------- dispatch costs
    def _cost_device(self):
        """The device whose peak table entry prices this engine's
        dispatches: mesh device 0 when sharded, local device 0
        otherwise."""
        if self.mesh is not None:
            return self.mesh.devices.flat[0]
        return jax.local_devices()[0]

    def _extract_program_costs(self) -> None:
        """Price every dispatch kind ONCE: sum XLA ``cost_analysis``
        over the kind's programs (prefill = target chunk [+ draft
        chunk]; decode = fused step, or propose + verify under
        speculation), lowered against the live buffers — tracing only,
        zero compiles, zero executions.  Any program the backend will
        not price drops the whole kind to the analytic transformer
        formulas at a representative context of half the cache."""
        S, rows = self.max_slots, self._policy.prefill_rows
        c = self._policy.chunk
        zt = self._h2d(jnp.zeros((S,), jnp.int32))
        zk = self._h2d(jax.random.PRNGKey(0))
        t1 = self._temp_const
        ids = self._h2d(jnp.zeros((rows, c), jnp.int32))
        rpos = self._h2d(jnp.zeros((rows,), jnp.int32))
        if self.paged:
            zT = self._h2d(jnp.zeros((S, self._table_len), jnp.int32))
            zTr = self._h2d(jnp.zeros((rows, self._table_len),
                                      jnp.int32))
            progs = {"prefill": [(self._chunk_jit,
                                  (self._params, self._buffers, ids,
                                   self._kv_pool, zTr, rpos, rpos))]}
            if self.draft is None:
                progs["decode"] = [(self._step_jit,
                                    (self._params, self._buffers, zt,
                                     zt, self._kv_pool, zT, zk, t1))]
            else:
                progs["prefill"].append(
                    (self._d_chunk_jit,
                     (self._d_params, self._d_bufs, ids,
                      self._d_kv_pool, zTr, rpos, rpos)))
                try:
                    props_sd, qlog_sd, _ = jax.eval_shape(
                        self._propose_jit, self._d_params,
                        self._d_bufs, zt, zt, self._d_kv_pool, zT,
                        zk, t1)
                except Exception:
                    props_sd = qlog_sd = None
                progs["decode"] = [
                    (self._propose_jit,
                     (self._d_params, self._d_bufs, zt, zt,
                      self._d_kv_pool, zT, zk, t1))]
                if props_sd is not None:
                    progs["decode"].append(
                        (self._spec_verify_jit,
                         (self._params, self._buffers, zt, props_sd,
                          qlog_sd, zt, self._kv_pool, zT, zk, t1)))
        else:
            progs = {"prefill": [(self._chunk_jit,
                                  (self._params, self._buffers, ids,
                                   self._staging, rpos, rpos))]}
            if self.draft is None:
                progs["decode"] = [(self._step_jit,
                                    (self._params, self._buffers, zt,
                                     zt, self._caches, zk, t1))]
            else:
                progs["prefill"].append(
                    (self._d_chunk_jit,
                     (self._d_params, self._d_bufs, ids,
                      self._d_staging, rpos, rpos)))
                try:
                    props_sd, qlog_sd, _ = jax.eval_shape(
                        self._propose_jit, self._d_params,
                        self._d_bufs, zt, zt, self._d_caches, zk, t1)
                except Exception:
                    props_sd = qlog_sd = None
                progs["decode"] = [
                    (self._propose_jit,
                     (self._d_params, self._d_bufs, zt, zt,
                      self._d_caches, zk, t1))]
                if props_sd is not None:
                    progs["decode"].append(
                        (self._spec_verify_jit,
                         (self._params, self._buffers, zt, props_sd,
                          qlog_sd, zt, self._caches, zk, t1)))
        ctx = self._phys_len // 2
        g = self._spec.gamma if self._spec is not None else 0
        analytic = {
            "prefill": (rows * c, ctx),
            "decode": (S * (g + 1) if g else S, ctx),
        }
        kv_tree = self._kv_pool if self.paged else self._caches
        cache_itemsize = int(jax.tree.leaves(kv_tree)[0]
                             .dtype.itemsize)
        for kind, entries in progs.items():
            costs = [program_cost(fn, *args) for fn, args in entries]
            if all(cst is not None for cst in costs):
                self._cost.set_program_cost(
                    kind, sum(cst["flops"] for cst in costs),
                    sum(cst["bytes"] for cst in costs), "xla")
                continue
            tokens, c_ctx = analytic[kind]
            flops = self.model.analytic_flops(tokens, c_ctx)
            byts = self.model.analytic_bytes(tokens, c_ctx,
                                             cache_itemsize)
            if self.draft is not None:
                # the draft's share of the kind: its own chunk during
                # prefill, gamma propose steps during decode
                d_tok = rows * c if kind == "prefill" else S * g
                flops += self.draft.analytic_flops(d_tok, c_ctx)
                byts += self.draft.analytic_bytes(d_tok, c_ctx,
                                                  cache_itemsize)
            self._cost.set_program_cost(kind, flops, byts, "analytic")

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ContinuousBatchingEngine":
        """Start the loop thread (idempotent; ``submit`` auto-starts)."""
        with self._lifecycle:
            if self._crashed is not None:
                raise EngineStopped(
                    "engine loop crashed; construct a new engine"
                ) from self._crashed
            if self._thread is None or not self._thread.is_alive():
                self._stop_evt.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="serving-engine", daemon=True)
                self._thread.start()
            self._ts.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Stop the loop thread. ``drain=True`` first waits (up to
        ``timeout``) for queued + running requests to finish; any
        request still unfinished when the loop halts fails with
        ``EngineStopped``."""
        if drain and self._thread is not None and self._thread.is_alive():
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while self._has_work():
                if self._crashed is not None or (
                        deadline is not None
                        and time.monotonic() > deadline):
                    break
                time.sleep(0.002)
        self._stop_evt.set()
        self._ts.stop()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # the loop is wedged inside a device dispatch: leave
                # its slot/admission state alone (mutating it under a
                # live loop would crash the loop on resume) — it will
                # observe _stop_evt and exit when the dispatch returns;
                # call stop() again then to fail the leftovers
                return
        err = EngineStopped("engine stopped before the request finished")
        for h in self._queue.drain():
            self._finish_handle(h, err, "stopped")
        for key in list(self._promotions):
            self._drop_promotion(key)
        for a in self._adms:
            if a.entry is not None:
                self._prefix.release(a.entry)
                a.entry = None
            if a.table is not None:
                a.table.free()
                a.table = None
            if a.d_table is not None:
                a.d_table.free()
                a.d_table = None
            self._finish_handle(a.handle, err, "stopped")
        self._adms = []
        for sid, st in enumerate(self._slots):
            if st is not None:
                self._finish_handle(st.handle, err, "stopped")
                self._slots[sid] = None
            self._free_slot_table(sid)
        if self.paged:
            # leak invariant: after the tables above and the index's
            # retained entries release their references, every page
            # is back on the free list (pages_in_use == 0 — tested)
            if self._prefix is not None:
                self._prefix.drop_all()
            self._sync_page_gauges()

    def drain(self) -> None:
        """Stop admitting NEW requests while everything already
        submitted (queued, prefilling, decoding) runs to completion —
        the loop keeps iterating, the slots empty out on their own.
        Further ``submit`` calls raise ``EngineDraining`` until
        ``resume()``; a fleet supervisor uses this pair to take a
        degraded replica out of rotation without dropping a single
        in-flight request. Idempotent; observable as
        ``healthz()["draining"]``."""
        if self._draining:
            return
        self._draining = True
        self._rec.record("engine/drain", self.service_name,
                         service=self.service_name,
                         in_flight=len(self._queue) + len(self._adms)
                         + sum(s is not None for s in self._slots))

    def resume(self) -> None:
        """Lift a ``drain()``: the engine admits new requests again
        (the rejoin half of the fleet drain lifecycle). Idempotent."""
        if not self._draining:
            return
        self._draining = False
        self._rec.record("engine/resume", self.service_name,
                         service=self.service_name)

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop(drain=exc_type is None)

    def _has_work(self) -> bool:
        return (len(self._queue) > 0 or len(self._adms) > 0
                or any(s is not None for s in self._slots))

    # ---------------------------------------------------------- client
    def submit(self, prompt_ids, max_new_tokens: int,
               timeout_s: Optional[float] = None, block: bool = True,
               queue_timeout_s: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: str = "normal",
               trace_id: Optional[str] = None) -> RequestHandle:
        """Queue one request (1-D prompt). Returns its handle
        immediately; stream with ``handle.tokens()`` or block on
        ``handle.result()``. ``timeout_s`` is a wall deadline covering
        queue + prefill + decode (expiry raises ``RequestTimedOut`` from
        the handle — including while blocked on a full queue); a full
        admission queue blocks (``block=True``, up to
        ``queue_timeout_s``) or raises ``QueueFull``.

        ``tenant`` names the workload the request's usage is billed to
        (the usage ledger's attribution key and the
        ``bigdl_serving_tenant_*`` label; ``None`` bills to
        ``"default"``). The first ``usage_tenants`` distinct names get
        their own series; later new names fold into ``"other"`` — the
        cardinality cap that keeps the label space bounded no matter
        what clients send. ``handle.usage()`` returns the request's
        metered consumption.

        ``priority`` (``"high"``/``"normal"``/``"low"``) is the QoS
        class: admission orders by (class, deadline slack, prefix
        score) with per-class starvation bounds; a waiting high-class
        request may PREEMPT a lower-class slot (the victim resumes
        token-identical); under an active TTFT burn the shed set
        (``shed_classes``) is refused with ``RequestShed``, and a
        tenant past its token bucket with ``RequestRateLimited`` —
        both carry ``retry_after_s``.

        ``trace_id`` is the distributed-trace correlation id (the
        fleet front door mints one per request, honoring an inbound
        ``traceparent``): the handle and the usage record carry it,
        and the recorder binds it so EVERY flight-recorder event of
        this request — queue, prefill, per-token decode, terminal —
        is joinable across processes in the merged fleet trace."""
        if self._crashed is not None:
            raise EngineStopped("engine loop crashed") from self._crashed
        if self._draining:
            raise EngineDraining(
                "engine is draining: in-flight requests are finishing "
                "but new submissions are refused (resume() to rejoin)")
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError("submit takes ONE request (1-D prompt), "
                             f"got shape {prompt.shape}")
        t0, n = prompt.shape[0], int(max_new_tokens)
        if n < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if t0 < 1 or t0 + n > self.max_len:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({n}) exceeds the "
                f"engine's serving window {self.max_len}")
        if self.paged:
            # the request's FULL page reservation (admission reserves
            # the whole span eagerly — the no-mid-flight-OOM contract)
            # must fit the pool even with every other page free
            g = self._spec.gamma if self._spec is not None else 0
            need = pages_needed(min(t0 + n + g, self._phys_len),
                                self.page_size)
            usable = self._pages.max_pages - 1  # page 0 is scratch
            if need > usable:
                raise ValueError(
                    f"request needs {need} KV pages but the pool only "
                    f"has {usable} allocatable (max_pages="
                    f"{self._pages.max_pages} minus the scratch page) "
                    f"— raise max_pages or shorten the request")
        self.start()
        h = RequestHandle(prompt, n, timeout_s, priority=priority)
        if trace_id is not None:
            h.trace_id = trace_id
            # one binding covers the request's whole recorded arc —
            # every layer that records with this request_id (queue,
            # loop, usage ledger) inherits the trace attr for free
            self._rec.bind_request(h.request_id, trace=trace_id)
        h._usage = self._usage.begin(h.request_id, tenant, t0, n,
                                     submitted_at=h.submitted_at)
        h._usage.trace_id = trace_id
        h.tenant = h._usage.tenant
        self._rec.record("request/submitted", h.request_id,
                         service=self.service_name, prompt_tokens=t0,
                         max_new_tokens=n, tenant=h.tenant,
                         priority=priority)
        # ---- QoS gates, cheapest-first: burn-rate shedding, then the
        # tenant's token bucket. Both are structured rejections (the
        # handle finishes through the _finish_handle funnel with its
        # outcome, the ledger bills the queue-side life, the front
        # door maps them to 429 + Retry-After) — never silent drops.
        shed = self._shed_state()
        if shed["active"] and priority in shed["classes"]:
            retry = self._shed_retry_after_s(shed)
            err = RequestShed(
                f"shed at admission: TTFT SLO burning at "
                f"{shed['burn_rate']:.1f}x budget "
                f"({shed['source']}), class {priority!r} is in the "
                f"shed set — retry in {retry:.2f}s",
                retry_after_s=retry)
            self._reject_qos(h, err, "shed")
            raise err
        bucket = self._tenant_bucket(h.tenant)
        if bucket is not None and not bucket.try_admit():
            retry = bucket.retry_after()
            err = RequestRateLimited(
                f"tenant {h.tenant!r} exhausted its device-second "
                f"budget (bucket {bucket.level():.3f}s, refill "
                f"{bucket.rate:.3f}/s) — retry in {retry:.2f}s",
                retry_after_s=retry)
            self._reject_qos(h, err, "rate_limited")
            raise err
        try:
            self._queue.put(h, block=block, timeout=queue_timeout_s)
        except Exception as e:
            # close the ledger, then the timeline — a backpressure
            # rejection must not read as a request that vanished
            # mid-flight, and the outcome event stays the LAST event
            # of the request's recorded arc (same order as
            # _finish_handle)
            self._usage.finalize(h._usage, "rejected",
                                 time.monotonic())
            self._rec.record("request/rejected", h.request_id,
                             service=self.service_name,
                             error=type(e).__name__)
            if isinstance(e, RequestTimedOut):
                self._ins.timed_out_total.inc()
            raise
        with self._wake:
            self._wake.notify_all()
        # submit can race stop() or a loop crash: if the loop died
        # between our start() and the put (both paths drain the queue
        # from the dying side, so a put landing after that drain would
        # otherwise strand the handle forever), drain-and-fail now
        # rather than hand back a handle nobody will ever finish
        if self._crashed is not None or (
                self._stop_evt.is_set()
                and (self._thread is None
                     or not self._thread.is_alive())):
            err = EngineStopped("engine stopped while the request was "
                                "being submitted")
            if self._crashed is not None:
                err.__cause__ = self._crashed
            for dropped in self._queue.drain():
                self._finish_handle(dropped, err, "stopped")
            self._finish_handle(h, err, "stopped")
            raise err
        return h

    # ------------------------------------------------------ QoS plumbing
    def _shed_state(self) -> dict:
        """The load-shedding decision input: is the TTFT SLO burning
        (really — an active SloWatchdog alert on a ``metric="ttft"``
        objective — or synthetically via the chaos injector), how
        hard, and which priority classes shed as a result. ``low``
        sheds on any active burn; ``normal`` (when opted into
        ``shed_classes``) only once the burn is SEVERE (>= 2x its
        alert threshold); ``high`` never sheds."""
        active = severe = False
        burn = 0.0
        source = None
        if self._chaos is not None and self._chaos.burn_active():
            active = True
            severe = self._chaos.burn_severe()
            burn = 4.0 if severe else 2.0
            source = "chaos"
        else:
            for row in self._slo_wd.state():
                if row["metric"] != "ttft" or not row["active"]:
                    continue
                active = True
                burn = max(burn, row["burn_rate"])
                severe = severe or row["severe"]
                source = "slo:" + row["objective"]
        classes = ()
        if active:
            classes = (self.shed_classes if severe else
                       tuple(p for p in self.shed_classes
                             if p == "low"))
        return {"active": active and bool(classes), "severe": severe,
                "burn_rate": burn, "source": source,
                "classes": classes}

    def _shed_retry_after_s(self, shed: dict) -> float:
        """Back-off hint for a shed rejection: long enough for the
        trailing burn window to move, doubled under a severe burn."""
        return 2.0 if shed["severe"] else 1.0

    def _tenant_bucket(self, tenant: str):
        """The tenant's device-second token bucket, created lazily
        from ``tenant_rate_limits`` (exact name first, then the
        ``"*"`` default); None = unlimited."""
        if not self._rate_limits:
            return None
        with self._buckets_lock:
            b = self._buckets.get(tenant)
            if b is not None:
                return b
            cfg = self._rate_limits.get(tenant,
                                        self._rate_limits.get("*"))
            if cfg is None:
                return None
            if isinstance(cfg, dict):
                b = TokenBucket(cfg["rate"], cfg["burst"])
            else:
                rate, burst = cfg
                b = TokenBucket(rate, burst)
            self._buckets[tenant] = b
            return b

    def _reject_qos(self, h: RequestHandle, err: Exception,
                    outcome: str) -> None:
        """Terminal bookkeeping for a structured QoS rejection
        (shed / rate_limited): through the ``_finish_handle`` funnel —
        the ledger bills the queue-side life under the real outcome,
        the ``request/shed`` / ``request/rate_limited`` event stays
        the last of the request's recorded arc, and the
        ``(class, tenant)``-labelled QoS counter increments. The
        caller raises ``err`` to the submitter."""
        self._qos_counts[outcome] += 1
        getattr(self._qos_ins, outcome + "_total").labels(
            self.service_name, h.priority, h.tenant).inc()
        self._finish_handle(h, err, outcome)

    def _finish_handle(self, h: RequestHandle,
                       err: Optional[BaseException],
                       outcome: str) -> None:
        """Terminal bookkeeping for ONE request — recorder event,
        stream sentinel, finished-timeline ring entry. Every lifecycle
        exit (finished / cancelled / timed_out / stopped / crashed)
        funnels through here so the flight recorder and the stats()
        percentiles can never disagree with the handles. ``_finish``
        arbitrates racing finishers (a stopping submitter vs. the
        crashing loop) — only the winner records."""
        if not h._finish(err):
            return
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # the usage ledger's terminal funnel shares _finish's
            # arbitration: exactly one finalizer closes residencies,
            # bills the tenant, and records request/usage_final —
            # BEFORE the outcome event, which stays the last event of
            # every request's recorded timeline (tested contract)
            self._usage.finalize(rec, outcome, h.finished_at)
            # post-paid rate limiting: the bucket consumes the
            # request's MEASURED device-seconds at the same terminal
            # point the ledger bills them
            bucket = self._tenant_bucket(rec.tenant)
            if bucket is not None and rec.device_s > 0:
                bucket.debit(rec.device_s)
        # a preemption pin that never reached re-admission (the victim
        # finished/cancelled/timed out while requeued) must not leak a
        # pinned prefix entry
        pin = h.__dict__.pop("_preempt_pin", None)
        if pin is not None and self._prefix is not None:
            self._prefix.release(pin)
        self._rec.record("request/" + outcome, h.request_id,
                         service=self.service_name,
                         tokens=len(h._tokens),
                         tenant=getattr(h, "tenant", None))
        tl = h.timeline()
        tl["request_id"] = h.request_id
        tl["outcome"] = outcome
        tl["tenant"] = getattr(h, "tenant", None)
        tl["trace_id"] = getattr(h, "trace_id", None)
        tl["page_waited"] = bool(getattr(h, "_page_waited", False))
        with self._timelines_lock:
            self._timelines.append(tl)

    def _counter(self, key: str):
        return getattr(self._ins, key + "_total")

    def stats(self) -> dict:
        """Operational façade over the registry series (same pattern —
        and same shared-``service_name`` caveat — as the batch
        services' ``stats()``): flow counters are the delta since THIS
        engine was constructed. ``latency`` adds per-phase percentile
        summaries (queue wait / prefill / TTFT / decode / total,
        each ``{count, mean, p50, p90, p99}``) computed from the
        engine's recent finished-request timelines; ``prefix_cache``
        adds the cache's hit rate, reused-token fraction, and current
        byte occupancy (per-instance exact — the cache object belongs
        to this engine); ``usage`` adds the ledger's per-tenant
        attribution table and the engine goodput block (device
        seconds by kind, occupancy-weighted utilization, padding
        waste, tokens per device-second); ``cost`` adds the dispatch
        cost model's per-kind FLOPs/bytes, achieved FLOP/s and
        bytes/s, MFU/bandwidth-utilization fractions, and the
        compute-vs-memory-bound roofline class; ``loop`` adds the
        loop-phase breakdown attributing the device-idle fraction to
        named host-side bubbles."""
        out = {k: int(self._counter(k).get() - base)
               for k, base in self._stats_base.items()}
        out["active_slots"] = sum(s is not None for s in self._slots)
        out["queue_depth"] = len(self._queue)
        out["jit_compiles"] = self._compile_total()
        out["latency"] = self._latency_summary()
        out["prefix_cache"] = self._prefix_summary()
        out["speculation"] = self._spec_summary()
        out["quantization"] = self._quant_summary()
        out["mesh"] = self._mesh_summary()
        out["usage"] = self._usage.summary()
        out["cost"] = self._cost.summary()
        out["loop"] = self._loop_obs.summary()
        out["slo_budget"] = self._slo_budget.state()
        out["capacity"] = self._capacity_summary(
            loop=out["loop"], cost=out["cost"], usage=out["usage"])
        out["qos"] = self._qos_summary()
        if self.paged:
            out["paging"] = self._paging_summary()
        out["alerts"] = self.alerts()
        out["incidents"] = {"count": self._incidents.total,
                            "by_kind": self._incidents.counts_by_kind()}
        return out

    def _qos_summary(self) -> dict:
        """The ``stats()["qos"]`` block: shedding state (is the TTFT
        SLO burning, which classes shed), the preempted / shed /
        rate-limited tallies, queue composition by class, and each
        provisioned tenant bucket's balance."""
        shed = self._shed_state()
        with self._buckets_lock:
            buckets = {t: b.snapshot()
                       for t, b in sorted(self._buckets.items())}
        out = {
            "shedding": {"active": shed["active"],
                         "severe": shed["severe"],
                         "burn_rate": round(shed["burn_rate"], 3),
                         "source": shed["source"],
                         "classes": list(shed["classes"])},
            "shed_classes_configured": list(self.shed_classes),
            "preempt_slack_s": self.preempt_slack_s,
            "queue_by_class": self._queue.depth_by_class(),
            "rate_limits": buckets,
            **self._qos_counts,
        }
        if self._chaos is not None:
            out["chaos"] = self._chaos.snapshot()
        return out

    def alerts(self) -> List[dict]:
        """The active watchdog alerts (recompile storm, SLO burns) as
        plain dicts — empty while the engine is healthy. The same list
        rides in ``stats()["alerts"]`` and the ``/healthz`` body."""
        out = []
        storm = self._recompile_wd.alert()
        if storm is not None:
            out.append(storm)
        out.extend(self._slo_wd.alerts())
        return out

    def _prefix_summary(self) -> dict:
        if self._prefix is None:
            return {"enabled": False}
        ps = self._prefix.stats()
        prefilled = self._prefilled_tokens
        denom = ps["reused_tokens"] + prefilled
        return {
            "enabled": True,
            **ps,
            "prefilled_tokens": prefilled,
            "reused_fraction": (round(ps["reused_tokens"] / denom, 4)
                                if denom else 0.0),
        }

    def _quant_summary(self) -> dict:
        """The ``stats()["quantization"]`` block: which numerics the
        hot path runs and what one KV slot row physically costs —
        ``kv_row_bytes`` (scale sidecars included) next to
        ``fp_row_bytes`` (the same geometry at full precision), whose
        ratio is the capacity multiplier quantization bought (rows per
        HBM byte scale by its inverse)."""
        return {
            "kv_dtype": self.kv_dtype or "fp",
            "weights_dtype": self.weights_dtype or "fp",
            "kv_row_bytes": int(self._row_bytes),
            "fp_row_bytes": int(self._fp_row_bytes),
            "row_bytes_ratio": (round(self._row_bytes
                                      / self._fp_row_bytes, 4)
                                if self._fp_row_bytes else 1.0),
        }

    def _spec_summary(self) -> dict:
        """The ``stats()["speculation"]`` block: per-instance proposed
        vs accepted draft-token tallies and the acceptance rate (the
        gamma-tuning signal — a rate near 1 says raise gamma, a rate
        near 0 says the draft disagrees with the target and every
        round degenerates to one corrected token)."""
        if self._spec is None:
            return {"enabled": False}
        prop = self._spec_proposed
        return {
            "enabled": True,
            "gamma": self._spec.gamma,
            "proposed_tokens": prop,
            "accepted_tokens": self._spec_accepted,
            "acceptance_rate": (round(self._spec_accepted / prop, 4)
                                if prop else 0.0),
        }

    def _latency_summary(self) -> dict:
        from bigdl_tpu.observability.events import percentile_summary

        with self._timelines_lock:
            snap = list(self._timelines)
        tls = [t for t in snap if t.get("outcome") == "finished"]
        return {phase: percentile_summary(
                    t[phase + "_s"] for t in tls)
                for phase in ("queue_wait", "prefill", "ttft",
                              "decode", "total")}

    def healthz(self) -> dict:
        """Liveness probe for ``MetricsHTTPServer(healthz=...)``: a
        status dict while the engine is serviceable, raising
        ``EngineStopped`` once the loop thread has crashed — the
        endpoint then flips to 503 instead of reporting a dead decode
        loop as healthy. While a watchdog alert is active the body
        carries ``status: degraded`` plus the alert list — still HTTP
        200 (the engine serves; 503 remains the crashed-loop signal),
        so orchestrators keep routing while operators see the fire."""
        if self._crashed is not None:
            raise EngineStopped(
                f"engine loop crashed: {self._crashed!r}"
            ) from self._crashed
        alerts = self.alerts()
        return {
            # always present: direct callers key on it, not only the
            # HTTP handler (which would merge in an "ok" of its own)
            "status": "degraded" if alerts else "ok",
            "engine": self.service_name,
            "loop_alive": bool(self._thread is not None
                               and self._thread.is_alive()),
            "active_slots": sum(s is not None for s in self._slots),
            "queue_depth": len(self._queue),
            # machine-readable drain state: a fleet supervisor keys on
            # status (degraded -> drain) + draining (rejoin gate) + the
            # in-flight count (drain completion), never on body prose
            "draining": self._draining,
            "in_flight": (len(self._queue) + len(self._adms)
                          + sum(s is not None for s in self._slots)),
            # compact QoS posture: is load shedding live right now,
            # and how much traffic has been preempted/shed/throttled
            # so far — the full picture lives in stats()["qos"]
            "qos": {"shedding": self._shed_state()["active"],
                    **self._qos_counts},
            "alerts": alerts,
        }

    def debug_requests(self) -> dict:
        """The ``/debug/requests`` payload: every in-flight request's
        id, phase, and progress, the recent finished timelines with
        their queue-wait/prefill/TTFT/decode breakdown (now including
        per-request ``prefix_tokens``), the percentile summary over
        them, and the prefix-cache occupancy/hit-rate block. Snapshot
        semantics — safe to call from an HTTP thread while the loop
        runs."""
        now = time.monotonic()
        in_flight = []
        for h in self._queue.snapshot():
            in_flight.append({
                "request_id": h.request_id, "state": "queued",
                "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
                "priority": h.priority, "preempted": h.preempted,
            })
        for adm in list(self._adms):
            h = adm.handle
            row = {
                "request_id": h.request_id, "state": "prefill",
                "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
                "priority": h.priority, "preempted": h.preempted,
                "chunks_done": adm.next_chunk,
                "chunks_total": adm.n_chunks,
                "staging_row": adm.row,
                "prefix_tokens": adm.base,
            }
            if self.draft is not None:
                row["draft_chunks_done"] = adm.d_next_chunk
                row["draft_chunks_total"] = adm.d_n_chunks
            in_flight.append(row)
        for sid, st in enumerate(list(self._slots)):
            if st is None:
                continue
            h = st.handle
            in_flight.append({
                "request_id": h.request_id, "state": "decoding",
                "slot": sid, "age_s": now - h.submitted_at,
                "prompt_tokens": int(h.prompt.shape[0]),
                "max_new_tokens": h.max_new_tokens,
                "tenant": getattr(h, "tenant", None),
                "priority": h.priority, "preempted": h.preempted,
                "tokens_delivered": st.delivered,
            })
        with self._timelines_lock:
            recent = list(self._timelines)[-50:]
        return {"service": self.service_name,
                "in_flight": in_flight,
                "recent": recent,
                "latency": self._latency_summary(),
                "prefix_cache": self._prefix_summary(),
                "speculation": self._spec_summary(),
                "mesh": self._mesh_summary(),
                "alerts": self.alerts()}

    def debug_usage(self, top_n: int = 10) -> dict:
        """The ``GET /debug/usage`` payload: the per-tenant usage
        table (tokens, queue seconds, device-seconds, KV
        byte-seconds, prefix savings), engine-wide totals, the
        goodput block, and the top-``top_n`` recently finished
        requests by attributed device-seconds. Snapshot semantics —
        safe from HTTP threads while the loop runs."""
        return {"service": self.service_name,
                **self._usage.summary(top_n=top_n)}

    def debug_timeseries(self, metric: Optional[str] = None,
                         n: Optional[int] = None) -> dict:
        """The ``GET /debug/timeseries?metric=&n=`` payload: the
        background sampler's bounded rings (MFU, tokens/s, slot
        occupancy, queue depth, acceptance rate, alert count) as
        ``{metric: {points: [[monotonic_ts, value], ...], last}}``.
        Snapshot semantics — safe from HTTP threads."""
        return {"service": self.service_name,
                "running": self._ts.running,
                **self._ts.snapshot(metric=metric, n=n)}

    def debug_incidents(self, n: Optional[int] = None) -> dict:
        """The ``GET /debug/incidents[?n=]`` payload: the newest
        ``n`` captured bundles plus the lifetime count and per-kind
        tallies. Snapshot semantics — safe from HTTP threads while
        the loop runs; the same shape ships over the fleet's
        ``incident_export`` RPC."""
        n = 10 if n is None else int(n)
        return {"service": self.service_name,
                "count": self._incidents.total,
                "by_kind": self._incidents.counts_by_kind(),
                "detectors": self._bank.states(),
                "incidents": self._incidents.snapshot(n)}

    def _capacity_summary(self, loop=None, cost=None,
                          usage=None) -> dict:
        """The ``stats()["capacity"]`` block: the what-if model over
        this engine's measured loop / cost / usage summaries."""
        from bigdl_tpu.observability.capacity import estimate_capacity

        return estimate_capacity(
            loop if loop is not None else self._loop_obs.summary(),
            cost if cost is not None else self._cost.summary(),
            usage if usage is not None else self._usage.summary(),
            max_slots=self.max_slots, service=self.service_name)

    def debug_capacity(self) -> dict:
        """The ``GET /debug/capacity`` payload: the capacity/what-if
        estimate plus the error-budget ledger — everything an
        autoscaling policy (or an operator sizing a fleet) reads.
        Snapshot semantics — safe from HTTP threads."""
        return {"service": self.service_name,
                "capacity": self._capacity_summary(),
                "slo_budget": self._slo_budget.state()}

    def dashboard(self) -> str:
        """The ``GET /debug/dashboard`` page: one self-contained HTML
        document (inline CSS + SVG sparklines, zero external assets)
        over the sampler rings, plus the live cost/roofline, loop
        bubble, and alert blocks. Captured incidents and fired
        triggers draw vertical markers on every sparkline; watched
        SLO objectives draw error-budget bars under the grid."""
        markers = [{"ts_s": t.get("ts_s"), "kind": "alert",
                    "label": t.get("detector")}
                   for t in self._incidents.history()]
        markers += [{"ts_s": b.get("ts_s"), "kind": "incident",
                     "label": "%s (%s)" % (b.get("id"),
                                           b.get("kind"))}
                    for b in self._incidents.snapshot()]
        markers.sort(key=lambda m: m.get("ts_s") or 0.0)
        return render_dashboard(
            self._ts.snapshot(), title=self.service_name,
            extra={"alerts": self.alerts() or None,
                   "incidents": (self._incidents.counts_by_kind()
                                 or None),
                   "cost": self._cost.summary(),
                   "loop": self._loop_obs.summary(),
                   "capacity": self._capacity_summary()},
            markers=markers,
            budgets=self._slo_budget.budget_bars() or None)

    # ------------------------------------------------------- loop body
    def _loop(self):
        from bigdl_tpu.observability import trace

        try:
            while not self._stop_evt.is_set():
                # idle engines BLOCK (submit/stop notify the condition;
                # idle_wait_s is only a lost-wakeup safety net) instead
                # of spinning no-op iterations that would burn CPU and
                # flood the tracer/iteration metrics. An empty engine
                # has no deadlines to sweep — queued deadlines imply
                # _has_work() and a live loop.
                with self._wake:
                    while (not self._stop_evt.is_set()
                           and not self._has_work()):
                        self._wake.wait(self.idle_wait_s)
                if self._stop_evt.is_set():
                    break
                with trace.span("serving/iteration",
                                histogram=self._ins.iteration_seconds):
                    self._iterate()
                self._ins.iterations_total.inc()
        except BaseException as e:  # donated buffers may be gone: crash
            self._crash(e)

    def _crash(self, e: BaseException) -> None:
        with self._lifecycle:
            self._crashed = e
        self._rec.record("engine/crash", service=self.service_name,
                         error=repr(e))
        # capture the in-flight picture BEFORE failing the handles —
        # the postmortem must show what the engine was doing when it
        # died, not the already-cleaned-up aftermath
        try:
            states = self.debug_requests()["in_flight"]
        except Exception:
            states = []
        self._write_postmortem(e, states)
        # the crash is itself an incident: same evidence pipeline as
        # the anomaly/watchdog triggers, kind "crash" — a fleet
        # supervisor aggregating incident_export sees the dead
        # replica's last picture without reading its postmortem file
        self._capture_incident(
            {"detector": "engine", "metric": "loop", "kind": "crash",
             "reason": f"engine loop crashed: {e!r}",
             "ts_s": time.monotonic(), "value": 1.0, "score": 1.0},
            error=e)
        err = EngineStopped(f"engine loop crashed: {e!r}")
        err.__cause__ = e
        for key in list(self._promotions):
            self._drop_promotion(key)
        for a in self._adms:
            if a.entry is not None:
                self._prefix.release(a.entry)
                a.entry = None
            if a.table is not None:
                a.table.free()
                a.table = None
            if a.d_table is not None:
                a.d_table.free()
                a.d_table = None
            self._finish_handle(a.handle, err, "crashed")
        self._adms = []
        for sid, st in enumerate(self._slots):
            if st is not None:
                self._finish_handle(st.handle, err, "crashed")
                self._slots[sid] = None
            self._free_slot_table(sid)
        if self.paged and self._prefix is not None:
            self._prefix.drop_all()
        for h in self._queue.drain():
            self._finish_handle(h, err, "crashed")

    def _write_postmortem(self, e: BaseException,
                          states: List[dict]) -> None:
        """Best-effort crash black box — the crash path must never
        raise (donated buffers are already gone; all that is left is
        to preserve the evidence)."""
        import os

        from bigdl_tpu.observability.postmortem import write_postmortem

        path = (self.postmortem_path
                or os.environ.get("BIGDL_POSTMORTEM_PATH")
                or "bigdl_postmortem.json")
        try:
            write_postmortem(
                path, error=e, requests=states, recorder=self._rec,
                registry=self._registry,
                context={"service": self.service_name,
                         "max_slots": self.max_slots,
                         "max_len": self.max_len,
                         "queue_depth": len(self._queue),
                         "stats": {k: int(self._counter(k).get() - b)
                                   for k, b in
                                   self._stats_base.items()}})
            print(f"[bigdl_tpu.serving] engine {self.service_name!r} "
                  f"crashed: {e!r}; postmortem -> {path}",
                  file=sys.stderr)
        except Exception as pe:
            print(f"[bigdl_tpu.serving] postmortem write failed: "
                  f"{pe!r} (crash: {e!r})", file=sys.stderr)

    def _process_triggers(self, occupied: List[int],
                          advanced: List[int]) -> None:
        """Once-per-iteration incident funnel: drain detector
        triggers recorded on the sampler thread, feed the
        iteration-scale stall detector (a live slot that stops
        advancing — sampler cadence is far too coarse for that), and
        map active watchdog alerts (plus a chaos-forced burn, which
        mints no real watchdog alert) onto the same stream. Every
        surviving trigger becomes one capture attempt, deduped by the
        manager's per-kind cooldown. Host-side bookkeeping only."""
        now = time.monotonic()
        triggers = self._bank.drain()
        triggers += self._bank.observe_iteration(now, occupied,
                                                 advanced)
        alerts = self.alerts()
        if self._chaos is not None and self._chaos.burn_active():
            alerts = alerts + [{"alert": "slo:forced_burn",
                                "severity": "critical",
                                "forced": True}]
        triggers += self._bank.alert_triggers(alerts, now)
        for t in triggers:
            name = str(t.get("detector", "detector"))
            c = self._trig_counters.get(name)
            if c is None:
                c = self._inc_ins.triggers_total.labels(
                    self.service_name, name)
                self._trig_counters[name] = c
            c.inc()
            self._capture_incident(t)
        for name, state in self._bank.states().items():
            g = self._det_gauges.get(name)
            if g is None:
                g = self._inc_ins.detector_state.labels(
                    self.service_name, name)
                self._det_gauges[name] = g
            g.set(1.0 if state == "firing" else 0.0)

    def _capture_incident(self, trigger: dict,
                          error: Optional[BaseException] = None):
        """Hand one trigger to the incident manager with the live
        evidence: the finished-timeline ring (exemplar source), the
        qos/latency/cost/loop stats blocks, and the memory/page-pool
        picture. Best-effort — capture must never take down the loop
        (or the crash path, which also funnels through here)."""
        try:
            with self._timelines_lock:
                tls = list(self._timelines)
            stats = {
                "qos": self._qos_summary(),
                "latency": self._latency_summary(),
                "cost": self._cost.summary(),
                "loop": self._loop_obs.summary(),
                "queue_depth": len(self._queue),
                "active_slots": sum(s is not None
                                    for s in self._slots),
                "jit_compiles": self._compile_total(),
            }
            memory = {"pools": self._pool_bytes}
            if self.paged:
                memory["paging"] = self._paging_summary()
            return self._incidents.capture(
                trigger, timelines=tls, stats=stats, memory=memory,
                error=error)
        except Exception:
            return None

    def _iterate(self) -> bool:
        now = time.monotonic()
        worked = False
        lo = self._loop_obs
        # per-iteration dispatch scratch: _prefill_round /
        # _decode_all* accumulate their dispatch walls here so the
        # boundary-measured host segments below can subtract them out
        # — phase seconds then sum to the iteration wall by
        # construction
        self._iter_disp = {"prefill": 0.0, "decode": 0.0}
        # paged: a fresh iteration may admit again — pages freed by
        # the releases/donations above can satisfy what blocked before
        self._adm_blocked = False
        if self._chaos is not None:
            self._chaos.begin_iteration()

        # 1. running slots: cancellation + deadline eviction
        for sid, st in enumerate(self._slots):
            if st is None:
                continue
            h = st.handle
            if h.cancelled:
                self._release(sid, RequestCancelled(
                    f"cancelled after {st.delivered} tokens"),
                    "cancelled")
            elif h.deadline is not None and now > h.deadline:
                self._release(sid, RequestTimedOut(
                    f"deadline passed mid-decode after {st.delivered} "
                    "tokens (partial output in tokens_so_far())"),
                    "timed_out")
        # ... and the admissions in progress
        for a in list(self._adms):
            h = a.handle
            err = kind = None
            if h.cancelled:
                err, kind = RequestCancelled(
                    "cancelled during prefill"), "cancelled"
            elif h.deadline is not None and now > h.deadline:
                err, kind = RequestTimedOut(
                    "deadline passed during prefill"), "timed_out"
            if err is not None:
                self._abort_admission(a, err, kind)

        # 2. queued requests: mid-queue deadline/cancel sweep
        for h, err in self._queue.sweep(now):
            self._finish_dropped(h, err)
        t_sweep = time.monotonic()
        lo.add("sweep", t_sweep - now)

        # 3. admission: prefix-aware intake + batched chunked-prefill
        #    rounds under this iteration's budget — every round
        #    advances ALL staged admissions together through one
        #    ragged dispatch
        self._policy.begin_iteration()
        while True:
            self._fill_admissions(now)
            if not self._adms or not self._policy.take_chunk():
                break
            self._prefill_round()
            worked = True
        t_adm = time.monotonic()
        # the prefill dispatch walls were phase-attributed inside
        # _prefill_round; the segment's remainder is host admission work
        lo.add("admission",
               max(0.0, t_adm - t_sweep - self._iter_disp["prefill"]))

        # 4. one fused decode step over every occupied slot
        occupied = [sid for sid, st in enumerate(self._slots)
                    if st is not None]
        active = list(occupied)
        if self._chaos is not None:
            # frozen slots sit out this round's fused step (their KV
            # and handle are untouched — they resume when the freeze
            # expires), simulating a straggler row
            active = [sid for sid in active
                      if not self._chaos.slot_frozen(sid)]
        if active:
            self._decode_all(active)
            worked = True
        t_dec = time.monotonic()
        # decode-segment remainder = sampling transfers + stream
        # delivery around the dispatch ("deliver" bubble)
        lo.add("deliver",
               max(0.0, t_dec - t_adm - self._iter_disp["decode"]))

        # 5. load gauges + watchdog sampling (one probe read and one
        #    histogram snapshot per objective — iteration-rate cheap)
        ins = self._ins
        ins.active_slots.set(sum(s is not None for s in self._slots))
        ins.queue_depth.set(len(self._queue))
        ins.jit_compiles.set(self._compile_total())
        if self.paged:
            self._accrue_paged_kv()
            self._sync_page_gauges()
        self._recompile_wd.sample()
        self._slo_wd.sample()
        self._slo_budget.sample(
            forced=self._chaos is not None
            and self._chaos.burn_active())
        self._process_triggers(occupied, active)
        mfu_d, bw_d = self._cost.rates("decode")
        if mfu_d is not None:
            ins.mfu_decode.set(mfu_d)
        if bw_d is not None:
            ins.membw_util_decode.set(bw_d)
        mfu_p, bw_p = self._cost.rates("prefill")
        if mfu_p is not None:
            ins.mfu_prefill.set(mfu_p)
        if bw_p is not None:
            ins.membw_util_prefill.set(bw_p)
        lo.iteration()
        lo.add("observe", time.monotonic() - t_dec)
        snap = lo.summary()
        for p, child in self._loop_phase_counters.items():
            delta = snap["phases"][p] - self._loop_flushed[p]
            if delta > 0.0:
                child.inc(delta)
                self._loop_flushed[p] += delta
        ins.loop_idle_fraction.set(snap["device_idle_fraction"])
        return worked

    # ------------------------------------------------ admission stages
    def _free_slot(self) -> Optional[int]:
        # a slot is free when no running request occupies it AND no
        # in-flight admission has reserved it as its insert target
        reserved = {a.slot for a in self._adms}
        for sid, st in enumerate(self._slots):
            if st is None and sid not in reserved:
                return sid
        return None

    def _free_staging_row(self) -> Optional[int]:
        used = {a.row for a in self._adms}
        for r in range(self._policy.prefill_rows):
            if r not in used:
                return r
        return None

    # ------------------------------------------------------ preemption
    def _maybe_preempt(self, now: float) -> bool:
        """With the slot pool exhausted and a high-class request
        waiting past ``preempt_slack_s``, evict one lower-class slot:
        lowest class first, longest-remaining-work tie-break (the
        victim with the most decode left ahead of it loses the least
        sunk progress per unit of freed time). The victim's KV is
        donated to the prefix pool and PINNED, the request requeued
        at the queue head — its automatic re-admission re-prefills
        only the tail the donated entry doesn't cover and resumes
        token-identical. High-class slots are never preempted; a pool
        full of high is simply full. Returns True when a slot was
        freed."""
        if self.preempt_slack_s is None:
            return False
        wait = self._queue.oldest_waiting("high", now)
        if wait is None or wait <= self.preempt_slack_s:
            return False
        victim_sid, victim_key = None, None
        for sid, st in enumerate(self._slots):
            if st is None:
                continue
            rank = PRIORITY_RANK.get(st.handle.priority, 1)
            if rank <= 0:
                continue  # never preempt a high-class slot
            remaining = st.handle.max_new_tokens - st.delivered
            key = (rank, remaining)
            if victim_key is None or key > victim_key:
                victim_sid, victim_key = sid, key
        if victim_sid is None:
            return False
        self._preempt_slot(victim_sid, now)
        return True

    def _preempt_slot(self, sid: int, now: float) -> None:
        st = self._slots[sid]
        h = st.handle
        # the slot's KV covers [0, pos): prompt + generated[:-1] —
        # exactly the donation key a finishing slot would use
        tokens = np.concatenate(
            [h.prompt, np.asarray(h._tokens[:-1], np.int32)])
        self._maybe_donate(sid, tokens, h.request_id)
        if self._prefix is not None:
            # pin the covering entry so the LRU cannot evict the
            # donated KV while the victim waits in the queue — the
            # pin is released at re-admission (or by _finish_handle
            # if the victim times out / is cancelled first). The
            # donation may have been declined (covered / all-pinned):
            # pin whatever entry covers the tokens, if any — a None
            # pin just means the resume re-prefills from scratch,
            # which is still token-identical.
            pin = self._prefix.pin_covering(tokens)
            if pin is not None:
                stale = h.__dict__.pop("_preempt_pin", None)
                if stale is not None:
                    self._prefix.release(stale)
                h._preempt_pin = pin
        self._free_slot_table(sid)
        self._slots[sid] = None
        self._ins.evicted_total.inc()
        h.preempted += 1
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # slot residency closes into kv_byte_seconds and the
            # requeue stamp opens a second queue-wait segment;
            # device-seconds already charged stay charged (the work
            # happened) — NOT a terminal transition
            self._usage.preempted(rec, now)
        self._qos_counts["preempted"] += 1
        self._qos_ins.preempted_total.labels(
            self.service_name, h.priority,
            getattr(h, "tenant", None) or "unknown").inc()
        self._rec.record("request/preempted", h.request_id,
                         service=self.service_name, slot=sid,
                         priority=h.priority, preempted=h.preempted,
                         tokens_so_far=len(h._tokens),
                         donated_tokens=int(tokens.shape[0]))
        self._queue.requeue(h)

    def _fill_admissions(self, now: float) -> None:
        """Start new admissions until the staging cache is full, the
        slot pool is exhausted, or the queue runs dry. With a prefix
        cache and ``admission_window > 1``, the pop prefers the queued
        candidate with the longest cached prefix (bounded bypass —
        see AdmissionQueue.pop_ready)."""
        if self.paged and self._adm_blocked:
            # the pool already refused this iteration's queue head —
            # popping more candidates would just thrash requeues
            return
        scorer = None
        if self.paged and self._prefix is not None \
                and self.admission_window > 1:
            c, ps = self._policy.chunk, self.page_size

            def scorer(h):
                # paged bounded-bypass score: reuse tokens, but a
                # candidate whose FRESH page need exceeds what the
                # pool could cover even after a full prefix reclaim
                # scores negative by the shortfall — electing it
                # would stall the fill loop for nothing
                p = self._effective_prompt(h)
                e, m = self._prefix.lookup(p)
                h._prefix_probe = (e, m, self._prefix.generation)
                base = (min(m, p.shape[0] - 1) // c) * c
                if e is not None and e.tier != "device":
                    base = 0  # promote may still land it, score cold
                g = (self._spec.gamma if self._spec is not None
                     else 0)
                need = pages_needed(
                    min(p.shape[0] + h.max_new_tokens + g,
                        self._phys_len), ps)
                fresh = need - base // ps
                avail = (self._pages.free_pages
                         + self._prefix.device_pages)
                return page_fit_score(base, fresh, avail)
        elif self._prefix is not None and self.admission_window > 1:
            c = self._policy.chunk
            if self._promotions:
                self._prune_promotions(now)

            def scorer(h):
                # score by the USABLE (capped, chunk-aligned) reuse —
                # exactly what _start_admission will skip — so a match
                # that alignment reduces to zero never bypasses the
                # FCFS head for nothing. The raw lookup is stamped on
                # the handle (generation-guarded) so the winner's
                # admission doesn't re-walk the trie. Preempted
                # requests score by their EFFECTIVE prompt (prompt +
                # already-generated tokens) — the donated KV makes
                # them near-perfect hits.
                p = self._effective_prompt(h)
                e, m = self._prefix.lookup(p)
                h._prefix_probe = (e, m, self._prefix.generation)
                if e is not None and e.tier == "host":
                    # host-tier match: start the async device_put NOW,
                    # overlapping this candidate's remaining queue wait
                    # — by its admission the transfer has (usually)
                    # already landed
                    self._begin_promotion(e)
                return (min(m, p.shape[0] - 1) // c) * c
        while len(self._adms) < self._policy.prefill_rows:
            slot = self._free_slot()
            if slot is None:
                # slot pool exhausted: a high-class request waiting
                # past its slack may preempt a lower-class victim
                # (KV donated, victim requeued — see _maybe_preempt)
                if not self._maybe_preempt(now):
                    return
                slot = self._free_slot()
                if slot is None:
                    return
            row = self._free_staging_row()
            if row is None:
                return
            h, dropped = self._queue.pop_ready(
                now, scorer=scorer, window=self.admission_window)
            for hd, err in dropped:
                self._finish_dropped(hd, err)
            if h is None:
                return
            if not self._start_admission(h, slot, row):
                return

    @staticmethod
    def _effective_prompt(h: RequestHandle) -> np.ndarray:
        """What a (re)admission must have in the KV cache before
        decode can continue: the prompt plus every already-generated
        token. Fresh requests: just the prompt. Preempted requests:
        the tail token's KV was never written (variable-advance
        invariant), but its position must still be COMPUTED — its
        logits seed the next token — so the full generated list is
        part of the effective prompt and the re-prefill covers
        exactly the suffix the donated entry doesn't."""
        if h._tokens:
            return np.concatenate(
                [h.prompt, np.asarray(h._tokens, np.int32)])
        return h.prompt

    def _start_admission(self, h: RequestHandle, slot: int,
                         row: int) -> bool:
        """Stage one popped request for chunked prefill. Returns True
        when the admission started; False (paged mode only) when the
        page pool could not cover the request's reservation — the
        request is already requeued at the head and the caller stops
        filling for this iteration."""
        if self.paged:
            return self._start_admission_paged(h, slot, row)
        c = self._policy.chunk
        prompt = self._effective_prompt(h)
        t0 = prompt.shape[0]
        base, entry = 0, None
        if self._prefix is not None:
            # reuse the pop_ready scorer's lookup when it is still
            # valid — the generation guard rejects probes that predate
            # any donation/eviction (a stale entry's pool row may
            # already hold different tokens' KV)
            probe = h.__dict__.pop("_prefix_probe", None)
            if probe is not None and probe[2] == self._prefix.generation:
                e, matched = probe[0], probe[1]
            else:
                e, matched = self._prefix.lookup(prompt)
            if e is not None:
                # cap at t0-1 (the last prompt position must be
                # COMPUTED — its logits seed the first token), then
                # chunk-align DOWN so the tail's chunk geometry — and
                # with it the numerics — matches a cold prefill's, and
                # the padded tail write can never overflow the cache
                base = (min(matched, t0 - 1) // c) * c
            from_host = base > 0 and e.tier == "host"
            if from_host and not self._promote_entry(e):
                # the host row could not be made device-resident
                # (transfer unavailable, every pool row pinned, or the
                # buffer raced away) — a CLEAN miss, never a copy from
                # a reused or uninitialized row
                base, e = 0, None
            if base > 0:
                entry = e
                self._prefix.record_hit(entry, base, host=from_host)
                self._prefix.acquire(entry)
                self._staging = self._copy_row_jit(
                    self._staging, self._pool, jnp.int32(row),
                    jnp.int32(entry.row))
                self._warm.add("copy:stage")
                self._ins.prefix_hits_total.inc()
                if from_host:
                    self._ins.prefix_host_hits_total.inc()
                    self._sync_prefix_gauges()
                self._ins.prefix_reused_tokens_total.inc(base)
                self._rec.record("request/prefix_hit", h.request_id,
                                 service=self.service_name,
                                 matched_tokens=base,
                                 raw_matched_tokens=matched,
                                 tail_tokens=t0 - base,
                                 tier="host" if from_host else "device")
            else:
                self._prefix.record_miss()
                self._ins.prefix_misses_total.inc()
            # the preemption-time pin held the donated entry alive
            # across the queue wait; the admission has now taken its
            # own reference (or cleanly missed) — the insurance ref
            # can go
            pin = h.__dict__.pop("_preempt_pin", None)
            if pin is not None:
                self._prefix.release(pin)
        tail = t0 - base
        n_chunks = self._policy.n_chunks(tail)
        ids = np.zeros((n_chunks * c,), np.int32)  # right-pad final chunk
        ids[:tail] = prompt[base:]
        d_ids, d_n_chunks = None, 0
        if self.draft is not None:
            # the draft prefills the FULL prompt into its own staging
            # row — the prefix pool holds target KV only, so a hit
            # skips target chunks but never draft chunks (the draft
            # cursor then lags and the admission completes when both
            # caches hold the prompt)
            d_n_chunks = self._policy.n_chunks(t0)
            d_ids = np.zeros((d_n_chunks * c,), np.int32)
            d_ids[:t0] = prompt
        self._adms.append(_Admission(h, slot, row, ids, t0, base,
                                     n_chunks, entry, d_ids,
                                     d_n_chunks))
        h.prefix_tokens = base
        t_adm = time.monotonic()
        if h.admitted_at is None:
            # set-once: a preempted request keeps its ORIGINAL
            # admission stamp — first_token_at is set-once too, so a
            # re-stamp would turn the timeline's prefill_s negative
            h.admitted_at = t_adm
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # queue wait closes (re-admissions ACCUMULATE from the
            # requeue stamp), staging-row residency opens, and the
            # chunk-aligned reuse is credited as tokens + bytes saved
            self._usage.admitted(rec, t_adm, reused_tokens=base)
        self._rec.record("request/admitted", h.request_id,
                         service=self.service_name, slot=slot,
                         staging_row=row, n_chunks=n_chunks,
                         prefix_tokens=base)
        self._ins.admitted_total.inc()
        return True

    def _start_admission_paged(self, h: RequestHandle, slot: int,
                               row: int) -> bool:
        """Paged admission: reserve the request's FULL page span up
        front — shared prefix head by refcount bump, fresh tail from
        the free list (with a reclaim sweep of unpinned prefix entries
        under pressure) — and never copy a row. A hit's shared pages
        are READ through the block table while the prefill writes land
        only in the fresh tail (chunk alignment implies page
        alignment, so a shared page is never written): the zero-copy
        hit leg. Admission is the ONLY allocation point — the
        reservation covers prompt + max_new_tokens (+ gamma verify
        headroom), so decode can never run out of pages mid-flight
        and ``ensure_writable`` never fires on an engine path.

        Returns False when the pool cannot cover the reservation even
        after reclaim: the request goes back to the queue HEAD (its
        order is preserved) and the ``_adm_blocked`` latch stops the
        fill loop for this iteration — pages free as slots finish, so
        the next iteration retries instead of thrashing pop/requeue."""
        c, ps = self._policy.chunk, self.page_size
        prompt = self._effective_prompt(h)
        t0 = prompt.shape[0]
        base, entry, from_host = 0, None, False
        if self._prefix is not None:
            probe = h.__dict__.pop("_prefix_probe", None)
            if probe is not None \
                    and probe[2] == self._prefix.generation:
                e, matched = probe[0], probe[1]
            else:
                e, matched = self._prefix.lookup(prompt)
            if e is not None:
                # cap at t0-1 (last position must be COMPUTED), then
                # chunk-align DOWN — and c % page_size == 0 makes the
                # reuse base page-aligned, the COW-free invariant
                base = (min(matched, t0 - 1) // c) * c
            from_host = base > 0 and e.tier == "host"
            if from_host and not self._promote_entry(e):
                base, e = 0, None
                from_host = False
            if base > 0:
                entry = e
        shared = (tuple(entry.pages[:base // ps])
                  if entry is not None else ())
        g = self._spec.gamma if self._spec is not None else 0
        remaining = h.max_new_tokens - len(h._tokens)
        need_tokens = min(t0 + remaining + g, self._phys_len)
        n_fresh = pages_needed(need_tokens, ps) - len(shared)
        table = BlockTable.build(self._pages, shared, n_fresh)
        if table is None:
            spill = (self._spill_pages
                     if self._prefix is not None
                     and self._prefix.host_rows > 0 else None)
            if self._prefix is not None:
                self._prefix.reclaim(n_fresh, spill)
                table = BlockTable.build(self._pages, shared, n_fresh)
        d_table = None
        if table is not None and self.draft is not None:
            # the draft pool is sized so a draft reservation can never
            # fail once the target's succeeded (1 + max_slots *
            # table_len, no prefix sharing) — the unwind is belt and
            # braces for exotic subclassing
            d_table = BlockTable.build(
                self._d_pages, (),
                pages_needed(need_tokens, ps))
            if d_table is None:
                table.free()
                table = None
        if table is None:
            self._queue.requeue(h)
            self._adm_blocked = True
            # sticky per-request latch: the finished timeline reports
            # page_waited and the incident exemplars classify the
            # request page_wait-bound
            h._page_waited = True
            self._rec.record("request/page_wait", h.request_id,
                             service=self.service_name,
                             needed_pages=n_fresh,
                             free_pages=self._pages.free_pages)
            return False
        if self._prefix is not None:
            if base > 0:
                # no staging copy and no entry acquire: the shared
                # refcounts keep the pages alive even if the entry is
                # evicted while we prefill (single mutator thread)
                self._prefix.record_hit(entry, base, host=from_host)
                self._ins.prefix_hits_total.inc()
                if from_host:
                    self._ins.prefix_host_hits_total.inc()
                    self._sync_prefix_gauges()
                self._ins.prefix_reused_tokens_total.inc(base)
                self._rec.record("request/prefix_hit", h.request_id,
                                 service=self.service_name,
                                 matched_tokens=base,
                                 tail_tokens=t0 - base,
                                 shared_pages=len(shared),
                                 tier="host" if from_host
                                 else "device")
            else:
                self._prefix.record_miss()
                self._ins.prefix_misses_total.inc()
            pin = h.__dict__.pop("_preempt_pin", None)
            if pin is not None:
                self._prefix.release(pin)
        tail = t0 - base
        n_chunks = self._policy.n_chunks(tail)
        ids = np.zeros((n_chunks * c,), np.int32)
        ids[:tail] = prompt[base:]
        d_ids, d_n_chunks = None, 0
        if self.draft is not None:
            d_n_chunks = self._policy.n_chunks(t0)
            d_ids = np.zeros((d_n_chunks * c,), np.int32)
            d_ids[:t0] = prompt
        a = _Admission(h, slot, row, ids, t0, base, n_chunks, None,
                       d_ids, d_n_chunks)
        a.table, a.d_table = table, d_table
        self._adms.append(a)
        h.prefix_tokens = base
        t_adm = time.monotonic()
        if h.admitted_at is None:
            h.admitted_at = t_adm
        rec = getattr(h, "_usage", None)
        if rec is not None:
            self._usage.admitted(rec, t_adm, reused_tokens=base)
        self._rec.record("request/admitted", h.request_id,
                         service=self.service_name, slot=slot,
                         staging_row=row, n_chunks=n_chunks,
                         prefix_tokens=base, pages=len(table),
                         shared_pages=len(shared))
        self._ins.admitted_total.inc()
        return True

    def _prefill_round(self) -> None:
        """Advance EVERY in-flight admission by one chunk through one
        ragged dispatch — plus, with a draft, one MIRRORED ragged
        dispatch over the draft staging cache — then complete the ones
        whose prompt is fully staged in every cache that needs it
        (slot insert + first-token sample).

        A prefix-cache hit can leave the target cursor finished while
        the draft still prefills the reused head: those rows REPLAY
        their final target chunk each round (an idempotent rewrite —
        same ids, same offset, same KV values) so the fixed-shape
        dispatch needs no per-row liveness flag and the final-round
        logits are fresh for the first-token sample whenever the
        admission actually completes."""
        c = self._policy.chunk
        rows = self._policy.prefill_rows
        spec = self.draft is not None
        ids = np.zeros((rows, c), np.int32)
        pos0 = np.zeros((rows,), np.int32)
        last = np.full((rows,), c - 1, np.int32)
        finals: List[_Admission] = []
        for a in self._adms:
            # once the target cursor is past its last chunk (draft
            # still catching up), clamp to the final chunk: a replay
            k = min(a.next_chunk, a.n_chunks - 1)
            ids[a.row] = a.ids[k * c:(k + 1) * c]
            pos0[a.row] = a.base + k * c
            if a.next_chunk >= a.n_chunks - 1:
                # the true last prompt position within the final chunk
                # — pad positions behind it are written but never
                # attended (causal mask within the chunk; decode
                # overwrites position p before attending <= p)
                last[a.row] = a.tail - 1 - (a.n_chunks - 1) * c
                if not spec or a.d_next_chunk >= a.d_n_chunks - 1:
                    finals.append(a)
        # a COLD dispatch's wall is dominated by its one-time compile —
        # billing that to whichever tenants happen to arrive first
        # would poison their device-seconds forever, so warmup rounds
        # are excluded from attribution AND the busy tally (both sides
        # skip: conservation holds, goodput reads the warm engine)
        was_warm = "chunk" in self._warm and (
            not spec or "d_chunk" in self._warm) and (
            not finals or "sample0" in self._warm)
        if self._chaos is not None:
            self._chaos.on_dispatch()
        t_disp = time.monotonic()
        if self.paged:
            # same ragged dispatch, but each row writes through its
            # admission's reserved block table (idle rows carry the
            # all-scratch table — their padding writes hit page 0)
            logits, self._kv_pool = self._chunk_jit(
                self._params, self._buffers, self._h2d(ids),
                self._kv_pool, self._adm_tables(), self._h2d(pos0),
                self._h2d(last))
        else:
            logits, self._staging = self._chunk_jit(
                self._params, self._buffers, self._h2d(ids),
                self._staging, self._h2d(pos0), self._h2d(last))
        self._warm.add("chunk")
        if spec:
            d_ids = np.zeros((rows, c), np.int32)
            d_pos0 = np.zeros((rows,), np.int32)
            for a in self._adms:
                dk = a.d_next_chunk
                d_ids[a.row] = a.d_ids[dk * c:(dk + 1) * c]
                d_pos0[a.row] = dk * c
            if self.paged:
                _, self._d_kv_pool = self._d_chunk_jit(
                    self._d_params, self._d_bufs, self._h2d(d_ids),
                    self._d_kv_pool, self._adm_tables(draft=True),
                    self._h2d(d_pos0),
                    self._h2d(np.zeros((rows,), np.int32)))
            else:
                _, self._d_staging = self._d_chunk_jit(
                    self._d_params, self._d_bufs, self._h2d(d_ids),
                    self._d_staging, self._h2d(d_pos0),
                    self._h2d(np.zeros((rows,), np.int32)))
            self._warm.add("d_chunk")
        toks = None
        if finals:
            # the host-side transfer blocks on the sampled tokens —
            # which depend on the chunk's logits, so the measured wall
            # covers the real dispatch on rounds that finish a prompt
            toks = np.asarray(self._sample0_jit(
                logits, self._next_key(), self._temp()))
            self._warm.add("sample0")
        wall = time.monotonic() - t_disp
        # the same warm-only wall feeds the usage ledger, the cost
        # model, and the loop-phase busy pool — one measurement, three
        # views, so roofline/idle/goodput figures reconcile exactly
        self._iter_disp["prefill"] += wall
        self._loop_obs.dispatch("prefill_dispatch", wall, warm=was_warm)
        self._cost.charge("prefill", wall, warm=was_warm)
        # pro-rata attribution by REAL tokens each row advanced (the
        # padded tail of a final chunk is engine overhead, not billable
        # work; a replayed chunk advances nothing and earns nothing;
        # draft chunks are real mirrored work); weights sum to 1 — the
        # round's full wall is conserved
        done_by = []
        for a in self._adms:
            t_done = (min(c, a.tail - a.next_chunk * c)
                      if a.next_chunk < a.n_chunks else 0)
            d_done = min(c, a.t0 - a.d_next_chunk * c) if spec else 0
            done_by.append((a, t_done, d_done))
        if was_warm:
            total_done = sum(t + d for _, t, d in done_by) or 1
            self._usage.charge_dispatch(
                "prefill", wall,
                [(getattr(a.handle, "_usage", None),
                  (t + d) / total_done)
                 for a, t, d in done_by],
                rows_advanced=len(self._adms),
                capacity_rows=self._policy.prefill_rows)
        for a, t_done, d_done in done_by:
            if t_done:
                k = a.next_chunk
                # only TARGET prompt tokens count as prefill work —
                # draft mirroring is engine overhead, and the billing
                # invariant prefill + prefix_reused == prompt holds
                self._prefilled_tokens += t_done
                self._ins.prefill_tokens_total.inc(t_done)
                rec = getattr(a.handle, "_usage", None)
                if rec is not None:
                    self._usage.add_prefill(rec, t_done)
                self._rec.record("request/prefill_chunk",
                                 a.handle.request_id,
                                 service=self.service_name, chunk=k,
                                 n_chunks=a.n_chunks, tokens=t_done)
                a.next_chunk += 1
            if spec:
                a.d_next_chunk += 1
        for a in finals:
            self._complete_admission(a, int(toks[a.row]))

    def _complete_admission(self, a: _Admission, tok: int) -> None:
        if self.paged:
            # zero-copy handoff: the admission's reserved tables
            # BECOME the slot's — the pages already hold the prompt
            # KV, there is no staging row to scatter
            self._free_slot_table(a.slot)
            self._tables[a.slot] = a.table
            a.table = None
            if self.draft is not None:
                self._d_tables[a.slot] = a.d_table
                a.d_table = None
        else:
            # prompt fully staged: scatter the staging row into the
            # reserved pool slot, release the prefix pin (the staged
            # copy is now independent of the pool row), deliver the
            # first token
            self._caches = self._copy_row_jit(
                self._caches, self._staging, jnp.int32(a.slot),
                jnp.int32(a.row))
            self._warm.add("copy:insert")
            if self.draft is not None:
                # draft slot state moves in lockstep: the draft's
                # staged full-prompt KV lands in the SAME slot index
                self._d_caches = self._copy_row_jit(
                    self._d_caches, self._d_staging, jnp.int32(a.slot),
                    jnp.int32(a.row))
                self._warm.add("copy:d_insert")
        if a.entry is not None:
            self._prefix.release(a.entry)
            a.entry = None
        self._adms.remove(a)
        now = time.monotonic()
        h = a.handle
        first = h.first_token_at is None
        h._deliver(tok, now)
        rec = getattr(h, "_usage", None)
        if rec is not None:
            # staging residency closes into kv_byte_seconds, the slot
            # row's opens; the first token counts as delivered
            self._usage.slot_acquired(rec, now)
            self._usage.delivered(rec, 1)
        if first:
            # re-admissions of a preempted request deliver here too,
            # but their first token shipped long ago — observing a
            # second TTFT would double-count the request
            self._ins.ttft_seconds.observe(now - h.submitted_at)
            # the histograms carry no priority label, so the budget
            # ledger's per-class view is fed directly at the source
            self._slo_budget.observe_class(
                getattr(h, "priority", "normal") or "normal",
                now - h.submitted_at)
            self._rec.record("request/first_token", h.request_id,
                             service=self.service_name, token=tok,
                             ttft_s=now - h.submitted_at)
        else:
            self._rec.record("request/resumed", h.request_id,
                             service=self.service_name, slot=a.slot,
                             tokens_so_far=len(h._tokens),
                             prefix_tokens=a.base,
                             reprefilled_tokens=a.t0 - a.base)
        if (self.eos_id is not None and tok == self.eos_id) \
                or len(h._tokens) >= h.max_new_tokens:
            # instant finisher: the slot row still holds the staged
            # effective prompt's KV — donate it before the slot
            # identity is lost (prompt + generated[:-1] is exactly
            # what the row covers)
            self._maybe_donate(a.slot, np.concatenate(
                [h.prompt, np.asarray(h._tokens[:-1], np.int32)]),
                h.request_id)
            self._free_slot_table(a.slot)
            self._finish_handle(h, None, "finished")
            self._ins.finished_total.inc()
            return
        st = _SlotState(h, a.t0, tok, now)
        # a resumed request's slot picks up where the preempted one
        # left off: pos == effective-prompt length keeps the
        # variable-advance invariant (KV covers [0, pos), the just-
        # delivered token's KV unwritten) for fresh and resumed alike
        st.delivered = len(h._tokens)
        self._slots[a.slot] = st

    def _abort_admission(self, a: _Admission, err: Exception,
                         kind: str) -> None:
        if a.entry is not None:
            self._prefix.release(a.entry)
            a.entry = None
        if a.table is not None:
            a.table.free()
            a.table = None
        if a.d_table is not None:
            a.d_table.free()
            a.d_table = None
        self._adms.remove(a)
        self._count_drop(kind)
        self._finish_handle(a.handle, err, kind)

    # --------------------------------------------------- prefix donation
    def _maybe_donate(self, sid: int, tokens: np.ndarray,
                      request_id: str) -> None:
        """Offer a finishing slot's KV to the prefix pool. ``tokens``
        are exactly the ids whose KV the slot holds (positions
        ``0..len-1``); the index decides (covered / LRU-evict /
        decline) and the accepted row is filled by one donated copy."""
        if self._prefix is None:
            return
        if self.paged:
            # page donation is a refcount move, never a copy: the
            # covering pages are SHARED into the new entry; the slot's
            # own references are freed separately by the caller
            tbl = self._tables[sid]
            if tbl is not None and tokens.shape[0] > 0:
                held = tbl.covering(int(tokens.shape[0]))
                if self._prefix.donate_pages(tokens, held):
                    self._rec.record(
                        "request/prefix_donated", request_id,
                        service=self.service_name,
                        tokens=int(tokens.shape[0]),
                        pages=len(held))
            self._sync_prefix_gauges()
            return
        row = self._prefix.donate(tokens)
        if row is not None:
            # the claimed row may still hold a DEMOTED victim's KV —
            # the bulk d2h spill must land before this copy overwrites
            # it (the engine-side half of the eviction-demotes contract)
            self._resolve_pending_demotion()
            self._pool = self._copy_row_jit(
                self._pool, self._caches, jnp.int32(row),
                jnp.int32(sid))
            self._warm.add("copy:donate")
            self._rec.record("request/prefix_donated", request_id,
                             service=self.service_name,
                             tokens=int(tokens.shape[0]), pool_row=row)
        self._sync_prefix_gauges()

    def _sync_prefix_gauges(self) -> None:
        """Publish the prefix cache's flow deltas and occupancy, both
        tiers (device pool + host spill)."""
        ev = self._prefix.evictions
        if ev > self._prefix_evictions_seen:
            self._ins.prefix_evicted_total.inc(
                ev - self._prefix_evictions_seen)
            self._prefix_evictions_seen = ev
        self._ins.prefix_cache_bytes.set(self._prefix.bytes_in_use)
        self._ins.prefix_cache_entries.set(len(self._prefix))
        if self._prefix.host_rows > 0:
            dm = self._prefix.demotions
            if dm > self._prefix_demotions_seen:
                self._ins.prefix_host_demoted_total.inc(
                    dm - self._prefix_demotions_seen)
                self._prefix_demotions_seen = dm
            hev = self._prefix.host_evictions
            if hev > self._prefix_host_evictions_seen:
                self._ins.prefix_host_evicted_total.inc(
                    hev - self._prefix_host_evictions_seen)
                self._prefix_host_evictions_seen = hev
            self._ins.prefix_host_cache_bytes.set(
                self._prefix.host_bytes_in_use)
            self._ins.prefix_host_cache_entries.set(
                self._prefix.stats()["host_entries"])

    # ------------------------------------------------ host-tier moves
    def _resolve_pending_demotion(self) -> None:
        """Complete the demotion a row claim left open: one jitted
        slice lifts the victim's pool row out, one bulk ``device_get``
        parks it on host (each mesh device ships only its own shard),
        and the cache attaches the buffer. Must run BEFORE the claimed
        row is overwritten — its KV is the source."""
        pend = self._prefix.pop_pending_demotion()
        if pend is None:
            return
        from bigdl_tpu.parallel.tp import fetch_to_host

        victim, vrow = pend
        try:
            one = self._take_row_jit(self._pool, jnp.int32(vrow))
            self._warm.add("copy:demote")
            buf = fetch_to_host(one)
        except Exception:
            # a failed spill degrades to the old drop semantics — the
            # entry is removed, never left pointing at garbage
            buf = None
        self._prefix.complete_demotion(victim, buf)

    def _begin_promotion(self, entry) -> None:
        """Start (or touch) the async host→device transfer for a
        host-tier entry a queued candidate's lookup landed on. The
        ``device_put`` returns immediately — the copy overlaps the
        request's remaining queue wait — and the record PINS the entry
        so its host buffer cannot be evicted mid-flight."""
        if self.paged:
            return  # paged promotion is synchronous at admission
        key = id(entry)
        now = time.monotonic()
        rec = self._promotions.get(key)
        if rec is not None:
            rec["touched"] = now
            return
        if entry.host_buf is None:
            return  # spill copy still pending; next score retries
        if len(self._promotions) >= self._promotions_max:
            # bound in-flight transfers (device bytes + host pins):
            # drop the stalest record, releasing its pin
            stalest = min(self._promotions,
                          key=lambda k: self._promotions[k]["touched"])
            self._drop_promotion(stalest)
        from bigdl_tpu.parallel.tp import put_from_host

        self._prefix.acquire(entry)
        tree = put_from_host(entry.host_buf, self._kv_shard)
        self._promotions[key] = {"entry": entry, "tree": tree,
                                 "touched": now}

    def _drop_promotion(self, key) -> None:
        rec = self._promotions.pop(key, None)
        if rec is not None:
            self._prefix.release(rec["entry"])

    def _prune_promotions(self, now: float) -> None:
        """Retire promotion records whose entry left the host tier
        (promoted by another admission, or dropped) and ones no scorer
        has touched recently (their request was cancelled or timed
        out) — a record's pin must never outlive its usefulness, or
        the host LRU cannot evict."""
        for key in [k for k, r in self._promotions.items()
                    if r["entry"].tier != "host"
                    or now - r["touched"] > 30.0]:
            self._drop_promotion(key)

    def _promote_entry(self, entry) -> bool:
        """Make a host-tier entry device-resident for the admission
        consuming it: claim a pool row (evict-or-demote, exactly the
        donation discipline), land the transferred ``(1, ...)`` tree
        with one warmed scatter, and flip the entry's tier. Uses the
        overlapped transfer when the scorer started one, else starts a
        blocking one here (window=1 engines never score). False means
        the promotion fell through — the caller treats the probe as a
        clean miss."""
        if self.paged:
            return self._promote_entry_paged(entry)
        rec = self._promotions.pop(id(entry), None)
        if entry.tier != "host":
            # raced: another admission promoted it first — its pool
            # row is live, directly consumable
            if rec is not None:
                self._prefix.release(entry)
            return entry.tier == "device"
        if rec is None:
            if entry.host_buf is None:
                return False
            # pin for the promotion's duration: allocate_row()'s
            # evict-or-demote sweep must not reclaim this entry's
            # host buffer out from under its own transfer (the
            # overlapped path pinned at _begin_promotion)
            self._prefix.acquire(entry)
        try:
            if rec is not None:
                tree = rec["tree"]
            else:
                from bigdl_tpu.parallel.tp import put_from_host

                tree = put_from_host(entry.host_buf, self._kv_shard)
            row = self._prefix.allocate_row()
            if row is None:
                return False  # every device row pinned: clean miss
            # the claimed row may itself hold a freshly demoted
            # victim's KV — spill it before the scatter overwrites it
            self._resolve_pending_demotion()
            self._pool = self._copy_row_jit(
                self._pool, tree, jnp.int32(row), jnp.int32(0))
            self._warm.add("copy:promote")
            self._prefix.promote(entry, row)
            self._ins.prefix_host_promoted_total.inc()
            return True
        finally:
            self._prefix.release(entry)

    def _promote_entry_paged(self, entry) -> bool:
        """Synchronous host→device promotion of a paged host-tier
        entry: allocate fresh pages (reclaim sweep of unpinned prefix
        entries under pressure), land each host page buffer with the
        warmed per-page transfer + scatter, flip the entry's tier.
        False = clean miss (pool exhausted or the buffer raced away).
        Per-page copies are small and bounded, so the dense tier's
        async-overlap machinery buys nothing here."""
        if entry.tier != "host":
            return entry.tier == "device"
        buf = entry.host_buf
        if buf is None:
            return False  # spill still pending or already evicted
        n = len(buf)
        pages = self._pages.alloc(n)
        if pages is None:
            spill = (self._spill_pages
                     if self._prefix.host_rows > 0 else None)
            self._prefix.reclaim(n, spill)
            pages = self._pages.alloc(n)
        if pages is None:
            return False
        from bigdl_tpu.parallel.tp import put_from_host

        try:
            for dst, host_page in zip(pages, buf):
                one = put_from_host(host_page, self._kv_shard)
                self._kv_pool = self._copy_row_jit(
                    self._kv_pool, one, jnp.int32(dst), jnp.int32(0))
            self._warm.add("copy:promote")
        except Exception:
            self._pages.free(pages)
            return False
        self._prefix.promote_pages(entry, pages)
        self._ins.prefix_host_promoted_total.inc()
        return True

    def _spill_pages(self, pages):
        """Demotion spill callback for ``PagedPrefixIndex.reclaim``:
        lift each victim page out of the pool with the warmed slice
        and bulk-copy it host-side. Returns the per-page host buffer
        list the host tier retains, or None to degrade the demotion
        to a plain drop (the index never keeps an entry pointing at
        garbage)."""
        if self._take_row_jit is None:
            return None
        from bigdl_tpu.parallel.tp import fetch_to_host

        try:
            out = []
            for p in pages:
                one = self._take_row_jit(self._kv_pool, jnp.int32(p))
                out.append(fetch_to_host(one))
            self._warm.add("copy:demote")
            return out
        except Exception:
            return None

    # --------------------------------------------------- paged plumbing
    def _copy_page(self, dst: int, src: int) -> None:
        """``BlockTable.ensure_writable``'s copy callback: one warmed
        jitted single-page copy inside the target pool. Engine hot
        paths never trigger COW (full-span reservation at admission);
        this exists for API users forking tables (n>1 completions)."""
        self._kv_pool = self._copy_page_jit(
            self._kv_pool, jnp.int32(dst), jnp.int32(src))

    def _adm_tables(self, draft: bool = False):
        """The prefill dispatch's ``(prefill_rows, table_len)`` block
        tables: each admission row's reserved table, idle rows padded
        with the all-scratch table (their padding writes land on page
        0 and are never attended)."""
        rows = self._policy.prefill_rows
        t = np.zeros((rows, self._table_len), np.int32)
        for a in self._adms:
            tbl = a.d_table if draft else a.table
            if tbl is not None:
                t[a.row] = tbl.as_array(self._table_len)
        return self._h2d(t)

    def _slot_tables(self, draft: bool = False):
        """The decode dispatch's ``(max_slots, table_len)`` block
        tables (idle slots all-scratch, same argument as above)."""
        t = np.zeros((self.max_slots, self._table_len), np.int32)
        tables = self._d_tables if draft else self._tables
        for sid, tbl in enumerate(tables):
            if tbl is not None:
                t[sid] = tbl.as_array(self._table_len)
        return self._h2d(t)

    def _free_slot_table(self, sid: int) -> None:
        """Drop slot ``sid``'s page references (target + draft) —
        refcount moves only; pages shared into the prefix index
        survive under the index's references."""
        if not self.paged:
            return
        tbl = self._tables[sid]
        if tbl is not None:
            tbl.free()
            self._tables[sid] = None
        if self._d_tables is not None:
            d = self._d_tables[sid]
            if d is not None:
                d.free()
                self._d_tables[sid] = None

    def _accrue_paged_kv(self) -> None:
        """Per-iteration paged-KV billing: integrate each request's
        ACTUALLY-HELD page bytes over the elapsed interval.
        ``holder_bytes`` prices a shared page pro-rata across its
        refcount, so a page shared by k holders is billed once in
        total no matter how many requests read it — summing every
        holder's accrual can never exceed the pool's physical
        ``bytes_in_use`` integrated over the same window (the
        conservation property the ledger test checks)."""
        now = time.monotonic()
        last, self._last_kv_accrue = self._last_kv_accrue, now
        if last is None:
            return
        dt = now - last
        if dt <= 0.0:
            return

        def bill(h, tbl, d_tbl):
            rec = getattr(h, "_usage", None)
            if rec is None:
                return
            b = (self._pages.holder_bytes(tbl.pages)
                 if tbl is not None else 0.0)
            if d_tbl is not None and self._d_pages is not None:
                b += self._d_pages.holder_bytes(d_tbl.pages)
            if b > 0.0:
                self._usage.accrue_kv(rec, b * dt)

        for sid, st in enumerate(self._slots):
            if st is not None:
                bill(st.handle, self._tables[sid],
                     self._d_tables[sid]
                     if self._d_tables is not None else None)
        for a in self._adms:
            bill(a.handle, a.table, a.d_table)

    def _fragmentation(self) -> float:
        """Internal fragmentation of the live reservations: the token
        slack inside held pages — 1 − covered_tokens / (held_pages ×
        page_size) over every slot table (coverage = the slot's KV
        cursor) and admission table (coverage = reuse base + prefill
        cursor). 0.0 when nothing is held."""
        ps = self.page_size
        c = self._policy.chunk
        held = covered = 0
        for sid, st in enumerate(self._slots):
            tbl = self._tables[sid]
            if st is None or tbl is None:
                continue
            held += len(tbl.pages)
            covered += min(st.pos, len(tbl.pages) * ps)
        for a in self._adms:
            if a.table is None:
                continue
            held += len(a.table.pages)
            covered += min(a.base + a.next_chunk * c, a.t0,
                           len(a.table.pages) * ps)
        if held == 0:
            return 0.0
        return 1.0 - covered / (held * ps)

    def _sync_page_gauges(self) -> None:
        """Publish page-flow counter deltas (target + draft pools
        summed) and pool occupancy/fragmentation gauges."""
        pools = [self._pages]
        if self._d_pages is not None:
            pools.append(self._d_pages)
        stats = [p.stats() for p in pools]
        ins = self._ins
        flows = (("allocated", "allocated_total",
                  ins.page_allocated_total),
                 ("shared", "shared_total", ins.page_shared_total),
                 ("cow_forks", "cow_forks_total",
                  ins.page_cow_forks_total),
                 ("freed", "freed_total", ins.page_freed_total))
        for key, stat_key, counter in flows:
            cur = sum(s[stat_key] for s in stats)
            if cur > self._page_seen[key]:
                counter.inc(cur - self._page_seen[key])
                self._page_seen[key] = cur
        ins.page_pool_bytes.set(
            sum(s["bytes_in_use"] for s in stats))
        ins.page_pool_pages_in_use.set(
            sum(s["pages_in_use"] for s in stats))
        ins.page_pool_fragmentation.set(self._fragmentation())

    def _paging_summary(self) -> dict:
        out = {"page_size": self.page_size,
               "table_len": self._table_len,
               "fragmentation": self._fragmentation(),
               "pool": self._pages.stats()}
        if self._d_pages is not None:
            out["draft_pool"] = self._d_pages.stats()
        if isinstance(self._prefix, PagedPrefixIndex):
            out["prefix_device_pages"] = self._prefix.device_pages
        return out

    # --------------------------------------------------------- decode
    def _decode_all(self, active: List[int]) -> None:
        if self.draft is not None:
            return self._decode_all_spec(active)
        tok = np.zeros((self.max_slots,), np.int32)
        pos = np.zeros((self.max_slots,), np.int32)
        for sid in active:
            st = self._slots[sid]
            tok[sid] = st.last_token
            pos[sid] = st.pos
        was_warm = "step" in self._warm   # cold = compile in the wall
        if self._chaos is not None:
            self._chaos.on_dispatch()
        t_disp = time.monotonic()
        if self.paged:
            nxt, self._kv_pool = self._step_jit(
                self._params, self._buffers, self._h2d(tok),
                self._h2d(pos), self._kv_pool, self._slot_tables(),
                self._next_key(), self._temp())
        else:
            nxt, self._caches = self._step_jit(
                self._params, self._buffers, self._h2d(tok),
                self._h2d(pos), self._caches, self._next_key(),
                self._temp())
        self._warm.add("step")
        nxt_np = np.asarray(nxt)   # blocks on the fused step
        now = time.monotonic()
        # same warm-only wall to ledger, cost model, and loop busy —
        # one measurement, three reconciling views
        self._iter_disp["decode"] += now - t_disp
        self._loop_obs.dispatch("decode_dispatch", now - t_disp,
                                warm=was_warm)
        self._cost.charge("decode", now - t_disp, warm=was_warm)
        # every advanced row got exactly one token: the step's wall
        # splits evenly across them — identical to weighting by
        # delivered tokens, the speculative path's rule (idle slots
        # ride along as padding — their share is the dispatch's
        # padding waste, not billed). Warmup steps are excluded like
        # cold prefill rounds above.
        if was_warm:
            w = 1.0 / len(active)
            self._usage.charge_dispatch(
                "decode", now - t_disp,
                [(getattr(self._slots[sid].handle, "_usage", None), w)
                 for sid in active],
                rows_advanced=len(active), capacity_rows=self.max_slots)
        for sid in active:
            self._deliver_burst(sid, nxt_np[sid:sid + 1], now)

    def _decode_all_spec(self, active: List[int]) -> None:
        """Speculative decode over every occupied slot: one draft
        propose scan + one ragged target verify + one draft sync step
        — three fixed-shape dispatches for up to ``gamma + 1`` tokens
        per row. Acceptance is per ROW (a row whose draft guessed well
        advances further than its neighbors — no min-over-batch
        conservatism), and eos or the per-request token budget can
        truncate an extension mid-burst. Compiled shapes depend only
        on ``(max_slots, gamma)``."""
        g = self._spec.gamma
        tok = np.zeros((self.max_slots,), np.int32)
        pos = np.zeros((self.max_slots,), np.int32)
        for sid in active:
            st = self._slots[sid]
            tok[sid] = st.last_token
            pos[sid] = st.pos
        was_warm = ("spec:propose" in self._warm
                    and "spec:verify" in self._warm)
        if self.temperature > 0.0:
            r_draft, r_acc = self._next_key(), self._next_key()
        else:
            r_draft = r_acc = self._zero_key
        if self._chaos is not None:
            self._chaos.on_dispatch()
        t_disp = time.monotonic()
        tok_d, pos_d = self._h2d(tok), self._h2d(pos)
        if self.paged:
            props, qlogits, self._d_kv_pool = self._propose_jit(
                self._d_params, self._d_bufs, tok_d, pos_d,
                self._d_kv_pool, self._slot_tables(draft=True),
                r_draft, self._temp())
            emit, n_acc, self._kv_pool = self._spec_verify_jit(
                self._params, self._buffers, tok_d, props,
                qlogits, pos_d, self._kv_pool, self._slot_tables(),
                r_acc, self._temp())
        else:
            props, qlogits, self._d_caches = self._propose_jit(
                self._d_params, self._d_bufs, tok_d, pos_d,
                self._d_caches, r_draft, self._temp())
            emit, n_acc, self._caches = self._spec_verify_jit(
                self._params, self._buffers, tok_d, props,
                qlogits, pos_d, self._caches, r_acc,
                self._temp())
        emit_np = np.asarray(emit)    # blocks on both dispatches
        n_np = np.asarray(n_acc)
        wall = time.monotonic() - t_disp
        self._warm.update(("spec:propose", "spec:verify"))
        now = time.monotonic()
        # same warm-only wall to ledger, cost model, and loop busy —
        # one measurement, three reconciling views
        self._iter_disp["decode"] += wall
        self._loop_obs.dispatch("decode_dispatch", wall, warm=was_warm)
        self._cost.charge("decode", wall, warm=was_warm)
        # draft sync BEFORE the next round can propose: a
        # FULL-acceptance row is missing exactly one draft KV write
        # (the propose scan never fed its gamma-th proposal through
        # the draft), so rewrite each row's last accepted token at its
        # own position — partial-acceptance rows rewrite identical
        # values in place, so one fixed-shape ragged dispatch serves
        # all rows. Skipped entirely when NO row fully accepted (their
        # scans already wrote everything); the program is warmed at
        # construction, so the conditional launch can never read as a
        # post-warmup compile. Enqueued async; the data dependency on
        # _d_caches orders it against the next propose.
        if any(int(n_np[sid]) == g for sid in active):
            sync_tok = np.zeros((self.max_slots,), np.int32)
            sync_pos = np.zeros((self.max_slots,), np.int32)
            for sid in active:
                n_r = int(n_np[sid])
                sync_tok[sid] = (tok[sid] if n_r == 0
                                 else int(emit_np[sid, n_r - 1]))
                sync_pos[sid] = pos[sid] + n_r
            if self.paged:
                self._d_kv_pool = self._d_sync_jit(
                    self._d_params, self._d_bufs, self._h2d(sync_tok),
                    self._h2d(sync_pos), self._d_kv_pool,
                    self._slot_tables(draft=True))
            else:
                self._d_caches = self._d_sync_jit(
                    self._d_params, self._d_bufs, self._h2d(sync_tok),
                    self._h2d(sync_pos), self._d_caches)
        # burst lengths FIRST (pure), so the dispatch wall is
        # attributed before any handle can finalize — a late charge
        # against an already-finalized record would leak out of the
        # tenant aggregates and break conservation
        bursts = {}
        proposed = accepted = 0
        for sid in active:
            st = self._slots[sid]
            n_r = int(n_np[sid])
            proposed += g
            accepted += n_r
            st.handle.spec_proposed += g
            st.handle.spec_accepted += n_r
            room = st.handle.max_new_tokens - st.delivered
            toks = emit_np[sid, :min(n_r + 1, room)]
            if self.eos_id is not None:
                hits = np.flatnonzero(toks == self.eos_id)
                if hits.size:     # eos mid-extension: stop AT it
                    toks = toks[:hits[0] + 1]
            bursts[sid] = toks
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._ins.spec_proposed_tokens_total.inc(proposed)
        self._ins.spec_accepted_tokens_total.inc(accepted)
        if proposed:
            self._ins.spec_acceptance_ratio.observe(accepted / proposed)
        if was_warm:
            # the round's wall splits by each row's DELIVERED tokens:
            # billing follows useful work, not slot occupancy — and
            # the weights still sum to 1, so tenant device-second
            # sums conserve the measured busy tally (tested)
            total = sum(len(b) for b in bursts.values()) or 1
            self._usage.charge_dispatch(
                "decode", wall,
                [(getattr(self._slots[sid].handle, "_usage", None),
                  len(b) / total) for sid, b in bursts.items()],
                rows_advanced=len(active), capacity_rows=self.max_slots)
        for sid in active:
            self._deliver_burst(sid, bursts[sid], now)

    def _deliver_burst(self, sid: int, toks, now: float) -> None:
        """Stream one decode round's extension (1..gamma+1 tokens, in
        order) into the slot's handle, advancing the slot position by
        exactly the delivered count — the variable-advance invariant:
        afterwards the slot's KV covers ``[0, pos)`` and the last
        delivered token's KV is not yet cached, same as a 1-token
        step. Observes the inter-token histogram per TOKEN (the burst
        gap split evenly across its tokens, so histogram count keeps
        equalling delivered tokens), records ONE ``decode_token``
        event per burst carrying ``accepted=``, and finishes the row
        at eos / token budget."""
        st = self._slots[sid]
        h = st.handle
        m = len(toks)
        gap = (now - st.last_token_at) / m
        last = int(toks[-1])
        for t in toks:
            st.delivered += 1
            h._deliver(int(t), now)
            self._ins.inter_token_seconds.observe(gap)
        st.pos += m
        st.last_token = last
        st.last_token_at = now
        rec = getattr(h, "_usage", None)
        if rec is not None:
            self._usage.delivered(rec, m)
        self._ins.decode_tokens_total.inc(m)
        self._rec.record("request/decode_token", h.request_id,
                         service=self.service_name, slot=sid,
                         token=last, n=st.delivered, accepted=m)
        if (self.eos_id is not None and last == self.eos_id) \
                or st.delivered >= h.max_new_tokens:
            self._release(sid, None, "finished")

    # ------------------------------------------------------- plumbing
    def _temp(self):
        return self._temp_const

    def _next_key(self):
        if self.temperature <= 0.0:
            return self._zero_key  # greedy: the key is never consumed
        self._key, sub = jax.random.split(self._key)
        return self._h2d(sub)

    def _release(self, sid: int, error: Optional[Exception],
                 reason: str) -> None:
        st = self._slots[sid]
        # donate BEFORE the slot is surrendered: the slot's KV covers
        # positions [0, st.pos) — the prompt plus every delivered token
        # except the last (whose KV the next decode step would have
        # written), so the donated key is exactly prompt +
        # generated[:-1]. Cancelled/timed-out slots donate too: their
        # KV satisfies the same invariant, and a timed-out long prompt
        # is exactly the request most likely to be RETRIED — the retry
        # then pays O(novel-suffix), not a second full prefill.
        tokens = np.concatenate(
            [st.handle.prompt,
             np.asarray(st.handle._tokens[:-1], np.int32)])
        self._maybe_donate(sid, tokens, st.handle.request_id)
        self._free_slot_table(sid)
        self._slots[sid] = None
        self._ins.evicted_total.inc()
        if reason == "finished":
            self._ins.finished_total.inc()
        else:
            self._count_drop(reason)
        self._finish_handle(st.handle, error, reason)

    def _finish_dropped(self, h: RequestHandle, err: Exception) -> None:
        kind = ("cancelled" if isinstance(err, RequestCancelled)
                else "timed_out")
        self._count_drop(kind)
        self._finish_handle(h, err, kind)

    def _count_drop(self, kind: str) -> None:
        (self._ins.cancelled_total if kind == "cancelled"
         else self._ins.timed_out_total).inc()
