"""TPU perf sweep: run the perf harness over a config matrix and print a
table + JSON lines. Used to pick the bench.py defaults (batch/format) on
real hardware; each config runs few iterations so a sweep fits one tunnel
session.

Run: bigdl-tpu-sweep [--quick]   (or python scripts/tpu_sweep.py)
"""

import argparse
import json
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true", help="2 configs only")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--out", default="tpu_sweep.jsonl")
    args = p.parse_args(argv)

    from bigdl_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.perf import run_perf

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", file=sys.stderr)

    if dev.platform == "cpu":  # smoke-test shapes only
        print("[sweep] CPU backend: smoke config only (lenet5, iters<=2); "
              "--iters/--quick apply on TPU", file=sys.stderr)
        configs = [dict(model="lenet5", batch=8, format="NCHW")]
        args.iters = min(args.iters, 2)
    else:
        configs = [
            dict(model="resnet50", batch=256, format="NHWC"),
            dict(model="resnet50", batch=512, format="NHWC"),
            dict(model="resnet50", batch=256, format="NCHW"),
            dict(model="resnet50", batch=128, format="NHWC"),
            dict(model="transformer", batch=8, format="NCHW"),
        ]
        if args.quick:
            configs = configs[:2]

    results = []
    with open(args.out, "a") as fh:
        for cfg in configs:
            t0 = time.perf_counter()
            cfg = dict(cfg, device=str(getattr(dev, "device_kind",
                                               dev.platform)))
            try:
                s = run_perf(cfg["model"], batch_size=cfg["batch"],
                             iterations=args.iters, dtype=jnp.bfloat16,
                             format=cfg["format"], master_f32=True,
                             log=lambda *a, **k: print(*a, file=sys.stderr))
                row = {**cfg, "records_per_sec": s["records_per_sec"],
                       "ms_per_iter": s["ms_per_iter"],
                       "compile_s": s["warmup_s"], "iters": args.iters,
                       "wall_s": round(time.perf_counter() - t0, 1)}
            except Exception as e:
                row = {**cfg, "error": f"{type(e).__name__}: {e}"}
            results.append(row)
            fh.write(json.dumps(row) + "\n")
            fh.flush()
            print(json.dumps(row), file=sys.stderr)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
