"""Checker registry, findings, suppressions, scoping, and the runner.

Design contracts:

- A **Finding** is (file, line, col, code, checker, message) with the
  file path always repo-relative POSIX — baselines and reports must
  diff cleanly across machines.
- **Checkers** register themselves into a module-level registry at
  import time (``@register``). Per-file checkers get one parsed AST
  per file (parsed once, shared by every checker); repo-level checkers
  (observability-drift) run once per scan against the root.
- **Suppressions**: ``# graftlint: ok[token]`` on the finding's line
  or the line directly above it, where ``token`` is a finding code
  (``LCK001``), a checker name (``lock-discipline``), or ``all``;
  several tokens may be comma-separated. A one-line reason after the
  bracket (``— immutable after construction``) is the house style.
- **Scoping**: some codes only make sense on specific subtrees (the
  lock-discipline race detector targets the serving stack; JIT005's
  pinned-out_shardings rule targets serving modules). The scope table
  lives HERE, not in the checkers, so a fixture run with explicit
  paths (``scoped=False``) exercises every rule on any file.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple

#: bump when the Finding schema / cache layout changes incompatibly
SCHEMA_VERSION = 1

#: directories never scanned (mirrors metrics_lint's historical scope:
#: tests mint deliberate violations, docs show myapp_* examples,
#: native/ is C++, the rest are build/VCS droppings)
SKIP_DIRS = {
    ".git", "__pycache__", "build", "dist", "docs", "tests", ".eggs",
    "bigdl_tpu.egg-info", "native", "docker", ".claude", "related",
}


class Finding:
    """One checker hit. Comparable/sortable; hashable on identity key."""

    __slots__ = ("file", "line", "col", "code", "checker", "message")

    def __init__(self, file: str, line: int, col: int, code: str,
                 checker: str, message: str):
        self.file = file.replace(os.sep, "/")
        self.line = int(line)
        self.col = int(col)
        self.code = code
        self.checker = checker
        self.message = message

    def key(self) -> Tuple[str, str]:
        """The baseline bucket: (file, code) — see baseline.py."""
        return (self.file, self.code)

    def sort_key(self):
        return (self.file, self.line, self.col, self.code)

    def to_dict(self) -> dict:
        return {"file": self.file, "line": self.line, "col": self.col,
                "code": self.code, "checker": self.checker,
                "message": self.message}

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(d["file"], d["line"], d.get("col", 0), d["code"],
                   d.get("checker", ""), d.get("message", ""))

    def render(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: {self.code} "
                f"{self.message} [{self.checker}]")

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Finding({self.render()!r})"

    def __eq__(self, other):
        return (isinstance(other, Finding)
                and self.sort_key() == other.sort_key()
                and self.message == other.message)

    def __hash__(self):
        return hash((self.sort_key(), self.message))


class Checker:
    """Base class. Subclasses set ``name``, ``codes``, ``version``;
    per-file checkers implement :meth:`check_file`, repo-level ones
    set ``repo_level = True`` and implement :meth:`check_repo`.

    ``version`` participates in the cache signature — bump it whenever
    the checker's behavior changes so stale cached findings never
    survive a logic change."""

    name: str = "base"
    #: code -> one-line description (the doc page renders this table)
    codes: Dict[str, str] = {}
    version: int = 1
    repo_level: bool = False

    def check_file(self, relpath: str, tree: ast.AST,
                   text: str) -> List[Finding]:
        return []

    def check_repo(self, root: str) -> List[Finding]:
        return []

    def finding(self, relpath: str, node, code: str,
                message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        return Finding(relpath, line, col, code, self.name, message)


_REGISTRY: "Dict[str, Checker]" = {}


def register(cls):
    """Class decorator: instantiate and register a checker (one
    instance per process — checkers must be stateless across files)."""
    inst = cls()
    _REGISTRY[inst.name] = inst
    return cls


def all_checkers() -> List[Checker]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def checkers_signature() -> str:
    """Cache-busting signature: schema + every checker's (name,
    version) — a checker logic bump invalidates its cached findings."""
    parts = [f"schema={SCHEMA_VERSION}"]
    parts += [f"{c.name}={c.version}" for c in all_checkers()]
    return ";".join(parts)


# ------------------------------------------------------------- scoping
def _serving(p: str) -> bool:
    return p.startswith("bigdl_tpu/serving/")


def _lock_scope(p: str) -> bool:
    # the issue's race-detector targets: the threaded serving stack
    # and the ledger every thread writes through
    return _serving(p) or p == "bigdl_tpu/observability/accounting.py"


def _hot_path(p: str) -> bool:
    return (_serving(p) or p.startswith("bigdl_tpu/observability/")
            or p.startswith("bigdl_tpu/optim/"))


#: code (or code-prefix ending in '*') -> predicate(relpath). Codes
#: with no entry apply everywhere. Consulted only in scoped runs —
#: explicit ``--paths`` / fixture runs see every rule.
SCOPES: Dict[str, Callable[[str], bool]] = {
    "LCK*": _lock_scope,
    "JIT005": _serving,
    "RES003": _hot_path,
}


def in_scope(code: str, relpath: str) -> bool:
    for pat, pred in SCOPES.items():
        if (pat.endswith("*") and code.startswith(pat[:-1])) \
                or code == pat:
            return pred(relpath)
    return True


# -------------------------------------------------------- suppressions
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*ok\[([A-Za-z0-9_*,\- ]+)\]")


def suppressions_for_text(text: str) -> Dict[int, set]:
    """Map line number -> set of suppression tokens active there.

    A ``# graftlint: ok[tok]`` comment suppresses matching findings on
    its OWN line and on the line directly BELOW it (so a suppression
    can sit on its own line above a long statement)."""
    out: Dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        toks = {t.strip() for t in m.group(1).split(",") if t.strip()}
        out.setdefault(i, set()).update(toks)
        out.setdefault(i + 1, set()).update(toks)
    return out


def is_suppressed(f: Finding, supp: Dict[int, set]) -> bool:
    toks = supp.get(f.line)
    if not toks:
        return False
    return bool(toks & {f.code, f.checker, "all"})


# ------------------------------------------------------------- walking
def iter_target_files(root: str) -> List[str]:
    """Repo-relative POSIX paths of every ``.py`` file in scan scope,
    sorted for deterministic output."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in SKIP_DIRS
                             and not d.endswith(".egg-info"))
        for fname in filenames:
            if fname.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fname),
                                      root)
                out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def check_one_file(root: str, relpath: str,
                   checkers: Optional[Iterable[Checker]] = None
                   ) -> Tuple[List[Finding], int]:
    """Run every per-file checker over one file. Returns
    ``(findings, n_suppressed)`` — suppressions already applied (they
    are a property of the file text, so the pair caches as a unit).
    Unparsable files yield a single GL000 finding: a syntax error in
    lintable code is itself a finding, never a crash."""
    path = os.path.join(root, relpath)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except (OSError, UnicodeDecodeError):
        return [], 0
    try:
        tree = ast.parse(text, filename=relpath)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, e.offset or 0,
                        "GL000", "graftlint",
                        f"file does not parse: {e.msg}")], 0
    supp = suppressions_for_text(text)
    findings: List[Finding] = []
    n_supp = 0
    for ch in (checkers if checkers is not None else all_checkers()):
        if ch.repo_level:
            continue
        for f in ch.check_file(relpath, tree, text):
            if is_suppressed(f, supp):
                n_supp += 1
            else:
                findings.append(f)
    findings.sort(key=Finding.sort_key)
    return findings, n_supp


def run_checkers(root: str, relpaths: Optional[Iterable[str]] = None,
                 scoped: bool = True, cache=None,
                 with_repo_level: bool = True
                 ) -> Tuple[List[Finding], int]:
    """Run the suite. ``relpaths=None`` scans the whole tree;
    otherwise only the given files (still repo-relative). Returns
    ``(findings, n_suppressed)``; ``scoped`` applies the SCOPES table
    (fixture/explicit runs pass False to exercise every rule)."""
    if relpaths is None:
        relpaths = iter_target_files(root)
    findings: List[Finding] = []
    n_supp = 0
    for rel in relpaths:
        cached = cache.get(root, rel) if cache is not None else None
        if cached is not None:
            fs, ns = cached
        else:
            fs, ns = check_one_file(root, rel)
            if cache is not None:
                cache.put(root, rel, fs, ns)
        findings.extend(fs)
        n_supp += ns
    if with_repo_level:
        for ch in all_checkers():
            if ch.repo_level:
                findings.extend(ch.check_repo(root))
    if scoped:
        findings = [f for f in findings if in_scope(f.code, f.file)]
    findings.sort(key=Finding.sort_key)
    return findings, n_supp
