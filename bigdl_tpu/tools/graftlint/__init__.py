"""graftlint — AST-based static analysis for the bigdl_tpu tree.

A pluggable checker framework that makes this repo's two costliest
invisible bug classes mechanical instead of tribal (the
"RPC Considered Harmful" argument, PAPERS.md 1805.08430): silent jit
recompilation on the serving hot path, and data races between the
scheduler / engine-loop / ledger threads. Four checkers ship in
:mod:`.checkers`:

- ``jit-hazard`` (JIT0xx) — recompile / abstract-value hazards inside
  functions reachable from ``jax.jit`` / ``pjit`` call sites.
- ``lock-discipline`` (LCK0xx) — per-class guarded-by inference over
  ``with self._lock:`` blocks; unguarded access to guarded attributes
  and blocking calls made while a lock is held.
- ``observability-drift`` (OBS0xx) — the former
  ``scripts/metrics_lint.py`` as a checker: ``bigdl_*`` instruments
  minted in one module, documented both directions.
- ``resource-hygiene`` (RES0xx) — non-daemon threads without join
  ownership, files/sockets opened outside a context manager,
  ``except: pass`` on the serving hot path.

Everything here is **stdlib-only** and import-light on purpose:
``scripts/graftlint.py`` loads this package standalone (without
executing ``bigdl_tpu/__init__``), so the CLI runs from any CI step in
milliseconds, with no jax in sight. Keep imports relative and keep
heavyweight dependencies out.

Public surface: :func:`run` (scan → findings split against the
baseline), the checker registry in :mod:`.core`, and
:func:`.cli.main` behind ``scripts/graftlint.py``.
"""

from .core import (  # noqa: F401
    Checker, Finding, SCHEMA_VERSION, all_checkers, in_scope,
    iter_target_files, register, run_checkers, suppressions_for_text,
)
from .baseline import load_baseline, split_findings, write_baseline  # noqa: F401
from .cache import FileCache  # noqa: F401
from . import checkers  # noqa: F401  (registers the shipped checkers)
from .cli import main, run  # noqa: F401

__all__ = [
    "Checker", "Finding", "FileCache", "SCHEMA_VERSION",
    "all_checkers", "in_scope", "iter_target_files", "load_baseline",
    "main", "register", "run", "run_checkers", "split_findings",
    "suppressions_for_text", "write_baseline",
]
