"""Per-file findings cache keyed on content hash + checker versions.

A full scan parses ~150 modules through four AST checkers; the cache
makes the steady-state ``--all`` run touch only edited files. Entries
key on the file's sha1 (not mtime — checkouts and CI restores scramble
mtimes) plus the combined checker signature, so bumping any checker's
``version`` invalidates exactly everything. The cache file lives at
the repo root as ``.graftlint_cache.json`` (gitignored) and is written
atomically — a torn write at worst costs one cold scan.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Tuple

from .core import Finding, checkers_signature

DEFAULT_CACHE = ".graftlint_cache.json"


def _sha1(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as f:
            return hashlib.sha1(f.read()).hexdigest()
    except OSError:
        return None


class FileCache:
    def __init__(self, path: str):
        self.path = path
        self._sig = checkers_signature()
        self._data: dict = {}
        self._dirty = False
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("signature") == self._sig:
                self._data = doc.get("files", {})
        except (OSError, ValueError):
            pass

    def get(self, root: str, relpath: str
            ) -> Optional[Tuple[List[Finding], int]]:
        ent = self._data.get(relpath)
        if not ent:
            return None
        if ent.get("sha1") != _sha1(os.path.join(root, relpath)):
            return None
        fs = [Finding.from_dict(d) for d in ent.get("findings", [])]
        return fs, int(ent.get("suppressed", 0))

    def put(self, root: str, relpath: str, findings: List[Finding],
            n_suppressed: int) -> None:
        sha = _sha1(os.path.join(root, relpath))
        if sha is None:
            return
        self._data[relpath] = {
            "sha1": sha,
            "suppressed": int(n_suppressed),
            "findings": [f.to_dict() for f in findings],
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        doc = {"signature": self._sig, "files": self._data}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a read-only checkout just runs cold every time
        self._dirty = False
