"""The committed-findings baseline: ratchet semantics.

``graftlint_baseline.json`` records every pre-existing finding as
``{file, code, line}`` — the line is the FIRST-SEEN line, kept so a
baseline diff stays reviewable (you can open the site), but matching
is **count-based per (file, code)**: a finding survives line drift
from unrelated edits above it, while an *additional* hazard of the
same code in the same file is always new. The ratchet only tightens —
``--write-baseline`` regenerates from the current tree, and review
should only ever see entries disappear.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Tuple

from .core import Finding, SCHEMA_VERSION

DEFAULT_BASELINE = "graftlint_baseline.json"


def load_baseline(path: str) -> Dict[Tuple[str, str], List[dict]]:
    """(file, code) -> baseline entries (empty when absent/corrupt —
    a missing baseline means everything is new, which is exactly the
    bootstrap behavior ``--write-baseline`` expects)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    out: Dict[Tuple[str, str], List[dict]] = collections.defaultdict(list)
    for e in data.get("entries", []):
        try:
            out[(e["file"], e["code"])].append(e)
        except (TypeError, KeyError):
            continue
    return dict(out)


def split_findings(findings: List[Finding],
                   baseline: Dict[Tuple[str, str], List[dict]]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Partition into ``(new, baselined)``: per (file, code) bucket the
    first N findings (by line) are absorbed by N baseline entries, the
    rest are new. Line-drift tolerant, count-exact."""
    budget = {k: len(v) for k, v in baseline.items()}
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        left = budget.get(f.key(), 0)
        if left > 0:
            budget[f.key()] = left - 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined


def write_baseline(findings: List[Finding], path: str) -> dict:
    """Serialize the current findings as the new baseline (sorted,
    one entry per finding, first-seen line recorded). Returns the
    written document."""
    doc = {
        "version": SCHEMA_VERSION,
        "note": ("pre-existing graftlint findings; matching is "
                 "count-based per (file, code) — lines are first-seen, "
                 "for review. Regenerate: scripts/graftlint.py --all "
                 "--write-baseline. The ratchet only tightens."),
        "entries": [
            {"file": f.file, "code": f.code, "line": f.line}
            for f in sorted(findings, key=Finding.sort_key)
        ],
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(doc, fp, indent=1, sort_keys=False)
        fp.write("\n")
    os.replace(tmp, path)
    return doc
