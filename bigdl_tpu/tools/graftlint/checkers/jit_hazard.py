"""jit-hazard: recompile / abstract-value hazards under ``jax.jit``.

The serving stack's whole performance story rests on a FLAT jit gauge
(pinned shapes + pinned shardings, ROADMAP PRs 2/8): one leaked
trace-time concretization or one unpinned output sharding turns every
request into a fresh compile. This checker finds the classic hazards
*statically*, inside any function reachable from a jit call site in
the same module:

- JIT001 — ``bool()/int()/float()/len()`` or ``.item()/.tolist()`` on
  a likely-traced value (forces concretization → TracerError or a
  silent host sync).
- JIT002 — ``np.*`` call on a likely-traced value (host math on a
  tracer: concretization or a per-call device→host transfer).
- JIT003 — f-string / ``str()`` / ``.format()`` / ``%`` formatting of
  a likely-traced value (stringifies the tracer, not the number).
- JIT004 — a ``static_argnames``/``static_argnums`` parameter whose
  default is mutable/unhashable (list/dict/set): static args are
  hashed per call — an unhashable default is a TypeError, a mutable
  one a cache-poisoning recompile per mutation.
- JIT005 — a raw ``jax.jit``/``pjit`` call without ``out_shardings=``
  (scoped to serving modules: left to GSPMD, a donated cache tree's
  layout drifts and every request adds a compile — the PR 8 lesson).

Reachability and tracedness are MODULE-LOCAL and deliberately
heuristic: jit entries are functions decorated with ``jit``/``pjit``
(bare or via ``partial``) or passed by name into a call whose callee
ends in ``jit``; their non-static params seed the traced set, which
propagates through assignments, arithmetic, ``jnp/lax/jax.*`` calls,
and same-module call argument binding. Heuristics miss cross-module
flows by design — a lint that needs whole-program inference stops
being a pre-commit tool.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, register

_CONCRETIZERS = {"bool", "int", "float", "len"}
_ITEM_METHODS = {"item", "tolist"}
#: jnp/lax-ish dotted heads whose call results are traced values
_TRACED_HEADS = ("jnp.", "lax.", "jax.numpy.", "jax.lax.", "jax.nn.",
                 "jax.random.", "jax.scipy.")
#: jax entry points that are NOT value-producing (don't mark traced)
_JAX_META = {"jax.jit", "jax.pjit", "jax.grad", "jax.vmap", "jax.pmap",
             "jax.tree.map", "jax.tree_util.tree_map"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _numpy_aliases(tree: ast.AST) -> Set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
        elif isinstance(node, ast.ImportFrom):
            # `from numpy import linalg as la` etc. — treat the bound
            # name as a numpy head too
            if node.module == "numpy":
                for a in node.names:
                    out.add(a.asname or a.name)
    return out


def _jnp_aliases(tree: ast.AST) -> Set[str]:
    """Names bound to jax.numpy / jax.lax / jax itself."""
    out = {"jax"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("jax.numpy", "jax.lax", "jax.nn",
                              "jax.random") and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name in ("numpy", "lax", "nn", "random"):
                        out.add(a.asname or a.name)
    return out


class _FnInfo:
    __slots__ = ("node", "qual", "traced_params", "reachable",
                 "statics")

    def __init__(self, node, qual):
        self.node = node
        self.qual = qual
        self.traced_params: Set[str] = set()
        self.reachable = False
        #: static param names (from the jit site) — never traced
        self.statics: Set[str] = set()


def _param_names(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _static_names_from_call(call: ast.Call, fn) -> Set[str]:
    """Resolve static_argnames/static_argnums kwargs of a jit call
    against the target function's positional parameter order."""
    out: Set[str] = set()
    pos = [p.arg for p in fn.args.posonlyargs + fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, int) \
                        and not isinstance(el.value, bool):
                    if 0 <= el.value < len(pos):
                        out.add(pos[el.value])
    return out


def _mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        head = _dotted(node.func)
        return head in ("list", "dict", "set", "bytearray",
                        "collections.defaultdict")
    return False


@register
class JitHazardChecker(Checker):
    name = "jit-hazard"
    version = 1
    codes = {
        "JIT001": "concretization (bool/int/float/len/.item) of a "
                  "traced value under jit",
        "JIT002": "numpy host math on a traced value under jit",
        "JIT003": "string formatting of a traced value under jit",
        "JIT004": "mutable/unhashable default on a static jit arg",
        "JIT005": "raw jax.jit/pjit without pinned out_shardings "
                  "(serving modules)",
    }

    # ------------------------------------------------------- analysis
    def check_file(self, relpath: str, tree: ast.AST,
                   text: str) -> List[Finding]:
        if "jit" not in text:
            return []  # cheap pre-filter: no jit, no hazard surface
        self._np = _numpy_aliases(tree)
        self._jnp = _jnp_aliases(tree)

        fns: Dict[str, _FnInfo] = {}
        order: List[_FnInfo] = []

        def collect(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = (prefix + "." + child.name) if prefix \
                        else child.name
                    info = _FnInfo(child, qual)
                    # bare name resolution (first definition wins)
                    fns.setdefault(child.name, info)
                    order.append(info)
                    collect(child, qual)
                elif isinstance(child, ast.ClassDef):
                    collect(child, (prefix + "." if prefix else "")
                            + child.name)
                else:
                    collect(child, prefix)

        collect(tree, "")

        findings: List[Finding] = []
        entries = self._find_jit_entries(tree, fns, relpath, findings)

        # seed: every non-static param of a jit entry is traced
        work: List[_FnInfo] = []
        for info, statics in entries:
            info.statics |= statics
            new = {p for p in _param_names(info.node)
                   if p not in info.statics}
            if not info.reachable or not new <= info.traced_params:
                info.reachable = True
                info.traced_params |= new
                work.append(info)

        # propagate through same-module call argument binding until
        # fixpoint (bounded: traced sets only grow)
        for _ in range(20):
            if not work:
                break
            batch, work = work, []
            for info in batch:
                for callee, params in self._called_with_traced(
                        info, fns):
                    added = params - callee.traced_params
                    if added or not callee.reachable:
                        callee.reachable = True
                        callee.traced_params |= added
                        work.append(callee)

        for info in order:
            if info.reachable:
                self._scan_body(relpath, info, findings)
        return findings

    # ------------------------------------------------- entry discovery
    def _find_jit_entries(self, tree, fns, relpath, findings
                          ) -> List[Tuple[_FnInfo, Set[str]]]:
        entries: List[Tuple[_FnInfo, Set[str]]] = []

        def is_jit_callee(func) -> bool:
            head = _dotted(func)
            if head is None:
                return False
            last = head.rsplit(".", 1)[-1]
            return last in ("jit", "pjit") or last.endswith("_jit") \
                or last == "_jit"

        for node in ast.walk(tree):
            # decorators: @jax.jit / @jit / @partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                info = fns.get(node.name)
                if info is None or info.node is not node:
                    info = next((i for i in fns.values()
                                 if i.node is node), info)
                for dec in node.decorator_list:
                    call = dec if isinstance(dec, ast.Call) else None
                    target = call.func if call else dec
                    head = _dotted(target) or ""
                    last = head.rsplit(".", 1)[-1]
                    if last == "partial" and call and call.args:
                        inner = _dotted(call.args[0]) or ""
                        if inner.rsplit(".", 1)[-1] in ("jit", "pjit"):
                            statics = (_static_names_from_call(
                                call, node) if call else set())
                            if info:
                                entries.append((info, statics))
                                self._check_static_defaults(
                                    relpath, call, node, findings)
                    elif last in ("jit", "pjit"):
                        statics = (_static_names_from_call(call, node)
                                   if call else set())
                        if info:
                            entries.append((info, statics))
                        if call:
                            self._check_static_defaults(
                                relpath, call, node, findings)
            # calls: jax.jit(fn, ...) / _jit(step, ...) — any function
            # NAME handed to a jit-ish callee becomes an entry
            elif isinstance(node, ast.Call) \
                    and is_jit_callee(node.func):
                head = _dotted(node.func) or ""
                raw = head.rsplit(".", 1)[-1] in ("jit", "pjit")
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in fns:
                        statics = _static_names_from_call(
                            node, fns[arg.id].node)
                        entries.append((fns[arg.id], statics))
                        if raw:
                            self._check_static_defaults(
                                relpath, node, fns[arg.id].node,
                                findings)
                if raw and not any(kw.arg == "out_shardings"
                                   for kw in node.keywords):
                    findings.append(self.finding(
                        relpath, node, "JIT005",
                        "jax.jit without out_shardings= — unpinned "
                        "output layout lets GSPMD drift a donated "
                        "tree and mint a compile per request"))
        return entries

    def _check_static_defaults(self, relpath, call, fn, findings):
        statics = _static_names_from_call(call, fn)
        if not statics:
            return
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        for p, d in zip(pos[len(pos) - len(defaults):], defaults):
            if p.arg in statics and _mutable_default(d):
                findings.append(self.finding(
                    relpath, d, "JIT004",
                    f"static arg {p.arg!r} of {fn.name!r} has a "
                    "mutable/unhashable default — static args are "
                    "hashed per jit call"))
        for p, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and p.arg in statics \
                    and _mutable_default(d):
                findings.append(self.finding(
                    relpath, d, "JIT004",
                    f"static arg {p.arg!r} of {fn.name!r} has a "
                    "mutable/unhashable default — static args are "
                    "hashed per jit call"))

    # --------------------------------------------------- traced values
    def _is_traced(self, node, traced: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in traced
        if isinstance(node, ast.Attribute):
            # x.T / x.dtype-ish chains: traced if the root is
            return self._is_traced(node.value, traced)
        if isinstance(node, ast.Subscript):
            return self._is_traced(node.value, traced)
        if isinstance(node, ast.BinOp):
            return (self._is_traced(node.left, traced)
                    or self._is_traced(node.right, traced))
        if isinstance(node, ast.UnaryOp):
            return self._is_traced(node.operand, traced)
        if isinstance(node, ast.Compare):
            return (self._is_traced(node.left, traced)
                    or any(self._is_traced(c, traced)
                           for c in node.comparators))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._is_traced(e, traced) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self._is_traced(node.body, traced)
                    or self._is_traced(node.orelse, traced))
        if isinstance(node, ast.Call):
            head = _dotted(node.func)
            if head:
                root = head.split(".", 1)[0]
                if head in _JAX_META:
                    return False
                if any(head.startswith(h) for h in _TRACED_HEADS) \
                        or root in self._jnp:
                    return True
                # method on a traced value (x.sum(), x.astype())
            if isinstance(node.func, ast.Attribute) \
                    and self._is_traced(node.func.value, traced):
                return True
        return False

    def _called_with_traced(self, info: _FnInfo, fns
                            ) -> List[Tuple[_FnInfo, Set[str]]]:
        """Same-module callees of ``info`` with the params that
        receive traced arguments."""
        out = []
        traced = info.traced_params
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Name):
                continue
            callee = fns.get(node.func.id)
            if callee is None or callee.node is info.node:
                continue
            pos = [p.arg for p in (callee.node.args.posonlyargs
                                   + callee.node.args.args)]
            hit: Set[str] = set()
            for i, arg in enumerate(node.args):
                if i < len(pos) and self._is_traced(arg, traced):
                    hit.add(pos[i])
            for kw in node.keywords:
                if kw.arg and self._is_traced(kw.value, traced):
                    hit.add(kw.arg)
            if hit:
                out.append((callee, hit))
        return out

    # ------------------------------------------------------- emission
    def _scan_body(self, relpath: str, info: _FnInfo,
                   findings: List[Finding]) -> None:
        traced = set(info.traced_params)
        own_defs = {n for n in ast.walk(info.node)
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                    and n is not info.node}

        def in_nested(node):
            return any(node in ast.walk(d) for d in own_defs)

        # forward pass: grow the traced set through assignments
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) \
                    and self._is_traced(node.value, traced):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            traced.add(n.id)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and self._is_traced(node.value, traced):
                traced.add(node.target.id)

        for node in ast.walk(info.node):
            if in_nested(node):
                continue  # nested defs analyzed via their own info
            if isinstance(node, ast.Call):
                head = _dotted(node.func)
                # bool(x) / len(x) / int(x) / float(x)
                if head in _CONCRETIZERS and node.args \
                        and self._is_traced(node.args[0], traced):
                    findings.append(self.finding(
                        relpath, node, "JIT001",
                        f"{head}() on traced value inside "
                        f"jit-reachable {info.qual!r} — forces "
                        "concretization at trace time"))
                # str(x) formats the tracer
                elif head == "str" and node.args \
                        and self._is_traced(node.args[0], traced):
                    findings.append(self.finding(
                        relpath, node, "JIT003",
                        f"str() of traced value inside jit-reachable "
                        f"{info.qual!r} — stringifies the tracer"))
                elif isinstance(node.func, ast.Attribute):
                    # x.item() / x.tolist()
                    if node.func.attr in _ITEM_METHODS \
                            and self._is_traced(node.func.value,
                                                traced):
                        findings.append(self.finding(
                            relpath, node, "JIT001",
                            f".{node.func.attr}() on traced value "
                            f"inside jit-reachable {info.qual!r} — "
                            "forces a device sync / concretization"))
                    # "...".format(traced)
                    elif node.func.attr == "format" \
                            and isinstance(node.func.value,
                                           ast.Constant) \
                            and any(self._is_traced(a, traced)
                                    for a in list(node.args)
                                    + [k.value for k in
                                       node.keywords]):
                        findings.append(self.finding(
                            relpath, node, "JIT003",
                            f".format() of traced value inside "
                            f"jit-reachable {info.qual!r}"))
                    # np.<anything>(traced)
                    if head:
                        root = head.split(".", 1)[0]
                        if root in self._np \
                                and any(self._is_traced(a, traced)
                                        for a in node.args):
                            findings.append(self.finding(
                                relpath, node, "JIT002",
                                f"{head}() on traced value inside "
                                f"jit-reachable {info.qual!r} — host "
                                "numpy concretizes the tracer"))
            elif isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.FormattedValue) \
                            and self._is_traced(v.value, traced):
                        findings.append(self.finding(
                            relpath, node, "JIT003",
                            f"f-string interpolates traced value "
                            f"inside jit-reachable {info.qual!r}"))
                        break
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Mod) \
                    and isinstance(node.left, ast.Constant) \
                    and isinstance(node.left.value, str) \
                    and self._is_traced(node.right, traced):
                findings.append(self.finding(
                    relpath, node, "JIT003",
                    f"%-format of traced value inside jit-reachable "
                    f"{info.qual!r}"))
