"""The shipped checkers. Importing this package registers all four
into :mod:`..core`'s registry (the ``@register`` decorator runs at
import time). To add a checker: write a module here subclassing
``core.Checker``, decorate it with ``@register``, import it below,
and give it a dirty+clean fixture pair under
``tests/graftlint_fixtures/`` — see
docs/programming-guide/static-analysis.md."""

from . import jit_hazard  # noqa: F401
from . import lock_discipline  # noqa: F401
from . import observability_drift  # noqa: F401
from . import resource_hygiene  # noqa: F401
