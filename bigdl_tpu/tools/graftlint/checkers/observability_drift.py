"""observability-drift: the metrics schema / docs contract as a checker.

This is ``scripts/metrics_lint.py`` folded into the graftlint
framework (that script survives as a thin delegating shim, so every
documented command keeps working). The contract it holds is unchanged:

- OBS001 — a ``bigdl_*`` instrument registered OUTSIDE
  ``bigdl_tpu/observability/instruments.py`` (one module is the
  schema; the fix is always an ``*_instruments`` entry there).
- OBS002 — an instrument registered in that module but missing from
  the instrument table in ``docs/programming-guide/observability.md``
  (an operator reading the docs must see every series a scrape can
  emit).
- OBS003 — a documented table row whose instrument is no longer
  registered (a ghost row promising a series no scrape will emit).

Doc-table grammar (unchanged): a row may spell a name exactly, expand
one ``{a,b,c}`` alternation, or end in ``*`` for a family prefix;
only markdown table rows (lines starting with ``|``) count.

Repo-level checker: it compares three artifacts (code tree, schema
module, doc table), so there is no per-file cache entry — it runs on
every scan and on every ``--changed`` run (it is milliseconds).
"""

from __future__ import annotations

import os
import re
from typing import List

from ..core import Checker, Finding, register

#: the one module allowed to register bigdl_* instruments
ALLOWED = ("bigdl_tpu", "observability", "instruments.py")

#: the guide whose instrument table must cover every registered name
DOCS_GUIDE = ("docs", "programming-guide", "observability.md")

SKIP_DIRS = {".git", "__pycache__", "build", "dist", "docs", "tests",
             ".eggs", "bigdl_tpu.egg-info", "native", "docker",
             ".claude", "related"}

# a registration call with a bigdl_* name literal as its first
# argument; assembled from pieces so this file never matches itself
_PATTERN = re.compile(
    r"\.\s*(counter|gauge|histogram)\s*\(\s*"
    r"[\"']" + "(bigdl" + r"_[A-Za-z0-9_:]*)[\"']",
    re.S)

# a documented-name token in the guide: a bigdl_ head, at most one
# {a,b,c} alternation (a {label=} brace contains '=' and is NOT an
# alternation, so it terminates the token), an optional tail, and an
# optional trailing * marking a family prefix
_DOC_TOKEN = re.compile(
    "(" + "bigdl" + r"_[A-Za-z0-9_]*)"
    r"(?:\{([A-Za-z0-9_,]+)\})?"
    r"([A-Za-z0-9_]*)"
    r"(\*)?")


def lint(root: str):
    """Yield (path, lineno, method, metric_name) out-of-place
    registrations (the historical metrics_lint API, kept verbatim for
    the shim and its tier-1 tests)."""
    allowed = os.path.join(root, *ALLOWED)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            if os.path.abspath(path) == os.path.abspath(allowed):
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except (OSError, UnicodeDecodeError):
                continue
            for m in _PATTERN.finditer(text):
                lineno = text.count("\n", 0, m.start()) + 1
                yield (os.path.relpath(path, root), lineno,
                       m.group(1), m.group(2))


def registered_names(root: str):
    """Every metric name literal registered in the canonical module."""
    path = os.path.join(root, *ALLOWED)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return []
    return sorted({m.group(2) for m in _PATTERN.finditer(text)})


def documented_patterns(root: str):
    """The doc guide's instrument-TABLE vocabulary: exact names,
    expanded ``{a,b,c}`` alternations, and ``prefix*`` family
    wildcards. Only markdown table rows (lines starting with ``|``)
    count — prose mentioning ``bigdl_*`` generically must not satisfy
    the per-instrument documentation requirement."""
    path = os.path.join(root, *DOCS_GUIDE)
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return set()
    pats = set()
    for line in lines:
        if not line.lstrip().startswith("|"):
            continue
        for m in _DOC_TOKEN.finditer(line):
            head, alts, tail, star = m.groups()
            for alt in (alts.split(",") if alts else ("",)):
                pats.add(head + alt + (tail or "")
                         + ("*" if star else ""))
    return pats


def doc_drift(root: str):
    """Registered instrument names the docs table never mentions."""
    pats = documented_patterns(root)

    def covered(name):
        return any((p.endswith("*") and name.startswith(p[:-1]))
                   or name == p for p in pats)

    return [n for n in registered_names(root) if not covered(n)]


def reverse_drift(root: str):
    """Documented table names/patterns with no registered counterpart:
    an exact (or ``{a,b,c}``-expanded) name must be registered
    verbatim; a ``prefix*`` wildcard row needs at least one registered
    name under its prefix."""
    names = set(registered_names(root))

    def alive(pat):
        if pat.endswith("*"):
            return any(n.startswith(pat[:-1]) for n in names)
        return pat in names

    return sorted(p for p in documented_patterns(root) if not alive(p))


def _doc_line(root: str, name: str) -> int:
    """Best-effort line of a doc-table token (for finding anchors)."""
    path = os.path.join(root, *DOCS_GUIDE)
    probe = name[:-1] if name.endswith("*") else name
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, start=1):
                if line.lstrip().startswith("|") and probe in line:
                    return i
    except OSError:
        pass
    return 1


def _registration_line(root: str, name: str) -> int:
    path = os.path.join(root, *ALLOWED)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return 1
    for m in _PATTERN.finditer(text):
        if m.group(2) == name:
            return text.count("\n", 0, m.start()) + 1
    return 1


@register
class ObservabilityDriftChecker(Checker):
    name = "observability-drift"
    version = 1
    repo_level = True
    codes = {
        "OBS001": "bigdl_* instrument registered outside "
                  "observability/instruments.py",
        "OBS002": "instrument registered but undocumented in the "
                  "docs instrument table",
        "OBS003": "ghost doc row: documented instrument no longer "
                  "registered",
    }

    def check_repo(self, root: str) -> List[Finding]:
        out: List[Finding] = []
        for path, lineno, method, mname in lint(root):
            out.append(Finding(
                path, lineno, 0, "OBS001", self.name,
                f".{method}({mname!r}) — bigdl_* metrics must be "
                f"defined in {'/'.join(ALLOWED)} (add an "
                "*_instruments entry)"))
        for mname in doc_drift(root):
            out.append(Finding(
                "/".join(ALLOWED), _registration_line(root, mname), 0,
                "OBS002", self.name,
                f"{mname!r} is registered but missing from the "
                f"instrument table in {'/'.join(DOCS_GUIDE)} (add a "
                "table row)"))
        for mname in reverse_drift(root):
            out.append(Finding(
                "/".join(DOCS_GUIDE), _doc_line(root, mname), 0,
                "OBS003", self.name,
                f"{mname!r} is documented in the instrument table but "
                f"no longer registered in {'/'.join(ALLOWED)} (drop "
                "the row or restore the instrument)"))
        return out


def legacy_main(argv=None, default_root=None) -> int:
    """The historical ``scripts/metrics_lint.py`` CLI, byte-compatible
    output — the shim delegates here (passing its own repo root as
    ``default_root``) so every documented command and in-process test
    keeps working."""
    import argparse

    here = default_root or os.getcwd()
    p = argparse.ArgumentParser(
        description="Fail when a bigdl_* metric is registered outside "
                    "observability/instruments.py, or registered there "
                    "but missing from the docs instrument table. "
                    "(Deprecated shim: see scripts/graftlint.py.)")
    p.add_argument("--root", default=here)
    args = p.parse_args(argv)

    violations = list(lint(args.root))
    for path, lineno, method, name in violations:
        print(f"[metrics-lint] {path}:{lineno}: .{method}({name!r}) — "
              f"bigdl_* metrics must be defined in "
              f"{'/'.join(ALLOWED)} (add an *_instruments entry)")
    undocumented = doc_drift(args.root)
    for name in undocumented:
        print(f"[metrics-lint] {'/'.join(ALLOWED)}: {name!r} is "
              f"registered but missing from the instrument table in "
              f"{'/'.join(DOCS_GUIDE)} (add a table row)")
    ghosts = reverse_drift(args.root)
    for name in ghosts:
        print(f"[metrics-lint] {'/'.join(DOCS_GUIDE)}: {name!r} is "
              f"documented in the instrument table but no longer "
              f"registered in {'/'.join(ALLOWED)} (drop the row or "
              f"restore the instrument)")
    if violations or undocumented or ghosts:
        print(f"[metrics-lint] FAIL: {len(violations)} out-of-place "
              f"registration(s), {len(undocumented)} undocumented "
              f"instrument(s), {len(ghosts)} ghost doc row(s)")
        return 1
    print("[metrics-lint] ok: all bigdl_* metrics registered in "
          + "/".join(ALLOWED) + " and documented in "
          + "/".join(DOCS_GUIDE) + " (both directions)")
    return 0
