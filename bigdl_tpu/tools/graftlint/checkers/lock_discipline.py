"""lock-discipline: guarded-by inference + held-lock blocking calls.

A lightweight race detector for the threaded serving stack (scoped by
core.SCOPES to ``bigdl_tpu/serving/**`` and
``observability/accounting.py`` — the modules the scheduler, engine
loop, HTTP front door, and ledger threads all write through).

Per class that owns a lock (an attribute assigned
``threading.Lock/RLock/Condition`` or used as ``with self._lock:``):

- the **guarded-by set** is inferred as every ``self.X`` attribute
  touched (read or write) while the lock is held. "Held" is lexical
  (inside the ``with``) plus one interprocedural step: a private
  method whose every intra-class call site is lock-held is analyzed
  as lock-held itself (the ``_refill``/``_terminal`` pattern), to a
  fixpoint.
- LCK001 — an access to a guarded attribute at a site where the lock
  is NOT held. ``__init__``/``__new__``/``__del__`` are exempt
  (construction/teardown are single-threaded by contract). Immutable
  config reads that trip this are exactly the "unguarded stat read"
  class — suppress each with ``# graftlint: ok[lock-discipline] — <why>``
  rather than widening the checker.
- LCK002 — a blocking call made while the lock is held:
  ``time.sleep``, zero-arg ``.join()`` (thread join; ``str.join``
  always takes an iterable), zero-arg ``.get()`` (queue get; ``dict
  .get`` always takes a key), socket ops, ``subprocess``/``urlopen``,
  ``jax device_put`` / ``.block_until_ready()`` — a device sync under
  a lock serializes every other thread behind the transfer.
  ``Condition.wait/notify`` are deliberately NOT flagged: holding the
  lock there is the API contract.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, register

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_LOCK_NAME_HINTS = ("lock", "cond", "mutex")
_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}
#: attribute-method calls that MUTATE their receiver (count as writes
#: for guarded-by inference — ``self._q.append`` guards ``_q``)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse", "rotate",
}
#: dotted names that block (module-level calls)
_BLOCKING_DOTTED = {
    "time.sleep", "select.select", "subprocess.run",
    "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "urllib.request.urlopen", "urlopen",
}
#: attribute calls that block regardless of receiver
_BLOCKING_ATTRS = {"block_until_ready", "accept", "recv", "recvfrom",
                   "sendall", "connect"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for a ``self.X`` attribute node, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _Access:
    __slots__ = ("attr", "write", "locked", "node")

    def __init__(self, attr, write, locked, node):
        self.attr = attr
        self.write = write
        self.locked = locked
        self.node = node


class _MethodScan:
    __slots__ = ("name", "accesses", "calls", "blocking")

    def __init__(self, name):
        self.name = name
        self.accesses: List[_Access] = []
        #: (callee_method_name, locked_at_call_site)
        self.calls: List[Tuple[str, bool]] = []
        #: blocking call sites seen while lexically locked:
        #: (node, rendered_callee)
        self.blocking: List[Tuple[ast.AST, str]] = []


@register
class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    version = 1
    codes = {
        "LCK001": "access to a lock-guarded attribute without the "
                  "lock held",
        "LCK002": "blocking call while holding a lock",
    }

    def check_file(self, relpath: str, tree: ast.AST,
                   text: str) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(relpath, node, findings)
        return findings

    # ---------------------------------------------------------- class
    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            # self.X = threading.Lock() / Condition() / ...
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                head = _dotted(node.value.func) or ""
                if head.rsplit(".", 1)[-1] in _LOCK_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            locks.add(a)
            # with self.X: where X smells like a lock
            elif isinstance(node, ast.With):
                for item in node.items:
                    a = _self_attr(item.context_expr)
                    if a and any(h in a.lower()
                                 for h in _LOCK_NAME_HINTS):
                        locks.add(a)
        return locks

    def _scan_method(self, fn, locks: Set[str]) -> _MethodScan:
        scan = _MethodScan(fn.name)

        def is_lock_item(withnode) -> bool:
            return any(_self_attr(i.context_expr) in locks
                       for i in withnode.items)

        def visit(node, locked):
            if isinstance(node, ast.With) and is_lock_item(node):
                for item in node.items:
                    visit(item.context_expr, locked)
                for st in node.body:
                    visit(st, True)
                return
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs: different execution time
            if isinstance(node, ast.Call):
                head = _dotted(node.func)
                if locked:
                    label = self._blocking_label(node, head)
                    if label:
                        scan.blocking.append((node, label))
                if isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    recv_attr = _self_attr(recv)
                    if isinstance(recv, ast.Name) \
                            and recv.id == "self":
                        # self.method(...): a call edge, not a data
                        # access — visit only the arguments
                        scan.calls.append((node.func.attr, locked))
                        for a in node.args:
                            visit(a, locked)
                        for kw in node.keywords:
                            visit(kw.value, locked)
                        return
                    if recv_attr is not None \
                            and recv_attr not in locks \
                            and node.func.attr in _MUTATORS:
                        # self._q.append(...): a WRITE to _q (skip the
                        # receiver subtree so it isn't double-counted
                        # as a read)
                        scan.accesses.append(_Access(
                            recv_attr, True, locked, recv))
                        for a in node.args:
                            visit(a, locked)
                        for kw in node.keywords:
                            visit(kw.value, locked)
                        return
            # subscript store: self.X[k] = v is a write to X (the
            # inner Attribute has Load ctx — record the write here
            # and skip the inner read)
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr is not None and attr not in locks:
                    scan.accesses.append(
                        _Access(attr, True, locked, node.value))
                    visit(node.slice, locked)
                    return
            attr = _self_attr(node)
            if attr is not None and attr not in locks:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                scan.accesses.append(
                    _Access(attr, write, locked, node))
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for st in fn.body:
            visit(st, False)
        # dedupe per (attr, line, locked): an augmented store or a
        # mutator call can record a read+write pair at one site — keep
        # the write (the stronger fact)
        best = {}
        for a in scan.accesses:
            k = (a.attr, a.node.lineno, a.locked)
            if k not in best or (a.write and not best[k].write):
                best[k] = a
        scan.accesses = list(best.values())
        return scan

    def _blocking_label(self, node: ast.Call,
                        head: Optional[str]) -> Optional[str]:
        if head:
            last = head.rsplit(".", 1)[-1]
            if head in _BLOCKING_DOTTED or last == "sleep":
                return head
            if last == "device_put" or head == "jax.device_put":
                return head
        if isinstance(node.func, ast.Attribute):
            a = node.func.attr
            if a in _BLOCKING_ATTRS:
                return f".{a}()"
            if a == "join" and not node.args:
                # zero-arg join: a thread join (str.join and
                # os.path.join always take positional args)
                return ".join()"
            if a == "get" and not node.args:
                # zero-positional-arg get: Queue.get-style blocking
                # (dict.get always takes the key positionally)
                return ".get()"
        return None

    def _check_class(self, relpath: str, cls: ast.ClassDef,
                     findings: List[Finding]) -> None:
        locks = self._lock_attrs(cls)
        if not locks:
            return
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        scans = {m.name: self._scan_method(m, locks) for m in methods}

        # fixpoint: a method whose every intra-class call site is
        # lock-held is itself analyzed as lock-held (``_refill``
        # pattern). Methods with no intra-class call sites stay
        # unlocked-context (they are the public API surface).
        locked_ctx: Set[str] = set()
        for _ in range(10):
            changed = False
            sites: Dict[str, List[bool]] = {}
            for s in scans.values():
                eff = s.name in locked_ctx
                for callee, locked in s.calls:
                    if callee in scans:
                        sites.setdefault(callee, []).append(
                            locked or eff)
            for name, states in sites.items():
                if name not in locked_ctx and states \
                        and all(states):
                    locked_ctx.add(name)
                    changed = True
            if not changed:
                break

        def effective(scan: _MethodScan, locked: bool) -> bool:
            return locked or scan.name in locked_ctx

        # guarded-by inference: attrs touched with the lock held,
        # outside the exempt methods
        guarded: Set[str] = set()
        for s in scans.values():
            if s.name in _EXEMPT_METHODS:
                continue
            for a in s.accesses:
                if effective(s, a.locked):
                    guarded.add(a.attr)

        for s in scans.values():
            if s.name in _EXEMPT_METHODS:
                continue
            for a in s.accesses:
                if a.attr in guarded and not effective(s, a.locked):
                    kind = "write to" if a.write else "read of"
                    findings.append(self.finding(
                        relpath, a.node, "LCK001",
                        f"{kind} {cls.name}.{a.attr} outside the "
                        f"lock that guards it elsewhere "
                        f"(in {s.name!r})"))
            for node, label in s.blocking:
                findings.append(self.finding(
                    relpath, node, "LCK002",
                    f"blocking call {label} while holding "
                    f"{cls.name}'s lock (in {s.name!r}) — every "
                    "other thread serializes behind it"))
