"""resource-hygiene: thread/file/socket ownership and swallowed errors.

Three habits that are harmless in a script and lethal in a serving
process that restarts workers, drains replicas, and runs for weeks:

- RES001 — ``threading.Thread(...)`` constructed without ``daemon=``
  and with no visible ``.join()`` ownership. A non-daemon thread with
  no joiner keeps the interpreter alive through shutdown (the fleet
  drain path hangs on exactly this). Pass ``daemon=`` explicitly —
  either value — or join the thread somewhere in the module.
- RES002 — ``open()`` / ``socket.socket()`` / ``socket.create_
  connection()`` / ``os.fdopen()`` used outside a ``with`` and without
  visible close ownership (assigned to ``self.X``, returned to the
  caller, registered with an ExitStack, or ``.close()``d on the bound
  name somewhere in the module). A bare/chained/argument use leaks
  the descriptor on any exception between acquire and release.
- RES003 — ``except:`` / ``except Exception:`` / ``except
  BaseException:`` whose body is exactly ``pass``. On the serving hot
  path (core.SCOPES confines RES003 to serving/observability/optim) a
  swallowed error is a request that vanishes with no metric, no log
  line, and no flight-recorder event. Narrow the exception or record
  it; a deliberate swallow takes
  ``# graftlint: ok[resource-hygiene] — <why>``.

Ownership evidence is module-wide, not flow-sensitive: a ``.join()``
or ``.close()`` on the bound name anywhere in the module clears the
construction site. That trades soundness for a reviewable signal —
the goal is catching the *no owner anywhere* case, which is the one
that bites in production.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Checker, Finding, register

_OPENERS_DOTTED = {"open", "io.open", "os.fdopen", "socket.socket",
                   "socket.create_connection"}
#: ExitStack-style sinks that take ownership of a resource argument
_OWNERSHIP_SINKS = {"enter_context", "push", "callback", "register"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_opener(call: ast.Call) -> bool:
    return _dotted(call.func) in _OPENERS_DOTTED


def _is_thread_ctor(call: ast.Call) -> bool:
    head = _dotted(call.func) or ""
    return head.rsplit(".", 1)[-1] == "Thread"


@register
class ResourceHygieneChecker(Checker):
    name = "resource-hygiene"
    version = 1
    codes = {
        "RES001": "thread created without daemon= or join ownership",
        "RES002": "file/socket opened outside a context manager "
                  "without close ownership",
        "RES003": "broad except clause that silently passes",
    }

    def check_file(self, relpath: str, tree: ast.AST,
                   text: str) -> List[Finding]:
        findings: List[Finding] = []
        owned_names = self._owned_names(tree)
        owned_calls = self._owned_call_sites(tree, owned_names)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(relpath, node, owned_calls, findings)
            elif isinstance(node, ast.ExceptHandler):
                self._check_except(relpath, node, findings)
        return findings

    # ----------------------------------------------------- ownership
    def _owned_names(self, tree: ast.AST) -> Set[str]:
        """Dotted names with visible lifecycle ownership anywhere in
        the module: ``.join()``ed or ``.close()``d, or an explicit
        ``X.daemon = ...`` assignment."""
        owned: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("join", "close"):
                base = _dotted(node.func.value)
                if base:
                    owned.add(base)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr == "daemon":
                        base = _dotted(t.value)
                        if base:
                            owned.add(base)
        # loop-alias ownership: ``for t in threads: t.join()`` makes
        # the iterated collection owned too (the common fan-out idiom
        # ``threads = [Thread(...) for ...]`` then join-all)
        for _ in range(3):
            grew = False
            for node in ast.walk(tree):
                if isinstance(node, ast.For) \
                        and isinstance(node.target, ast.Name) \
                        and node.target.id in owned \
                        and isinstance(node.iter, ast.Name) \
                        and node.iter.id not in owned:
                    owned.add(node.iter.id)
                    grew = True
            if not grew:
                break
        return owned

    def _owned_call_sites(self, tree: ast.AST,
                          owned_names: Set[str]) -> Set[int]:
        """id()s of Call nodes appearing in an ownership position:
        a with-item, a return value, an assignment to ``self.X`` or to
        a name the module later joins/closes, or an argument to an
        ExitStack-style sink."""
        owned: Set[int] = set()

        def mark(node):
            if isinstance(node, ast.Call):
                owned.add(id(node))

        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    mark(item.context_expr)
            elif isinstance(node, ast.Return) and node.value:
                mark(node.value)
            elif isinstance(node, ast.Assign):
                val = node.value
                # comprehension building a collection of resources:
                # ownership of the collection name covers the element
                # constructor (``files = [open(p) for p in ps]``)
                elt = val.elt if isinstance(
                    val, (ast.ListComp, ast.SetComp,
                          ast.GeneratorExp)) else None
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        # self.X = open(...): the object owns it (its
                        # close()/__exit__ is a different method)
                        mark(val)
                        mark(elt)
                    elif isinstance(t, ast.Name) \
                            and t.id in owned_names:
                        mark(val)
                        mark(elt)
            elif isinstance(node, ast.Call):
                head = _dotted(node.func) or ""
                if head.rsplit(".", 1)[-1] in _OWNERSHIP_SINKS:
                    for a in node.args:
                        mark(a)
        return owned

    # -------------------------------------------------------- checks
    def _check_call(self, relpath: str, node: ast.Call,
                    owned_calls: Set[int],
                    findings: List[Finding]) -> None:
        if id(node) in owned_calls:
            return
        if _is_thread_ctor(node):
            if not any(kw.arg == "daemon" for kw in node.keywords):
                findings.append(self.finding(
                    relpath, node, "RES001",
                    "Thread() without daemon= and no visible .join() "
                    "owner — it will outlive shutdown; pass daemon= "
                    "explicitly or join it"))
        elif _is_opener(node):
            findings.append(self.finding(
                relpath, node, "RES002",
                f"{_dotted(node.func)}(...) outside a context manager "
                "with no close ownership — the handle leaks on any "
                "exception before close; use 'with' or an ExitStack"))

    def _check_except(self, relpath: str, node: ast.ExceptHandler,
                      findings: List[Finding]) -> None:
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        silent = (len(node.body) == 1
                  and isinstance(node.body[0], ast.Pass))
        if broad and silent:
            what = ("bare except" if node.type is None
                    else f"except {node.type.id}")
            # anchor at the pass, not the except: the pass is the
            # defect, and a suppression reads naturally next to it
            findings.append(self.finding(
                relpath, node.body[0], "RES003",
                f"{what}: pass swallows every error with no metric "
                "or log — narrow it or record the failure"))
