"""graftlint CLI: scan, diff against the baseline, report.

Exit status is the contract: 0 means *no finding that is not in the
committed baseline* — new code is held to zero findings while the
pre-existing debt recorded in ``graftlint_baseline.json`` neither
fails the build nor silently grows (the baseline is count-exact per
(file, code): fixing a finding without refreshing the baseline is
fine; adding one is not).

Modes:

- ``scripts/graftlint.py FILE...`` — scan just those files, all rules
  (no scope filter: explicit paths mean "tell me everything here").
- ``--all`` — full repo scan, code scoping applied, per-file cache on.
- ``--changed`` — scan files touched vs HEAD (staged + unstaged +
  untracked); falls back to ``--all`` when git is unavailable.
  Repo-level checkers (observability-drift) always run in full.
- ``--write-baseline`` — accept the current findings as debt.
- ``--json`` / ``--report PATH`` — machine-readable findings document
  (the CI artifact ``graftlint_report.json``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .cache import DEFAULT_CACHE, FileCache
from .core import (SCHEMA_VERSION, all_checkers, iter_target_files,
                   run_checkers)


def _find_root(start: str) -> str:
    """Nearest ancestor holding a .git dir or the bigdl_tpu package."""
    cur = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(cur, ".git")) \
                or os.path.isdir(os.path.join(cur, "bigdl_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def _changed_files(root: str) -> Optional[List[str]]:
    """Tracked files touched vs HEAD plus untracked files, as
    repo-relative paths; None when git can't answer (not a checkout,
    no git binary) so the caller can fall back to a full scan."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30)
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or extra.returncode != 0:
        return None
    seen = []
    for line in (diff.stdout + extra.stdout).splitlines():
        line = line.strip()
        if line and line not in seen:
            seen.append(line)
    return seen


def run(root: str, paths: Optional[List[str]] = None,
        scoped: bool = True, use_cache: bool = True):
    """Scan and return (findings, n_suppressed). ``paths`` of None
    means the whole tree; explicit paths skip code scoping."""
    cache = FileCache(os.path.join(root, DEFAULT_CACHE)) \
        if use_cache else None
    findings, n_sup = run_checkers(root, relpaths=paths, scoped=scoped,
                                   cache=cache)
    if cache is not None:
        cache.save()
    return findings, n_sup


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based static analysis for jit hazards, lock "
                    "discipline, observability drift, and resource "
                    "hygiene. Exit 0 iff no non-baselined findings.")
    p.add_argument("paths", nargs="*",
                   help="files to scan (all rules, no scope filter); "
                        "default: --changed behavior")
    p.add_argument("--all", action="store_true",
                   help="scan the whole repository")
    p.add_argument("--changed", action="store_true",
                   help="scan files changed vs HEAD (falls back to "
                        "--all without git)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "<root>/graftlint_baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings as the new baseline")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the findings document as JSON")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="also write the JSON findings document here")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and do not write the per-file cache")
    p.add_argument("--list-checkers", action="store_true",
                   help="print registered checkers and codes, exit 0")
    args = p.parse_args(argv)

    root = os.path.abspath(args.root) if args.root \
        else _find_root(os.getcwd())

    if args.list_checkers:
        for c in all_checkers():
            print(f"{c.name} (v{c.version})")
            for code, desc in sorted(c.codes.items()):
                print(f"  {code}: {desc}")
        return 0

    explicit = bool(args.paths)
    if explicit:
        paths = []
        for raw in args.paths:
            ap = os.path.abspath(raw)
            rel = os.path.relpath(ap, root).replace(os.sep, "/")
            paths.append(rel)
        scoped = False
    elif args.all:
        paths, scoped = None, True
    else:
        # --changed (also the default mode)
        changed = _changed_files(root)
        if changed is None:
            paths, scoped = None, True
        else:
            known = set(iter_target_files(root))
            paths = [c for c in changed if c in known]
            scoped = True

    findings, n_sup = run(root, paths=paths, scoped=scoped,
                          use_cache=not args.no_cache)

    baseline_path = args.baseline or os.path.join(
        root, baseline_mod.DEFAULT_BASELINE)
    if args.write_baseline:
        baseline_mod.write_baseline(findings, baseline_path)
        print(f"[graftlint] baseline written: {len(findings)} "
              f"finding(s) -> {os.path.relpath(baseline_path, root)}")
        return 0

    bl = baseline_mod.load_baseline(baseline_path)
    new, baselined = baseline_mod.split_findings(findings, bl)

    doc = {
        "schema": SCHEMA_VERSION,
        "root": root,
        "mode": ("paths" if explicit
                 else "all" if paths is None else "changed"),
        "checked": (len(paths) if paths is not None else "all"),
        "suppressed": n_sup,
        "baselined": len(baselined),
        "new": [f.to_dict() for f in new],
    }
    if args.report:
        tmp = args.report + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.report)
    if args.as_json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in sorted(new, key=lambda x: x.sort_key()):
            print(f"[graftlint] {f.render()}")
        tail = (f"{len(baselined)} baselined, {n_sup} suppressed"
                if (baselined or n_sup) else "clean")
        if new:
            print(f"[graftlint] FAIL: {len(new)} new finding(s) "
                  f"({tail})")
        else:
            print(f"[graftlint] ok: no new findings ({tail})")
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
