"""Cross-lowering builders: the production programs packaged for
``jax.export(platforms=["tpu"])``.

Nothing here needs TPU hardware. ``jax.export`` runs the FULL TPU
lowering pipeline from any host — including Mosaic for the pallas flash
kernel, whose compiled payload lands in the module as a
``tpu_custom_call`` — so Mosaic/layout/lowering breakage is caught
offline instead of eating a live-hardware window (the axon tunnel can
wedge for hours; see PERF.md). Consumers: ``tests/test_tpu_lowering.py``
(fast shapes, every suite run) and ``scripts/tpu_export.py`` (flagship
shapes, records artifact hashes in ``TPU_LOWERING.json``).

Each builder returns ``(fn, args)`` where ``fn`` is the jitted program
and ``args`` are ``ShapeDtypeStruct``s carrying the production
shardings, ready for ``jax.export.export(fn, platforms=["tpu"])(*args)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P


def _abstract(tree):
    """Concrete pytree -> ShapeDtypeStructs preserving shardings."""
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=getattr(a, "sharding", None)),
        tree)


def flash_attention_program(b: int = 2, h: int = 8, h_kv: int = 4,
                            t: int = 1024, d: int = 64,
                            dtype=jnp.bfloat16, grad: bool = True):
    """The pallas flash kernel at its shipped auto_block default (256
    when the sequence tiles into it, else 128 — tuned on hardware, see
    flash_matrix.jsonl) with the GQA BlockSpec index map, fwd (+bwd when
    ``grad``), single chip.
    This is the program whose Mosaic lowering has never run on hardware —
    the VERDICT r4 bar (``ops/flash_attention.py`` must survive real
    Mosaic lowering, not just interpret mode)."""
    from bigdl_tpu.ops.flash_attention import flash_attention

    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=False)

    if grad:
        def loss(q, k, v):
            return jnp.mean(fwd(q, k, v).astype(jnp.float32))

        fn = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    else:
        fn = jax.jit(fwd)
    q = jax.ShapeDtypeStruct((b, h, t, d), dtype)
    kv = jax.ShapeDtypeStruct((b, h_kv, t, d), dtype)
    return fn, (q, kv, kv)


def ring_flash_program(n_devices: int = 8, t_per_shard: int = 256,
                       dtype=jnp.bfloat16):
    """Ring attention composed with the flash kernel (trainable custom
    vjp), sharded over a ('data', 'seq') mesh — K/V blocks rotate over
    the 'seq' axis via ppermute, each ring step runs the Mosaic kernel."""
    from bigdl_tpu.parallel import Engine
    from bigdl_tpu.parallel.ring_attention import ring_attention

    dp = 2 if n_devices % 2 == 0 else 1
    sp = n_devices // dp
    mesh = Engine.create_mesh([("data", dp), ("seq", sp)])
    b, h, h_kv, d = 2 * dp, 8, 4, 64
    t = t_per_shard * sp

    def body(q, k, v):
        def loss_fn(q, k, v):
            o = ring_attention(q, k, v, axis_name="seq", causal=True,
                               use_flash=True, interpret=False)
            return jnp.mean(o.astype(jnp.float32))

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(q, k, v)
        return lax.pmean(loss, ("data", "seq")), grads

    spec = P("data", None, "seq", None)
    fn = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(P(), (spec, spec, spec)), check_vma=False))
    sh = NamedSharding(mesh, spec)
    q = jax.ShapeDtypeStruct((b, h, t, d), dtype, sharding=sh)
    kv = jax.ShapeDtypeStruct((b, h_kv, t, d), dtype, sharding=sh)
    return fn, (q, kv, kv)


def distri_sharded_step_program(model_name: str = "lenet5",
                                n_devices: int = 8,
                                global_batch: int = 32,
                                format: str = "NCHW",
                                mesh=None):
    """The PRODUCTION DistriOptimizer ZeRO-1 sharded train step — the
    exact program ``_build_sharded_step`` jits (reduce-scatter bf16 wire,
    per-shard update, all-gather, donation), with abstract args laid out
    exactly as ``_optimize_impl`` lays them out."""
    from bigdl_tpu import nn
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.perf import build_model
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.parallel import DistriOptimizer, Engine
    from bigdl_tpu.parallel.all_reduce import flatten_params, pad_to_multiple
    from bigdl_tpu.utils import random as bt_random

    mesh = mesh or Engine.create_mesh([("data", n_devices)])
    n_data = mesh.shape["data"]
    model, input_shape, class_num = build_model(model_name, format=format)
    criterion = (nn.CrossEntropyCriterion() if model_name.startswith("resnet")
                 else nn.ClassNLLCriterion())
    dummy = [Sample(np.zeros(input_shape, np.float32),
                    np.array([1.0], np.float32))]
    opt = DistriOptimizer(model=model, dataset=DataSet.array(dummy),
                          criterion=criterion, batch_size=global_batch,
                          end_when=Trigger.max_iteration(1), mesh=mesh,
                          parameter_sync="sharded")
    method = SGD(learning_rate=0.01)
    opt.set_optim_method(method)

    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("data"))
    params = jax.device_put(model.params_dict(), repl)
    buffers = jax.device_put(
        jax.tree.map(lambda bf: jnp.broadcast_to(bf[None],
                                                 (n_data,) + bf.shape),
                     model.buffers_dict()),
        data_sh)
    flat, _ = flatten_params(params)
    flat, _ = pad_to_multiple(flat, n_data)
    flat = jax.device_put(flat, data_sh)
    slots = method.init_slots(flat)
    step, _, _ = opt._build_sharded_step(model, criterion, method, None,
                                         slots)
    x = jax.ShapeDtypeStruct((global_batch,) + tuple(input_shape),
                             jnp.float32, sharding=data_sh)
    y = jax.ShapeDtypeStruct((global_batch, 1), jnp.float32,
                             sharding=data_sh)
    lrs = jax.ShapeDtypeStruct((), jnp.float32, sharding=repl)
    rng = _abstract(jax.device_put(bt_random.next_key(), repl))
    return step, (_abstract(params), _abstract(buffers), _abstract(flat),
                  _abstract(slots), x, y, lrs, rng)


def combined_3d_program(n_devices: int = 8, t_per_shard: int = 8,
                        embed_dim: int = 16, vocab: int = 32,
                        use_flash: bool = False,
                        abstract_args: bool = False):
    """The combined dp x sp x ep train step from the driver dryrun
    (``__graft_entry__._dryrun_combined_3d``): RoPE + GQA + ring
    attention over 'seq' + MoE all_to_all over 'expert' in one shard_map,
    per-axis-correct gradient reductions.

    ``use_flash=True`` + a 128-tileable ``t_per_shard`` makes the ring
    run the pallas kernel, so the exported module carries the Mosaic
    kernel inside the full composed program. ``abstract_args`` returns
    ShapeDtypeStructs (export) instead of concrete arrays (dryrun)."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.module import pure_apply
    from bigdl_tpu.parallel import Engine

    ep = 2 if n_devices % 2 == 0 else 1
    rest = n_devices // ep
    dp = 2 if rest % 2 == 0 and rest > 1 else 1
    sp = rest // dp
    mesh = Engine.create_mesh([("data", dp), ("seq", sp), ("expert", ep)])
    seq_len = t_per_shard * sp
    model = TransformerLM(vocab_size=vocab, embed_dim=embed_dim,
                          num_heads=4, num_kv_heads=2, use_rope=True,
                          num_layers=1, max_len=seq_len, causal=True,
                          sequence_parallel="seq", use_flash=use_flash,
                          n_experts=2 * ep, expert_parallel="expert")
    apply_fn = pure_apply(model)
    params, buffers = model.params_dict(), model.buffers_dict()

    EXPERT_LEAVES = {"w1", "b1", "w2", "b2"}

    def spec_of(path, _leaf):
        names = {getattr(k, "key", getattr(k, "name", None)) for k in path}
        if names & {"mlp"} and names & EXPERT_LEAVES:
            return P("expert")
        return P()

    pspec = jax.tree_util.tree_map_with_path(spec_of, params)

    def step(p, ids, targets):
        def loss_fn(p):
            logits, _ = apply_fn(p, buffers, ids, rng=None, training=True)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return -jnp.mean(ll) + 0.01 * model.l_aux

        loss, grads = jax.value_and_grad(loss_fn)(p)
        loss = lax.pmean(loss, ("data", "seq", "expert"))
        # expert-sharded leaves average over the axes their tokens came
        # from, never over 'expert' itself
        grads = jax.tree.map(
            lambda g, s: lax.pmean(
                g, ("data", "seq") if s == P("expert")
                else ("data", "seq", "expert")),
            grads, pspec)
        return loss, jax.tree.map(lambda a, g: a - 0.1 * g, p, grads)

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(pspec, P(("data", "expert"), "seq"),
                  P(("data", "expert"), "seq")),
        out_specs=(P(), pspec), check_vma=False))

    dsh = NamedSharding(mesh, P(("data", "expert"), "seq"))
    psh = jax.tree.map(lambda s: NamedSharding(mesh, s), pspec)
    if abstract_args:
        params = jax.tree.map(
            lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=sh),
            params, psh)
        ids = jax.ShapeDtypeStruct((2 * dp * ep, seq_len), jnp.int32,
                                   sharding=dsh)
        return fn, (params, ids, ids)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (2 * dp * ep, seq_len)).astype(np.int32)
    targets = np.roll(ids, -1, axis=1).astype(np.int32)
    params = jax.device_put(params, psh)
    ids = jax.device_put(ids, dsh)
    targets = jax.device_put(targets, dsh)
    return fn, (params, ids, targets)


def _serving_model(batch, vocab, embed_dim, layers, heads, kv_heads,
                   max_len, dtype):
    """Shared serving-program setup: the LM in eval mode with
    dtype-cast params, plus abstract (params, buffers, caches)."""
    from bigdl_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab, embed_dim=embed_dim, num_heads=heads,
                          num_kv_heads=kv_heads, num_layers=layers,
                          max_len=max_len, use_rope=True)
    model.evaluate()
    params = jax.tree.map(
        lambda a: (a.astype(dtype)
                   if jnp.issubdtype(a.dtype, jnp.floating) else a),
        model.params_dict())
    caches = _abstract(model.init_cache(batch, max_len, dtype=dtype))
    return (model, _abstract(params), _abstract(model.buffers_dict()),
            caches)


def decode_step_program(batch: int = 8, vocab: int = 32000,
                        embed_dim: int = 512, layers: int = 8, heads: int = 8,
                        kv_heads: int = 2, max_len: int = 2048,
                        dtype=jnp.bfloat16):
    """The serving flagship: one KV-cache decode step (GQA, RoPE, bf16
    cache) — the program run per generated token."""
    from bigdl_tpu.nn.module import bind

    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)

    def step(p, bufs, ids_t, pos, caches):
        with bind(model, p, bufs, False, None):
            return model.decode_step(ids_t, pos, caches)

    ids_t = jax.ShapeDtypeStruct((batch,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    return (jax.jit(step, donate_argnums=(4,)),
            (params, buffers, ids_t, pos, caches))


def decode_scan_program(batch: int = 8, n_tokens: int = 32,
                        vocab: int = 32000, embed_dim: int = 512,
                        layers: int = 8, heads: int = 8,
                        kv_heads: int = 2, max_len: int = 2048,
                        dtype=jnp.bfloat16):
    """The one-dispatch serving loop: n_tokens of sample->decode_step as a
    single on-device ``lax.scan`` (TransformerLM.decode_scan) — what
    generate() actually runs per batch, so its TPU lowering is the one
    that matters for serving."""
    from bigdl_tpu.nn.module import bind

    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)

    def scan_fn(p, bufs, logits, pos0, caches, rng):
        with bind(model, p, bufs, False, None):
            # eos + nucleus filtering included so the lowered module
            # carries the cond-skip and the per-step vocab sort too
            return model.decode_scan(logits, pos0, caches, rng,
                                     jnp.float32(0.8), n_tokens,
                                     sampled=True, eos_id=2, top_p=0.95)

    logits = jax.ShapeDtypeStruct((batch, vocab), dtype)
    pos0 = jax.ShapeDtypeStruct((), jnp.int32)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return (jax.jit(scan_fn, donate_argnums=(2, 4)),
            (params, buffers, logits, pos0, caches, rng))


def sharded_decode_scan_program(n_devices: int = 8, batch: int = 4,
                                n_tokens: int = 16, vocab: int = 32000,
                                embed_dim: int = 512, layers: int = 8,
                                heads: int = 8, kv_heads: int = 2,
                                max_len: int = 2048, dtype=jnp.bfloat16):
    """The long-context serving lowering: the one-dispatch greedy decode
    loop with the KV caches SHARDED along T over the mesh (params
    replicated) — generate(kv_cache_sharding=...)'s program. GSPMD
    partitions the per-step attention + softmax reductions across
    devices (flash-decoding style)."""
    from bigdl_tpu.nn.module import bind
    from bigdl_tpu.parallel import Engine

    mesh = Engine.create_mesh([("seq", n_devices)])
    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)
    rep = NamedSharding(mesh, P())

    def reshard(tree, sh):
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            tree)

    params, buffers = reshard(params, rep), reshard(buffers, rep)
    caches = reshard(caches, NamedSharding(mesh, P(None, None, "seq",
                                                   None)))

    def scan_fn(p, bufs, logits, pos0, caches, rng):
        with bind(model, p, bufs, False, None):
            return model.decode_scan(logits, pos0, caches, rng,
                                     jnp.float32(1.0), n_tokens,
                                     sampled=False, eos_id=2)

    logits = jax.ShapeDtypeStruct((batch, vocab), dtype, sharding=rep)
    pos0 = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep)
    return (jax.jit(scan_fn, donate_argnums=(4,)),
            (params, buffers, logits, pos0, caches, rng))


def ragged_decode_program(batch: int = 8, n_tokens: int = 32,
                          vocab: int = 32000, embed_dim: int = 512,
                          layers: int = 8, heads: int = 8,
                          kv_heads: int = 2, max_len: int = 2048,
                          dtype=jnp.bfloat16):
    """The ragged serving program (generate_ragged / GenerationService):
    per-row last-valid prefill + the decode scan carrying a (B,) per-row
    position vector — per-row cache writes, masks, and RoPE."""
    from bigdl_tpu.nn.module import bind

    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)

    def ragged(p, bufs, ids, lengths, caches, rng):
        with bind(model, p, bufs, False, None):
            logits, caches = model._prefill_impl(
                ids, caches, 0, chunked=False, gather_last=lengths - 1)
            return model.decode_scan(logits, lengths, caches, rng,
                                     jnp.float32(0.8), n_tokens,
                                     sampled=True, eos_id=2, top_p=0.95)

    tmax = max_len - n_tokens
    ids = jax.ShapeDtypeStruct((batch, tmax), jnp.int32)
    lengths = jax.ShapeDtypeStruct((batch,), jnp.int32)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return (jax.jit(ragged, donate_argnums=(4,)),
            (params, buffers, ids, lengths, caches, rng))


def beam_scan_program(batch: int = 4, beams: int = 4, n_tokens: int = 32,
                      vocab: int = 32000, embed_dim: int = 512,
                      layers: int = 8, heads: int = 8, kv_heads: int = 2,
                      max_len: int = 2048, dtype=jnp.bfloat16):
    """The one-dispatch scanned beam search (select->step scan +
    parent-pointer backtracking, TransformerLM._beam_scan_fn's program)
    — beam serving's TPU lowering."""
    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)
    inner = model._beam_scan_closure(batch, beams, n_tokens, eos_id=2)

    logits = jax.ShapeDtypeStruct((batch, vocab), dtype)
    pos0 = jax.ShapeDtypeStruct((), jnp.int32)
    lp = jax.ShapeDtypeStruct((), jnp.float32)
    return (jax.jit(inner, donate_argnums=(4,)),
            (params, buffers, logits, pos0, caches, lp))


def chunked_prefill_program(batch: int = 8, chunk: int = 256,
                            vocab: int = 32000, embed_dim: int = 512,
                            layers: int = 8, heads: int = 8,
                            kv_heads: int = 2, max_len: int = 2048,
                            dtype=jnp.bfloat16):
    """One traced-offset prefill chunk (generate(prefill_chunk=...)) —
    the long-prompt serving path: fixed chunk length, full-cache masked
    attention, one compilation for every offset."""
    from bigdl_tpu.nn.module import bind

    model, params, buffers, caches = _serving_model(
        batch, vocab, embed_dim, layers, heads, kv_heads, max_len, dtype)

    def chunk_fn(p, bufs, ids, caches, pos0):
        with bind(model, p, bufs, False, None):
            return model.prefill_chunk(ids, caches, pos0)

    ids = jax.ShapeDtypeStruct((batch, chunk), jnp.int32)
    pos0 = jax.ShapeDtypeStruct((), jnp.int32)
    return (jax.jit(chunk_fn, donate_argnums=(3,)),
            (params, buffers, ids, caches, pos0))


def combined_3d_flash_program(n_devices: int = 8, t_per_shard: int = 256,
                              embed_dim: int = 256):
    """The combined dp x sp x ep step at FLASH-ELIGIBLE shapes: per-shard
    sequence tiles into the pallas kernel's auto blocks, so the exported
    module carries the Mosaic kernel INSIDE the full composed program
    (ring + MoE + RoPE + GQA), unlike the tiny-shape dryrun variant whose
    ring falls back to the dense path. (One parameterization of
    combined_3d_program — the expert-gradient reduction rule lives in
    exactly one place.)"""
    return combined_3d_program(n_devices, t_per_shard=t_per_shard,
                               embed_dim=embed_dim, vocab=128,
                               use_flash=True, abstract_args=True)


def export_for_tpu(fn, args):
    """jax.export the program for platforms=["tpu"]; returns the Exported.
    Tracing runs under ``force_interpret(False)`` so every flash call
    site (including ones buried inside full models, whose interpret
    default follows the HOST platform) lowers the real Mosaic kernel."""
    from jax import export

    from bigdl_tpu.ops.flash_attention import force_interpret

    with force_interpret(False):
        return export.export(fn, platforms=["tpu"])(*args)
