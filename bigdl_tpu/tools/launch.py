"""``bigdl-tpu-launch`` — one command that hides cluster topology.

The reference wraps its whole Spark topology behind single launcher
scripts (ref: scripts/spark-submit-with-bigdl.sh:1,
pyspark-with-bigdl.sh:1); this is the TPU-pod analog (SURVEY §7 "Hard
parts"): it wires ``jax.distributed.initialize`` coordinator/rank and
then execs the user's training main, so user code never touches
topology.

Three ways in:

* **TPU pod slice** (default, no flags)::

      gcloud compute tpus tpu-vm ssh $TPU --worker=all \\
          --command "bigdl-tpu-launch train.py --epochs 10"

  Every host runs the same line; ``jax.distributed.initialize()``
  auto-discovers coordinator/rank/process-count from the TPU metadata.
  On a single non-pod host the auto-init is skipped and the script just
  runs (so the same command works from a laptop to a v5e-256).

* **Explicit cluster** (non-TPU or custom DNS)::

      bigdl-tpu-launch --coordinator host0:1234 --num-procs 4 \\
          --proc-id $RANK train.py

* **Local multi-process grid** (``--procs N``) — the testing mode: N
  processes on THIS host form a real ``jax.distributed`` cluster on the
  CPU backend, each with ``--cpu-devices K`` virtual devices (an
  N×K-device pod without hardware; the validated recipe of
  tests/multihost_child.py)::

      bigdl-tpu-launch --procs 2 --cpu-devices 4 train.py
"""

from __future__ import annotations

import argparse
import os
import runpy
import socket
import subprocess
import sys

_ENV_COORD = "BIGDL_TPU_COORDINATOR"
_ENV_NPROCS = "BIGDL_TPU_NUM_PROCS"
_ENV_PID = "BIGDL_TPU_PROC_ID"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_user_main(script: str, script_args, as_module: bool) -> None:
    """Exec the user's main in THIS process (distributed is already up),
    exactly as ``python script.py args`` / ``python -m pkg.mod args``
    would see it."""
    sys.argv = [script] + list(script_args)
    if as_module:
        runpy.run_module(script, run_name="__main__", alter_sys=True)
    else:
        runpy.run_path(script, run_name="__main__")


# Child bootstrap for the local grid, run via `python -c` so NOTHING
# (not even this package, whose import touches jax) loads before
# jax.distributed.initialize — the ordering jax requires. A FAILING rank
# must os._exit: the normal exit path runs jax's atexit distributed
# shutdown, which is a BARRIER over all ranks — a crashed rank would
# block there forever waiting for peers that are stuck waiting for it.
# Successful ranks exit normally (all reach the barrier; it completes).
_BOOTSTRAP = f"""
import os, runpy, sys, traceback
import jax
jax.distributed.initialize(os.environ['{_ENV_COORD}'],
                           num_processes=int(os.environ['{_ENV_NPROCS}']),
                           process_id=int(os.environ['{_ENV_PID}']))
tgt = sys.argv[1]
as_mod = sys.argv[2] == '1'
sys.argv = [tgt] + sys.argv[3:]
try:
    if as_mod:
        runpy.run_module(tgt, run_name='__main__', alter_sys=True)
    else:
        runpy.run_path(tgt, run_name='__main__')
except SystemExit as e:
    code = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
    if code:
        sys.stderr.flush(); sys.stdout.flush()
        os._exit(code)
except BaseException:
    traceback.print_exc()
    sys.stderr.flush(); sys.stdout.flush()
    os._exit(1)
"""


def _spawn_local_grid(args) -> int:
    port = args.port or _free_port()
    env_base = dict(os.environ)
    # CPU backend for the virtual grid. The axon sitecustomize (when on
    # PYTHONPATH) dials the TPU tunnel from EVERY interpreter and can
    # deadlock with a pre-startup platform pin — drop it for CPU children.
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["PYTHONPATH"] = os.pathsep.join(
        p for p in env_base.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or ""
    flags = [f for f in env_base.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(
        f"--xla_force_host_platform_device_count={args.cpu_devices}")
    env_base["XLA_FLAGS"] = " ".join(flags)

    procs = []
    for i in range(args.procs):
        env = dict(env_base)
        env[_ENV_COORD] = f"localhost:{port}"
        env[_ENV_NPROCS] = str(args.procs)
        env[_ENV_PID] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _BOOTSTRAP, args.script,
             "1" if args.module else "0", *args.script_args], env=env))
    # poll rather than wait sequentially: a crashed rank strands its
    # peers inside collectives, so the FIRST failure must kill survivors
    # or the launcher would hang on them forever
    import time as _time

    rc = 0
    live = list(procs)
    while live:
        for p in list(live):
            code = p.poll()
            if code is None:
                continue
            live.remove(p)
            if code and not rc:
                rc = code
                for q in live:
                    q.kill()
        if live:
            _time.sleep(0.2)
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bigdl-tpu-launch",
        description="Launch a training main with jax.distributed wired up "
                    "(TPU-pod auto-discovery, explicit cluster, or a local "
                    "N-process CPU grid for testing)")
    p.add_argument("--procs", type=int, default=None,
                   help="local grid: spawn N processes on this host")
    p.add_argument("--cpu-devices", type=int, default=1,
                   help="local grid: virtual CPU devices per process")
    p.add_argument("--port", type=int, default=None,
                   help="local grid: coordinator port (default: free port)")
    p.add_argument("--coordinator", default=None,
                   help="explicit cluster: coordinator host:port")
    p.add_argument("--num-procs", type=int, default=None,
                   help="explicit cluster: total process count")
    p.add_argument("--proc-id", type=int, default=None,
                   help="explicit cluster: this process's rank")
    p.add_argument("-m", "--module", action="store_true",
                   help="treat the target as a module name (python -m style)")
    p.add_argument("script", help="training script (or module with -m) to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER,
                   help="arguments passed through to the script")
    args = p.parse_args(argv)
    if args.procs is not None:
        if args.procs < 1:
            p.error("--procs must be >= 1")
        return _spawn_local_grid(args)

    import jax

    if args.coordinator is not None:
        if args.num_procs is None or args.proc_id is None:
            p.error("--coordinator needs --num-procs and --proc-id")
        jax.distributed.initialize(args.coordinator,
                                   num_processes=args.num_procs,
                                   process_id=args.proc_id)
    else:
        try:
            # TPU pod: coordinator/rank auto-discovered from metadata
            jax.distributed.initialize()
        except Exception as e:  # single host / no cluster env — run anyway
            print(f"bigdl-tpu-launch: single-process run "
                  f"(auto-init skipped: {e})", file=sys.stderr)
    _run_user_main(args.script, args.script_args, args.module)
    return 0


if __name__ == "__main__":
    sys.exit(main())
