"""Operational CLIs shipped as console scripts (≙ the reference's
``scripts/`` launchers, ref: scripts/spark-submit-with-bigdl.sh:1)."""
