"""Module tree ↔ (json spec, tensor archive).

Reference: utils/serializer/ (ModuleSerializer reflection +
converters/DataConverter typed attributes + TensorStorageManager spill,
SURVEY.md §2.7). Design here: every Module subclass records its
constructor call (bigdl_tpu.utils.config_capture); the serializer encodes
that config with a small value codec (primitives, containers, tensors,
nested modules, captured objects like regularizers/init methods), plus the
parameter/buffer arrays, plus any children attached after construction
(Container.add). Graphs carry their node topology via
``__serialize_spec__`` / ``__deserialize_spec__`` hooks.

Format: ``path`` is a zip with
  module.json — {"format": 1, "root": id, "records": {id: record}}
  tensors.npz — numpy arrays keyed t0, t1, ...
"""

from __future__ import annotations

import importlib
import io
import json
import os
import zipfile
from typing import Dict

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.config_capture import get_init_config
from bigdl_tpu.utils.table import Table


class _Ctx:
    def __init__(self):
        self.records: Dict[str, dict] = {}
        self.mod_ids: Dict[int, str] = {}
        self.tensors: Dict[str, np.ndarray] = {}

    def tensor_key(self, arr) -> str:
        key = f"t{len(self.tensors)}"
        self.tensors[key] = np.asarray(arr)
        return key


def _class_path(obj) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _resolve_class(path: str):
    mod, _, name = path.rpartition(".")
    target = importlib.import_module(mod)
    for part in name.split("."):
        target = getattr(target, part)
    return target


def _encode(value, ctx: _Ctx):
    if value is None or isinstance(value, (bool, int, str)):
        return value
    if isinstance(value, float):
        return {"t": "f", "v": repr(value)}  # repr round-trips inf/nan via eval-free parse
    if isinstance(value, Module):
        return {"t": "module", "id": _serialize_module(value, ctx)}
    if isinstance(value, Table):
        return {"t": "table", "items": [_encode(v, ctx) for v in value]}
    if isinstance(value, (list, tuple)):
        return {"t": "tuple" if isinstance(value, tuple) else "list",
                "items": [_encode(v, ctx) for v in value]}
    if isinstance(value, dict):
        return {"t": "dict", "items": [[_encode(k, ctx), _encode(v, ctx)]
                                       for k, v in value.items()]}
    if isinstance(value, (np.ndarray, jnp.ndarray)):
        return {"t": "tensor", "key": ctx.tensor_key(value)}
    if np.isscalar(value) and hasattr(value, "item"):  # numpy scalar
        return _encode(value.item(), ctx)
    if hasattr(value, "_init_config"):  # captured object (regularizer, init, ...)
        args, kwargs = get_init_config(value)
        return {"t": "obj", "class": _class_path(value),
                "args": [_encode(a, ctx) for a in args],
                "kwargs": {k: _encode(v, ctx) for k, v in kwargs.items()}}
    if type(value).__name__ == "dtype" or value in (jnp.float32, jnp.bfloat16,
                                                    jnp.float16, jnp.int32):
        return {"t": "dtype", "v": np.dtype(value).name if not hasattr(value, "dtype")
                else np.dtype(value.dtype).name}
    raise TypeError(
        f"cannot serialize constructor argument of type {type(value)!r}: {value!r}")


def _decode(enc, ctx_records, ctx_tensors, memo):
    if enc is None or isinstance(enc, (bool, int, str)):
        return enc
    t = enc["t"]
    if t == "f":
        return float(enc["v"])
    if t == "module":
        return _materialize(enc["id"], ctx_records, ctx_tensors, memo)
    if t == "table":
        return Table(*[_decode(v, ctx_records, ctx_tensors, memo) for v in enc["items"]])
    if t == "tuple":
        return tuple(_decode(v, ctx_records, ctx_tensors, memo) for v in enc["items"])
    if t == "list":
        return [_decode(v, ctx_records, ctx_tensors, memo) for v in enc["items"]]
    if t == "dict":
        return {_decode(k, ctx_records, ctx_tensors, memo):
                _decode(v, ctx_records, ctx_tensors, memo) for k, v in enc["items"]}
    if t == "tensor":
        return jnp.asarray(ctx_tensors[enc["key"]])
    if t == "dtype":
        return jnp.dtype(enc["v"])
    if t == "obj":
        cls = _resolve_class(enc["class"])
        args = [_decode(a, ctx_records, ctx_tensors, memo) for a in enc["args"]]
        kwargs = {k: _decode(v, ctx_records, ctx_tensors, memo)
                  for k, v in enc["kwargs"].items()}
        return cls(*args, **kwargs)
    raise ValueError(f"unknown encoded tag {t!r}")


def _serialize_module(module: Module, ctx: _Ctx) -> str:
    mid = ctx.mod_ids.get(id(module))
    if mid is not None:
        return mid
    mid = f"m{len(ctx.mod_ids)}"
    ctx.mod_ids[id(module)] = mid
    rec: dict = {"class": _class_path(module), "name": module._name}
    ctx.records[mid] = rec  # register before recursing (shared-module cycles)

    if hasattr(module, "__serialize_spec__"):
        rec["custom"] = module.__serialize_spec__(
            lambda m: _serialize_module(m, ctx),
            lambda arr: ctx.tensor_key(arr))
    else:
        args, kwargs = get_init_config(module)
        rec["init"] = {"args": [_encode(a, ctx) for a in args],
                       "kwargs": {k: _encode(v, ctx) for k, v in kwargs.items()}}
        rec["children"] = [[name, _serialize_module(child, ctx)]
                           for name, child in module._modules.items()]
    rec["params"] = {k: ctx.tensor_key(v) for k, v in module._parameters.items()}
    rec["buffers"] = {k: ctx.tensor_key(v) for k, v in module._buffers.items()}
    rec["frozen"] = bool(module._frozen)
    extra = _extra_state(module)
    if extra:
        rec["extra"] = {k: _encode(v, ctx) for k, v in extra.items()}
    return mid


_TRANSIENT_ATTRS = {"output", "grad_input", "training"}


def _is_plain(v) -> bool:
    if v is None or isinstance(v, (bool, int, str)):
        return True
    if isinstance(v, float):
        return np.isfinite(v)  # inf defaults (e.g. max_norm) re-derive from init
    if isinstance(v, (tuple, list)):
        return all(_is_plain(i) for i in v)
    return False


def _extra_state(module: Module) -> dict:
    """Primitive attributes mutated after construction (``.ceil()``,
    ``set_p``...). Restored verbatim on load — constructor args alone don't
    capture builder-style mutations."""
    out = {}
    for k, v in vars(module).items():
        if k.startswith("_") or k in _TRANSIENT_ATTRS:
            continue
        if k in module._parameters or k in module._buffers or k in module._modules:
            continue
        if _is_plain(v):
            out[k] = v
    return out


def _materialize(mid: str, records, tensors, memo) -> Module:
    if mid in memo:
        return memo[mid]
    rec = records[mid]
    cls = _resolve_class(rec["class"])

    if "custom" in rec:
        inst = cls.__deserialize_spec__(
            rec["custom"],
            lambda child_id: _materialize(child_id, records, tensors, memo),
            lambda key: jnp.asarray(tensors[key]))
        memo[mid] = inst
    else:
        init = rec["init"]
        args = [_decode(a, records, tensors, memo) for a in init["args"]]
        kwargs = {k: _decode(v, records, tensors, memo)
                  for k, v in init["kwargs"].items()}
        inst = cls(*args, **kwargs)
        memo[mid] = inst
        for name, child_id in rec["children"]:
            child = _materialize(child_id, records, tensors, memo)
            if name not in inst._modules or inst._modules[name] is not child:
                inst._modules[name] = child
                object.__setattr__(inst, name, child)

    for k, key in rec["params"].items():
        inst._set_param(k, jnp.asarray(tensors[key]))
        inst._gradients[k] = jnp.zeros_like(inst._parameters[k])
    for k, key in rec["buffers"].items():
        inst._set_buffer(k, jnp.asarray(tensors[key]))
    for k, enc in rec.get("extra", {}).items():
        setattr(inst, k, _decode(enc, records, tensors, memo))
    if rec.get("name"):
        inst.set_name(rec["name"])
    if rec.get("frozen"):
        inst._frozen = True
    return inst


def module_to_spec(module: Module):
    """(spec_dict, {tensor_key: np.ndarray}) — the in-memory form."""
    ctx = _Ctx()
    root = _serialize_module(module, ctx)
    return {"format": 1, "root": root, "records": ctx.records}, ctx.tensors


def module_from_spec(spec: dict, tensors) -> Module:
    return _materialize(spec["root"], spec["records"], tensors, {})


def save_module(module: Module, path: str, overwrite: bool = False) -> None:
    """≙ AbstractModule.saveModule (protobuf path, AbstractModule.scala:523)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    spec, tensors = module_to_spec(module)
    buf = io.BytesIO()
    np.savez(buf, **tensors)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("module.json", json.dumps(spec))
        z.writestr("tensors.npz", buf.getvalue())


def load_module(path: str) -> Module:
    """≙ Module.loadModule (nn/Module.scala:44-94 protobuf path)."""
    with zipfile.ZipFile(path, "r") as z:
        spec = json.loads(z.read("module.json").decode("utf-8"))
        with np.load(io.BytesIO(z.read("tensors.npz"))) as npz:
            tensors = {k: npz[k] for k in npz.files}
    return module_from_spec(spec, tensors)
