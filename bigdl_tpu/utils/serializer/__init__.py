"""Structured module serialization (≙ the reference's protobuf format).

Reference: utils/serializer/ModuleSerializer.scala:34-118 + bigdl.proto —
reflection-driven save/load of any registered layer with typed attribute
converters and tensor-storage management. TPU-native analog: a zip archive
holding ``module.json`` (the module tree: class path, constructor config,
child links, graph topology) and ``tensors.npz`` (all parameters/buffers
as numpy arrays), written/read by :mod:`bigdl_tpu.utils.serializer.serializer`.
"""

from bigdl_tpu.utils.serializer.serializer import (
    save_module, load_module, module_to_spec, module_from_spec,
)
