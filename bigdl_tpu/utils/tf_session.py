"""Train from an UNFROZEN TF graphdef: variables become trainable params.

Reference: utils/tf/Session.scala:54-330 (BigDLSessionImpl.train): loads a
TF training graph, turns VariableV2 nodes + their Assign initializers into
BigDL weights, and drives the standard Optimizer against a chosen loss
endpoint.

Here TensorflowLoader resolves each VariableV2's initial value from its
``Assign(var, Const)`` initializer (the tf.compat.v1 initializer pattern);
the variable becomes an ``nn.tf_ops.Variable`` module whose value is a
trainable parameter of the imported Graph, so the whole model trains under
the ordinary Optimizer/TrainStep machinery — no session/feed emulation.
"""

from __future__ import annotations

from typing import List, Optional

from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.optim_method import SGD, OptimMethod
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.tf_import import TensorflowLoader


class Session:
    """≙ BigDLSessionImpl (utils/tf/Session.scala:54). ``inputs`` are
    placeholder names; ``outputs`` the prediction endpoint(s)."""

    def __init__(self, graph_pb_path: str, inputs: List[str],
                 outputs: List[str]):
        self._loader = TensorflowLoader(graph_pb_path)
        self.model: Module = self._loader.load(list(inputs), list(outputs))

    def train(self, dataset, criterion, optim_method: Optional[OptimMethod] = None,
              end_when: Optional[Trigger] = None, batch_size: int = 32) -> Module:
        """≙ Session.train(endpoints, rdd, optMethod, criterion, endTrigger):
        imported variables update in place on the returned model."""
        from bigdl_tpu.optim.optimizer import Optimizer

        opt = Optimizer(model=self.model, dataset=dataset,
                        criterion=criterion, batch_size=batch_size,
                        end_when=end_when or Trigger.max_epoch(1))
        opt.set_optim_method(optim_method or SGD())
        return opt.optimize()

    def predict(self, x):
        self.model.evaluate()
        import jax.numpy as jnp

        return self.model(jnp.asarray(x))
