"""TensorFlow GraphDef import (inference subset).

Reference: utils/tf/TensorflowLoader.scala:55 + the 159 per-op loaders in
utils/tf/loaders/ — parse a frozen graph.pb, convert nodes to modules,
build a Graph between user-named inputs and outputs. Here the GraphDef is
decoded with utils/protowire against the public tensorflow .proto field
numbers; constants fold into their consumers (weights), and the supported
op set covers frozen feed-forward inference graphs: Placeholder, Const,
Identity, MatMul, BiasAdd/BiasAddV1, Add/AddV2, Relu, Relu6, Tanh, Sigmoid,
Softmax, Conv2D (NHWC), DepthwiseConv2dNative, MaxPool, AvgPool, Mean and
the reduction family (Sum/Max/Min/Prod/All/Any), Reshape, Squeeze, Pad,
ConcatV2, plus control-flow/state/parsing infra (see nn/tf_ops.py).

The reference's ``*Grad`` loaders (ReluGrad, MaxPoolGrad, Conv2DBackprop*,
FusedBatchNormGrad, ... — 18 files under utils/tf/loaders/) are absorbed by
design: training an imported graph goes through JAX autodiff over the
forward program (utils/tf_session.py), so hand-written gradient ops are
never imported. Likewise the queue/reader input-pipeline loaders
(QueueDequeue*/QueueEnqueue*/ReaderReadV2) — the reference splices RDDs in
their place (Session.scala adjustInputNames); here Session.train feeds a
DataSet directly at the placeholder boundary.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

#: ops whose module emits a Table of outputs; consumers reference "name:i"
_MULTI_OUTPUT_OPS = {"Split", "SplitV", "Unpack", "TopK", "TopKV2",
                     "SoftmaxCrossEntropyWithLogits"}

#: FunctionDef refs name the output arg ("node:out_arg:idx"); flat output
#: index = arg's base offset + idx. Ops with one (possibly repeated) output
#: arg have offset 0 and are omitted.
_OUT_ARG_OFFSET = {
    "TopK": {"values": 0, "indices": 1},
    "TopKV2": {"values": 0, "indices": 1},
    "Switch": {"output_false": 0, "output_true": 1},
    "Merge": {"output": 0, "value_index": 1},
}

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import protowire as pw

# tensorflow dtype enum (subset); 7 = DT_STRING (object arrays of bytes)
_DT = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 6: np.int8,
       7: object, 9: np.int64, 10: bool, 17: np.uint16}


def _parse_tensor(tensor_bytes: bytes) -> np.ndarray:
    msg = pw.decode(tensor_bytes)
    dtype = _DT.get(msg.get(1, [1])[0], np.float32)
    shape = []
    if 2 in msg:
        shape_msg = pw.decode(msg[2][0])
        for dim in shape_msg.get(2, []):
            shape.append(pw.as_signed(pw.decode(dim).get(1, [0])[0]))
    # TensorProto field numbers (tensorflow/core/framework/tensor.proto):
    # 4 tensor_content, 5 float_val, 6 double_val, 7 int_val, 9 int64_val,
    # 10 bool_val.  A tensor with NO value field is all default (zeros).
    if dtype is object:  # DT_STRING: string_val = field 8
        vals = [v for v in msg.get(8, [])]
        arr = np.asarray(vals, object)
    elif 4 in msg and msg[4][0]:  # tensor_content: raw bytes
        arr = np.frombuffer(msg[4][0], dtype=dtype).copy()
    elif 5 in msg:  # float_val
        vals = []
        for v in msg[5]:
            vals.extend(pw.packed_floats(v) if isinstance(v, bytes)
                        else [struct.unpack("<f", v if isinstance(v, bytes)
                                            else struct.pack("<I", v))[0]])
        arr = np.asarray(vals, np.float32)
    elif 7 in msg:  # int_val
        arr = np.asarray([pw.as_signed(v) for v in pw.repeated_varints(msg[7])],
                         np.int32)
    elif 9 in msg:  # int64_val
        arr = np.asarray([pw.as_signed(v) for v in pw.repeated_varints(msg[9])],
                         np.int64)
    elif 10 in msg:  # bool_val
        arr = np.asarray(pw.repeated_varints(msg[10]), bool)
    else:
        arr = np.zeros(tuple(shape), dtype)
    if shape:
        if arr.size == 1 and int(np.prod(shape)) > 1:
            arr = np.full(shape, arr.reshape(-1)[0])
        arr = arr.reshape(shape)
    elif arr.size == 1 and arr.ndim == 1:
        arr = arr.reshape(())  # TensorProto with scalar shape
    return arr


class _TFNode:
    def __init__(self, node_bytes: bytes):
        msg = pw.decode(node_bytes)
        self.name = pw.as_string(msg.get(1, [b""])[0])
        self.op = pw.as_string(msg.get(2, [b""])[0])
        self.inputs = [pw.as_string(v) for v in msg.get(3, [])]
        self.attr: Dict[str, dict] = {}
        for entry in msg.get(5, []):
            em = pw.decode(entry)
            key = pw.as_string(em.get(1, [b""])[0])
            self.attr[key] = pw.decode(em[2][0]) if 2 in em else {}

    def attr_ints(self, key: str) -> List[int]:
        a = self.attr.get(key, {})
        if 1 not in a:
            return []
        lst = pw.decode(a[1][0])
        return [pw.as_signed(v) for v in pw.repeated_varints(lst.get(3, []))]

    def attr_s(self, key: str) -> Optional[str]:
        a = self.attr.get(key, {})
        return pw.as_string(a[2][0]) if 2 in a else None

    def attr_b(self, key: str, default=False) -> bool:
        a = self.attr.get(key, {})
        return bool(a[5][0]) if 5 in a else default

    def attr_func(self, key: str) -> Optional[str]:
        """AttrValue.func (NameAttrList, field 10) -> function name."""
        a = self.attr.get(key, {})
        if 10 not in a:
            return None
        nal = pw.decode(a[10][0])
        return pw.as_string(nal.get(1, [b""])[0])

    def attr_types(self, key: str) -> List[type]:
        """AttrValue.list.type (repeated DataType, ListValue field 6)."""
        a = self.attr.get(key, {})
        if 1 not in a:
            return []
        lst = pw.decode(a[1][0])
        return [_DT.get(int(v), np.float32)
                for v in pw.repeated_varints(lst.get(6, []))]

    def attr_shapes(self, key: str) -> List[tuple]:
        """AttrValue.list.shape (repeated TensorShapeProto, ListValue field 7)."""
        a = self.attr.get(key, {})
        if 1 not in a:
            return []
        lst = pw.decode(a[1][0])
        shapes = []
        for sb in lst.get(7, []):
            sm = pw.decode(sb)
            shapes.append(tuple(
                pw.as_signed(pw.decode(d).get(1, [0])[0]) for d in sm.get(2, [])))
        return shapes

    def attr_f(self, key: str, default: float = 0.0) -> float:
        a = self.attr.get(key, {})
        if 4 not in a:
            return default
        v = a[4][0]
        if isinstance(v, bytes):  # protowire yields fixed32 as raw bytes
            return struct.unpack("<f", v)[0]
        return struct.unpack("<f", struct.pack("<I", v))[0]

    def attr_i(self, key: str, default: int = 0) -> int:
        a = self.attr.get(key, {})
        return pw.as_signed(a[3][0]) if 3 in a else default

    def attr_type(self, key: str):
        a = self.attr.get(key, {})
        return _DT.get(a[6][0]) if 6 in a else None

    def attr_tensor(self) -> Optional[np.ndarray]:
        a = self.attr.get("value", {})
        return _parse_tensor(a[8][0]) if 8 in a else None


def parse_graphdef(data: bytes) -> List[_TFNode]:
    return [_TFNode(nb) for nb in pw.decode(data).get(1, [])]


class _TFFunction:
    """FunctionDef (tensorflow/core/framework/function.proto): signature
    OpDef (1), node_def (3), ret map (4). Inside a function body, input refs
    use ``node:out_arg:idx`` / ``arg_name`` syntax."""

    def __init__(self, data: bytes):
        msg = pw.decode(data)
        sig = pw.decode(msg[1][0])
        self.name = pw.as_string(sig.get(1, [b""])[0])
        self.input_args = [pw.as_string(pw.decode(a).get(1, [b""])[0])
                           for a in sig.get(2, [])]
        self.output_args = [pw.as_string(pw.decode(a).get(1, [b""])[0])
                            for a in sig.get(3, [])]
        self.nodes = [_TFNode(nb) for nb in msg.get(3, [])]
        self.ret: Dict[str, str] = {}
        for e in msg.get(4, []):
            em = pw.decode(e)
            self.ret[pw.as_string(em[1][0])] = pw.as_string(em[2][0])


def parse_function_library(data: bytes) -> Dict[str, _TFFunction]:
    """GraphDef.library (field 2) -> {name: _TFFunction}."""
    fns: Dict[str, _TFFunction] = {}
    for lib in pw.decode(data).get(2, []):
        lm = pw.decode(lib)
        for fb in lm.get(1, []):
            fn = _TFFunction(fb)
            fns[fn.name] = fn
    return fns


def _clean(name: str) -> str:
    name = name.lstrip("^")
    return name.split(":")[0]


# ------------------------------------------------------ NHWC math modules
class _Fn(Module):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        from bigdl_tpu.utils.table import Table

        if isinstance(x, Table):
            return self._fn(*list(x))
        return self._fn(x)


class _ConstBind(Module):
    """Wrap a multi-arg module, baking const operands in at fixed positions
    (functional ops like While take consts as loop vars; they can't fold
    into the function body because position matters)."""

    def __init__(self, inner: Module, consts: dict, n_total: int):
        super().__init__()
        self.inner = inner
        self._consts = consts
        self._n_total = n_total

    def forward(self, input):
        from bigdl_tpu.utils.table import Table

        dyn = list(input) if isinstance(input, Table) else [input]
        full, di = [], 0
        for pos in range(self._n_total):
            if pos in self._consts:
                full.append(self._consts[pos])
            else:
                full.append(dyn[di])
                di += 1
        return self.inner.forward(Table(*full) if len(full) > 1 else full[0])


class _Conv2D(Module):
    def __init__(self, w_hwio, strides, padding, depthwise=False):
        super().__init__()
        self.register_parameter("weight", jnp.asarray(w_hwio))
        self.strides = strides
        self.padding = padding
        self.depthwise = depthwise

    def forward(self, x):
        w = self.weight
        groups = 1
        if self.depthwise:
            h, wd, c, m = w.shape
            w = w.reshape(h, wd, 1, c * m)
            groups = c
        return lax.conv_general_dilated(
            x, w, window_strides=tuple(self.strides[1:3]),
            padding=self.padding, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class _Pool(Module):
    def __init__(self, ksize, strides, padding, kind):
        super().__init__()
        self.ksize, self.strides, self.pad, self.kind = ksize, strides, padding, kind

    def forward(self, x):
        k = tuple(self.ksize)
        s = tuple(self.strides)
        if self.kind == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, k, s, self.pad)
        summed = lax.reduce_window(x, 0.0, lax.add, k, s, self.pad)
        if self.pad == "VALID":
            return summed / np.prod(self.ksize)
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, k, s, self.pad)
        return summed / counts


class _MatMul(Module):
    def __init__(self, w=None, transpose_a=False, transpose_b=False):
        super().__init__()
        if w is not None:
            self.register_parameter("weight", jnp.asarray(w))
        self.has_w = w is not None
        self.ta, self.tb = transpose_a, transpose_b

    def forward(self, input):
        if self.has_w:
            a, b = input, self.weight
        else:
            a, b = input[1], input[2]
        if self.ta:
            a = a.T
        if self.tb:
            b = b.T
        return a @ b


class _BiasAdd(Module):
    def __init__(self, b):
        super().__init__()
        self.register_parameter("bias", jnp.asarray(b))

    def forward(self, x):
        return x + self.bias


class TensorflowLoader:
    """≙ TensorflowLoader.load (utils/tf/TensorflowLoader.scala:55)."""

    def __init__(self, graph_pb_path: str):
        with open(graph_pb_path, "rb") as f:
            data = f.read()
        self.nodes = {n.name: n for n in parse_graphdef(data)}
        self.functions = parse_function_library(data)
        self._fn_models: Dict[str, object] = {}
        # unfrozen graphs: VariableV2 initial values from Assign(var, Const)
        # initializers (≙ Session.scala's variable extraction)
        self._var_init_refs: Dict[str, str] = {}
        for nd in self.nodes.values():
            # ref variables (VariableV2+Assign) and resource variables
            # (VarHandleOp + AssignVariableOp + ReadVariableOp)
            if nd.op in ("Assign", "AssignVariableOp") and len(nd.inputs) >= 2:
                self._var_init_refs.setdefault(_clean(nd.inputs[0]),
                                               nd.inputs[1])
        self.variables: Dict[str, object] = {}  # name -> Variable module

    def _function_model(self, fname: str):
        """Build (once) an nn.Graph executing the named FunctionDef — used
        as the cond/body of While and the branches of If (≙ the reference
        executing loop-frame subgraphs via Scheduler; here the subgraph is a
        plain module traced into lax control flow)."""
        if fname not in self._fn_models:
            fdef = self.functions[fname]
            sub = TensorflowLoader.__new__(TensorflowLoader)
            sub.nodes = {n.name: n for n in fdef.nodes}
            sub.functions = self.functions
            sub._fn_models = self._fn_models
            outs = [fdef.ret.get(o, o) for o in fdef.output_args]
            if not fdef.input_args:
                # zero-arg branch (e.g. `lambda: tf.constant(c)`): outputs
                # must be const-only; return a plain callable
                consts = {nd.name: nd.attr_tensor() for nd in fdef.nodes
                          if nd.op == "Const"}

                def c_of(ref):
                    b = _clean(ref)
                    if b in consts:
                        return consts[b]
                    nd = sub.nodes.get(b)
                    if nd is not None and nd.op == "Identity":
                        return c_of(nd.inputs[0])
                    raise ValueError(
                        f"zero-arg function {fname!r}: output {ref!r} is "
                        "not constant")

                vals = [jnp.asarray(c_of(o)) for o in outs]
                from bigdl_tpu.utils.table import Table as _T

                self._fn_models[fname] = (
                    lambda *a, vals=tuple(vals):
                    vals[0] if len(vals) == 1 else _T(*vals))
            else:
                self._fn_models[fname] = sub.load(list(fdef.input_args), outs,
                                                  allow_unused_inputs=True)
        return self._fn_models[fname]

    def load(self, inputs: List[str], outputs: List[str],
             allow_unused_inputs: bool = False):
        consts: Dict[str, np.ndarray] = {}
        for n in self.nodes.values():
            if n.op == "Const":
                consts[n.name] = n.attr_tensor()

        def const_of(name: str) -> Optional[np.ndarray]:
            name = _clean(name)
            if name in consts:
                return consts[name]
            n = self.nodes.get(name)
            if n is not None and n.op == "Identity":
                return const_of(n.inputs[0])
            return None

        graph_nodes: Dict[str, nn.Node] = {}
        multi_bases: Dict[str, nn.Node] = {}
        tf1_frames: Dict[str, tuple] = {}
        input_nodes = []
        for name in inputs:
            node = nn.Input()
            graph_nodes[_clean(name)] = node
            input_nodes.append(node)

        def build(ref: str) -> nn.Node:
            base = _clean(ref)
            body = ref.lstrip("^")
            # GraphDef refs are "node[:idx]"; FunctionDef bodies use
            # "node:out_arg[:idx]" — flat index = arg offset + idx
            parts = body.split(":")
            if len(parts) >= 3:
                idx = int(parts[-1])
                prod = self.nodes.get(parts[0])
                if prod is not None:
                    idx += _OUT_ARG_OFFSET.get(prod.op, {}).get(parts[1], 0)
            elif len(parts) == 2 and parts[1].isdigit():
                idx = int(parts[1])
            else:
                idx = 0
            if base in graph_nodes:       # single-output / graph input
                return graph_nodes[base]
            key = f"{base}:{idx}"
            if key in graph_nodes:
                return graph_nodes[key]
            n = self.nodes[base]
            var_base = None
            if n.op == "VariableV2" and base in self._var_init_refs:
                var_base = base
            elif n.op == "ReadVariableOp":
                handle = _clean(n.inputs[0])
                if handle in self._var_init_refs:
                    var_base = handle
            if var_base is not None:
                from bigdl_tpu.nn.tf_ops import Variable

                if var_base in self.variables:
                    var = self.variables[var_base]
                else:
                    init = const_of(self._var_init_refs[var_base])
                    if init is None:
                        raise ValueError(
                            f"variable {var_base!r}: initializer is not a "
                            "constant; freeze the graph or init from consts")
                    var = Variable(jnp.asarray(init))
                    var.set_name(var_base)
                    self.variables[var_base] = var
                node = nn.Node(var).inputs(input_nodes[0])
                graph_nodes[base] = node
                return node
            if n.op == "Const" and input_nodes:
                # a Const used structurally (e.g. an If branch returning a
                # constant): emit a literal node anchored on the first input
                c = const_of(base)
                cval = (np.asarray(c) if np.asarray(c).dtype == object
                        else jnp.asarray(c))
                node = (_Fn(lambda *_a, c=cval: c).set_name(base)
                        .inputs(input_nodes[0]))
                graph_nodes[base] = node
                return node
            if n.op == "Exit":
                # TF1 while frame: reconstruct once, select this exit's var
                wl_node, exit_of = self._tf1_while(n, build, const_of,
                                                   tf1_frames)
                node = (_Fn(lambda *xs, i=exit_of[base]: xs[i])
                        .set_name(base).inputs(wl_node))
                graph_nodes[base] = node
                return node
            if n.op in _MULTI_OUTPUT_OPS or self._n_outputs(n) > 1:
                # node emits a Table; each consumed :idx gets a selector
                if base not in multi_bases:
                    multi_bases[base] = self._convert(n, build, const_of)
                node = (_Fn(lambda *xs, i=idx: xs[i])
                        .set_name(f"{n.name}_out{idx}")
                        .inputs(multi_bases[base]))
                graph_nodes[key] = node
            else:
                node = self._convert(n, build, const_of)
                graph_nodes[base] = node
            return node

        output_nodes = [build(o) for o in outputs]
        model = nn.Graph(input_nodes, output_nodes,
                         allow_unused_inputs=allow_unused_inputs)
        return model

    def _n_outputs(self, n: _TFNode) -> int:
        """Output arity for functional ops (loop vars / branch results)."""
        if n.op in ("While", "StatelessWhile"):
            return len([i for i in n.inputs if not i.startswith("^")])
        if n.op in ("If", "StatelessIf"):
            f = n.attr_func("then_branch")
            return len(self.functions[f].output_args) if f in self.functions else 1
        if n.op in ("PartitionedCall", "StatefulPartitionedCall"):
            f = n.attr_func("f")
            return len(self.functions[f].output_args) if f in self.functions else 1
        if n.op in ("ParseExample", "ParseExampleV2"):
            return len(n.attr_types("Tdense"))
        if n.op in ("Switch", "Merge"):
            return 2
        return 1

    # ---------------- TF1 raw control flow (lowered Switch/Merge frames)
    @staticmethod
    def _ref_idx(ref: str) -> int:
        parts = ref.lstrip("^").split(":")
        return int(parts[-1]) if len(parts) > 1 and parts[-1].isdigit() else 0

    def _trace_switch(self, ref: str, _depth=0):
        """Walk ancestors from ``ref`` to the gating Switch; returns
        (switch_node, output_index_used) or None.

        Nested conds: an intervening Merge means an inner cond already
        resolved on that path — it is skipped by continuing from its own
        gating Switch's *data* input (the value that entered the inner
        cond), so the outer Merge finds the outer Switch. Memoized so
        diamond fan-in stays linear."""
        memo = getattr(self, "_trace_memo", None)
        if memo is None:
            memo = self._trace_memo = {}
        if ref in memo:
            return memo[ref]
        if _depth > 500:
            return None
        base = _clean(ref)
        nd = self.nodes.get(base)
        found = None
        if nd is not None:
            if nd.op == "Switch":
                found = (nd, self._ref_idx(ref))
            elif nd.op == "Merge":
                inner = self._trace_switch(nd.inputs[0], _depth + 1)
                if inner is not None:
                    found = self._trace_switch(inner[0].inputs[0], _depth + 1)
            else:
                for i in nd.inputs:
                    if i.startswith("^"):
                        continue
                    found = self._trace_switch(i, _depth + 1)
                    if found:
                        break
        memo[ref] = found
        return found

    def _branch_side(self, ref: str) -> bool:
        """True if ``ref`` flows from a Switch's true (:1) output."""
        found = self._trace_switch(ref)
        return bool(found and found[1] == 1)

    def _switch_pred(self, ref: str):
        found = self._trace_switch(ref)
        return found[0].inputs[1] if found else None

    @staticmethod
    def _bind_consts(module: Module, refs: List[str], const_of):
        """Bake const operands of a multi-arg functional module in place;
        returns (module, dynamic_refs) (shared by wire_call + _tf1_while)."""
        consts, dyn_refs = {}, []
        for pos, ref in enumerate(refs):
            c = const_of(ref)
            if c is not None:
                consts[pos] = (jnp.asarray(c) if np.asarray(c).dtype != object
                               else np.asarray(c))
            else:
                dyn_refs.append(ref)
        if consts:
            module = _ConstBind(module, consts, len(refs))
        return module, dyn_refs

    def _consumers(self):
        if not hasattr(self, "_consumers_idx"):
            idx: Dict[str, list] = {}
            for nd in self.nodes.values():
                for i in nd.inputs:
                    idx.setdefault(_clean(i), []).append(nd)
            self._consumers_idx = idx
        return self._consumers_idx

    def _subgraph(self, input_names: List[str], output_refs: List[str]):
        """Sub-model over this graph's nodes with the given names seeded as
        placeholders (used for TF1 loop-frame cond/body extraction)."""
        sub = TensorflowLoader.__new__(TensorflowLoader)
        sub.nodes = self.nodes
        sub.functions = self.functions
        sub._fn_models = self._fn_models
        return sub.load(input_names, output_refs, allow_unused_inputs=True)

    def _tf1_while(self, exit_node: _TFNode, build, const_of, frames: dict):
        """Reconstruct a TF1 while frame (Enter/Merge/Switch/LoopCond/
        NextIteration/Exit — the graph the reference walks with
        Scheduler/FrameManager, nn/Scheduler.scala:36) into ONE structured
        WhileLoop lowered to lax.while_loop.

        Loop vars are the frame's Merge nodes; loop invariants are Enter
        nodes without a Merge consumer, appended as extra carried vars."""
        from bigdl_tpu.nn.tf_ops import WhileLoop

        switch = self.nodes[_clean(exit_node.inputs[0])]
        merge0 = self.nodes[_clean(switch.inputs[0])]
        enter0 = self.nodes[_clean(merge0.inputs[0])]
        frame = enter0.attr_s("frame_name") or ""
        if frame in frames:
            return frames[frame]

        consumers = self._consumers()
        enters = sorted((nd for nd in self.nodes.values()
                         if nd.op == "Enter"
                         and (nd.attr_s("frame_name") or "") == frame),
                        key=lambda e: e.name)
        merges, inv_enters = [], []
        for e in enters:
            ms = [c for c in consumers.get(e.name, []) if c.op == "Merge"]
            (merges.append(ms[0]) if ms else inv_enters.append(e))
        merges = sorted(set(merges), key=lambda m: m.name)

        switches, exit_of = [], {}
        loopcond_ref = None
        for m in merges:
            sw = [c for c in consumers.get(m.name, []) if c.op == "Switch"]
            if not sw:
                raise ValueError(f"while frame {frame!r}: loop var "
                                 f"{m.name!r} has no Switch")
            switches.append(sw[0])
            loopcond_ref = sw[0].inputs[1]
            for c in consumers.get(sw[0].name, []):
                if c.op == "Exit":
                    exit_of[c.name] = len(switches) - 1
        loopcond = self.nodes[_clean(loopcond_ref)]

        var_seeds = [m.name for m in merges] + [e.name for e in inv_enters]
        cond_model = self._subgraph(var_seeds, [loopcond.inputs[0]])
        body_seeds = ([sw.name for sw in switches]
                      + [e.name for e in inv_enters])
        nextit_refs = []
        for m in merges:
            ni = self.nodes[_clean(m.inputs[1])]
            if ni.op != "NextIteration":
                raise ValueError(f"while frame {frame!r}: merge {m.name!r} "
                                 f"second input is {ni.op}, not NextIteration")
            nextit_refs.append(ni.inputs[0])
        body_model = self._subgraph(
            body_seeds, nextit_refs + [e.name for e in inv_enters])

        # outer wiring: initial values enter through each var's Enter
        outer_refs = ([self.nodes[_clean(m.inputs[0])].inputs[0] for m in merges]
                      + [e.inputs[0] for e in inv_enters])
        module, dyn_refs = self._bind_consts(
            WhileLoop(cond_model, body_model), outer_refs, const_of)
        node = module.set_name(f"while_frame/{frame}").inputs(
            *[build(r) for r in dyn_refs])
        frames[frame] = (node, exit_of)
        return frames[frame]

    def _convert(self, n: _TFNode, build, const_of) -> nn.Node:
        op = n.op
        data_inputs = [i for i in n.inputs if not i.startswith("^")]

        def prev(i=0):
            return build(data_inputs[i])

        def unary(fn):
            return _Fn(fn).set_name(n.name).inputs(prev(0))

        def binop(fn):
            """Binary op folding a const operand on either side."""
            c0 = const_of(data_inputs[0])
            c1 = const_of(data_inputs[1])
            if c1 is not None:
                return _Fn(lambda x, c=jnp.asarray(c1): fn(x, c)
                           ).set_name(n.name).inputs(prev(0))
            if c0 is not None:
                return _Fn(lambda x, c=jnp.asarray(c0): fn(c, x)
                           ).set_name(n.name).inputs(prev(1))
            return _Fn(fn).set_name(n.name).inputs(prev(0), prev(1))

        if op in ("Identity", "StopGradient", "CheckNumerics"):
            return prev()
        if op == "Cast":
            dst = n.attr_type("DstT")
            if dst is None:
                return prev()
            return unary(lambda x, d=dst: jnp.asarray(x).astype(d))
        if op == "Placeholder":
            raise ValueError(
                f"placeholder {n.name!r} reached but not listed in inputs")
        if op == "Const":
            raise ValueError(
                f"const {n.name!r} must fold into a consumer; unsupported use")
        if op == "MatMul":
            w = const_of(data_inputs[1])
            m = _MatMul(w, n.attr_b("transpose_a"), n.attr_b("transpose_b"))
            m.set_name(n.name)
            if w is None:  # dynamic rhs (e.g. an imported Variable)
                return m.inputs(prev(0), prev(1))
            return m.inputs(prev(0))
        if op in ("BiasAdd", "BiasAddV1") or (
                op in ("Add", "AddV2")
                and const_of(data_inputs[1]) is not None):
            return _BiasAdd(const_of(data_inputs[1])).set_name(n.name).inputs(prev(0))
        if op in ("Add", "AddV2"):
            return nn.CAddTable().set_name(n.name).inputs(prev(0), prev(1))
        if op == "Conv2D":
            w = const_of(data_inputs[1])
            return _Conv2D(w, n.attr_ints("strides"), n.attr_s("padding")
                           ).set_name(n.name).inputs(prev(0))
        if op == "DepthwiseConv2dNative":
            w = const_of(data_inputs[1])
            return _Conv2D(w, n.attr_ints("strides"), n.attr_s("padding"),
                           depthwise=True).set_name(n.name).inputs(prev(0))
        if op == "MaxPool":
            return _Pool(n.attr_ints("ksize"), n.attr_ints("strides"),
                         n.attr_s("padding"), "max").set_name(n.name).inputs(prev(0))
        if op == "AvgPool":
            return _Pool(n.attr_ints("ksize"), n.attr_ints("strides"),
                         n.attr_s("padding"), "avg").set_name(n.name).inputs(prev(0))
        if op == "Relu":
            return nn.ReLU().set_name(n.name).inputs(prev(0))
        if op == "Relu6":
            return nn.ReLU6().set_name(n.name).inputs(prev(0))
        if op == "Tanh":
            return nn.Tanh().set_name(n.name).inputs(prev(0))
        if op == "Sigmoid":
            return nn.Sigmoid().set_name(n.name).inputs(prev(0))
        if op == "Softmax":
            return nn.SoftMax().set_name(n.name).inputs(prev(0))
        if op == "Reshape":
            shape = const_of(data_inputs[1])
            if shape is None:
                # computed target shape (e.g. TF2's SMCE flatten/unflatten):
                # resolved from the runtime shape tensor — eager-safe, and
                # trace-safe whenever the producing ops fold to constants
                def dyn_reshape(x, s):
                    t = [int(v) for v in np.asarray(s).reshape(-1)]
                    known = int(np.prod([d for d in t if d != -1])) or 1
                    return x.reshape(tuple(
                        int(x.size // known) if d == -1 else d for d in t))

                return (_Fn(dyn_reshape).set_name(n.name)
                        .inputs(prev(0), prev(1)))
            tgt = tuple(int(s) for s in np.asarray(shape).reshape(-1))

            def reshape(x, t=tgt):
                known = int(np.prod([d for d in t if d != -1])) or 1
                return x.reshape(tuple(
                    int(x.size // known) if d == -1 else d for d in t))

            return _Fn(reshape).set_name(n.name).inputs(prev(0))
        if op == "Squeeze":
            dims = n.attr_ints("squeeze_dims")
            return _Fn(lambda x, d=tuple(dims): jnp.squeeze(x, axis=d or None)
                       ).set_name(n.name).inputs(prev(0))
        if op == "Mean":
            axes = const_of(data_inputs[1])
            keep = n.attr_b("keep_dims")
            ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
            return _Fn(lambda x, a=ax, k=keep: jnp.mean(x, axis=a, keepdims=k)
                       ).set_name(n.name).inputs(prev(0))
        if op == "SegmentSum":
            ids_c = const_of(data_inputs[1])
            if ids_c is not None:  # fold num_segments at import time (jit-safe)
                num = int(np.asarray(ids_c).reshape(-1)[-1]) + 1
                return unary(lambda x, i=jnp.asarray(ids_c), m=num:
                             jax.ops.segment_sum(x, i, m))

            def segsum(x, ids):
                if isinstance(ids, jax.core.Tracer):
                    raise ValueError(
                        "SegmentSum with non-constant segment ids cannot run "
                        "under jit (num_segments would be data-dependent); "
                        "run the imported graph eagerly or freeze the ids")
                ids = jnp.asarray(ids)
                num = int(np.asarray(ids)[-1]) + 1  # ids sorted, TF contract
                return jax.ops.segment_sum(jnp.asarray(x), ids, num)

            return _Fn(segsum).set_name(n.name).inputs(prev(0), prev(1))
        if op in ("InTopK", "InTopKV2"):
            if op == "InTopKV2":  # k arrives as a const input, not an attr
                k = int(np.asarray(const_of(data_inputs[2])).reshape(()))
            else:
                k = n.attr_i("k", 1)

            def intopk(pred, tgt, k=k):
                thresh = jnp.sort(pred, axis=-1)[..., -k]
                return jnp.take_along_axis(
                    pred, jnp.asarray(tgt)[:, None].astype(jnp.int32),
                    axis=-1)[:, 0] >= thresh

            return _Fn(intopk).set_name(n.name).inputs(prev(0), prev(1))
        if op == "RandomUniform":
            shape_c = const_of(data_inputs[0])
            shp = tuple(int(v) for v in np.asarray(shape_c).reshape(-1))

            def randu(_x, shp=shp):
                from bigdl_tpu.utils import random as bt_random
                return jax.random.uniform(bt_random.next_key(), shp)

            return _Fn(randu).set_name(n.name).inputs(prev(0))
        if op == "RandomShuffle":
            def shuffle(x):
                from bigdl_tpu.utils import random as bt_random
                return jax.random.permutation(bt_random.next_key(),
                                              jnp.asarray(x), axis=0)

            return unary(shuffle)
        if op == "Dilation2D":
            from bigdl_tpu.nn.ops import Dilation2D as _Dil

            filt = const_of(data_inputs[1])
            if filt is None:
                raise ValueError(
                    f"Dilation2D {n.name!r}: dynamic (non-Const) filters are "
                    "unsupported; freeze the filter into the graph")
            mod = _Dil(strides=n.attr_ints("strides") or (1, 1, 1, 1),
                       rates=n.attr_ints("rates") or (1, 1, 1, 1),
                       padding=n.attr_s("padding") or "SAME")
            return _Fn(lambda x, m=mod, f=jnp.asarray(filt): m([x, f])
                       ).set_name(n.name).inputs(prev(0))
        if op in ("DecodeJpeg", "DecodePng", "DecodeImage", "DecodeGif"):
            channels = n.attr_i("channels", 0)
            # DecodeImage honors a dtype attr (convert_image_dtype semantics)
            want_dtype = n.attr_type("dtype") if op == "DecodeImage" else None

            def _scalar_bytes(x):
                if isinstance(x, (bytes, bytearray)):
                    return bytes(x)
                if isinstance(x, str):
                    return x.encode("latin-1")
                return np.asarray(x, object).reshape(-1)[0]

            def _frame(img, ch):
                # ch == 0 keeps the file's own channel count (TF semantics);
                # palette images expand to RGB like TF does
                if ch == 1:
                    img = img.convert("L")
                elif ch == 4:
                    img = img.convert("RGBA")
                elif ch == 3 or img.mode == "P":
                    img = img.convert("RGB")
                arr = np.asarray(img)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                return arr

            def decode(x, ch=channels):
                import io

                from PIL import Image

                img = Image.open(io.BytesIO(_scalar_bytes(x)))
                is_gif = (img.format or "").upper() == "GIF"
                if op == "DecodeGif" or (op == "DecodeImage" and is_gif):
                    # 4-D (frames, H, W, 3): TF expands animations — GIFs
                    # are rank-4 even with a single frame
                    frames = []
                    for f in range(getattr(img, "n_frames", 1)):
                        img.seek(f)
                        frames.append(np.asarray(img.convert("RGB")))
                    arr = np.stack(frames)
                else:
                    arr = _frame(img, ch)
                # DecodeImage applies convert_image_dtype semantics
                if want_dtype is not None and arr.dtype != want_dtype:
                    src_max = np.iinfo(arr.dtype).max
                    if np.issubdtype(want_dtype, np.floating):
                        arr = arr.astype(np.float32) / src_max
                    elif np.issubdtype(want_dtype, np.integer):
                        dst_max = np.iinfo(want_dtype).max
                        arr = (arr.astype(np.int64)
                               * (dst_max // src_max)).astype(want_dtype)
                return jnp.asarray(arr)

            return unary(decode)
        if op == "Pad":
            pads = const_of(data_inputs[1])
            p = tuple((int(a), int(b)) for a, b in np.asarray(pads))
            return _Fn(lambda x, pp=p: jnp.pad(x, pp)).set_name(n.name).inputs(prev(0))
        if op == "ConcatV2":
            axis = int(np.asarray(const_of(data_inputs[-1])).reshape(())[()])
            prevs = [build(i) for i in data_inputs[:-1]]
            return _Fn(lambda *xs, a=axis: jnp.concatenate(xs, axis=a)
                       ).set_name(n.name).inputs(*prevs)

        # ----- elementwise unary (utils/tf/loaders/{Neg,Rsqrt,Sqrt,...}.scala)
        _UNARY = {
            "Neg": jnp.negative, "Rsqrt": lambda x: 1.0 / jnp.sqrt(x),
            "Sqrt": jnp.sqrt, "Square": jnp.square, "Exp": jnp.exp,
            "Log": jnp.log, "Log1p": jnp.log1p, "Abs": jnp.abs,
            "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
            "Rint": jnp.rint, "Sign": jnp.sign, "Erf": jax.scipy.special.erf,
            "Erfc": jax.scipy.special.erfc, "Reciprocal": lambda x: 1.0 / x,
            "Inv": lambda x: 1.0 / x,
            "Softplus": jax.nn.softplus, "Softsign": jax.nn.soft_sign,
            "Elu": jax.nn.elu, "Selu": jax.nn.selu,
            "LogSoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
            "Tanh": jnp.tanh,
        }
        if op in _UNARY:
            return unary(_UNARY[op])
        if op == "LeakyRelu":
            alpha = n.attr_f("alpha", 0.2)
            return unary(lambda x, a=alpha: jnp.where(x > 0, x, a * x))

        # ----- elementwise binary (Sub/Mul/RealDiv/... loaders)
        _BINARY = {
            "Sub": jnp.subtract, "Mul": jnp.multiply, "RealDiv": jnp.divide,
            "Div": jnp.divide, "Maximum": jnp.maximum, "Minimum": jnp.minimum,
            "Pow": jnp.power, "SquaredDifference": lambda a, b: (a - b) ** 2,
            "FloorDiv": jnp.floor_divide, "FloorMod": jnp.mod,
            "Greater": lambda a, b: a > b, "GreaterEqual": lambda a, b: a >= b,
            "Less": lambda a, b: a < b, "LessEqual": lambda a, b: a <= b,
            "Equal": lambda a, b: a == b, "NotEqual": lambda a, b: a != b,
            "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
            "TruncateDiv": lambda a, b: jnp.trunc(a / b).astype(jnp.asarray(a).dtype),
        }
        if op in _BINARY:
            return binop(_BINARY[op])
        if op == "AddN":
            prevs = [build(i) for i in data_inputs]
            return _Fn(lambda *xs: sum(xs[1:], xs[0])
                       ).set_name(n.name).inputs(*prevs)
        if op == "LogicalNot":
            return unary(jnp.logical_not)

        # ----- batch norm (utils/tf/loaders/FusedBatchNorm*.scala)
        if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            scale = jnp.asarray(const_of(data_inputs[1]))
            offset = jnp.asarray(const_of(data_inputs[2]))
            mean = jnp.asarray(const_of(data_inputs[3]))
            var = jnp.asarray(const_of(data_inputs[4]))
            eps = n.attr_f("epsilon", 1e-4)
            inv = scale / jnp.sqrt(var + eps)

            def bn(x, inv=inv, off=offset, mu=mean):
                return x * inv + (off - mu * inv)

            return unary(bn)
        if op == "LRN":
            radius = n.attr_i("depth_radius", 5)
            bias = n.attr_f("bias", 1.0)
            alpha = n.attr_f("alpha", 1.0)
            beta = n.attr_f("beta", 0.5)

            def lrn(x, r=radius, b=bias, a=alpha, be=beta):
                sq = jnp.square(x)
                # sum over the channel window [c-r, c+r] (NHWC)
                pads = [(0, 0)] * (x.ndim - 1) + [(r, r)]
                padded = jnp.pad(sq, pads)
                win = sum(padded[..., i:i + x.shape[-1]] for i in range(2 * r + 1))
                return x / jnp.power(b + a * win, be)

            return unary(lrn)

        # ----- shape/layout ops
        if op == "Transpose":
            perm = tuple(int(p) for p in np.asarray(const_of(data_inputs[1])).reshape(-1))
            return unary(lambda x, pm=perm: jnp.transpose(x, pm))
        if op == "ExpandDims":
            dim = int(np.asarray(const_of(data_inputs[1])).reshape(())[()])
            return unary(lambda x, d=dim: jnp.expand_dims(x, d))
        if op == "Pack":
            axis = n.attr_i("axis", 0)
            prevs = [build(i) for i in data_inputs]
            return _Fn(lambda *xs, a=axis: jnp.stack(xs, axis=a)
                       ).set_name(n.name).inputs(*prevs)
        if op == "Tile":
            mult = tuple(int(m) for m in np.asarray(const_of(data_inputs[1])).reshape(-1))
            return unary(lambda x, m=mult: jnp.tile(x, m))
        if op == "StridedSlice":
            begin = np.asarray(const_of(data_inputs[1])).reshape(-1)
            end = np.asarray(const_of(data_inputs[2])).reshape(-1)
            strides = np.asarray(const_of(data_inputs[3])).reshape(-1)
            bm = n.attr_i("begin_mask")
            em = n.attr_i("end_mask")
            sm = n.attr_i("shrink_axis_mask")
            nm = n.attr_i("new_axis_mask")
            elm = n.attr_i("ellipsis_mask")

            def sslice(x, begin=begin, end=end, strides=strides,
                       bm=bm, em=em, sm=sm, nm=nm, elm=elm):
                idx = []
                for d in range(len(begin)):
                    if elm & (1 << d):
                        idx.append(Ellipsis)
                        continue
                    if nm & (1 << d):
                        idx.append(None)  # np.newaxis
                        continue
                    if sm & (1 << d):
                        idx.append(int(begin[d]))
                        continue
                    b = None if bm & (1 << d) else int(begin[d])
                    e = None if em & (1 << d) else int(end[d])
                    idx.append(slice(b, e, int(strides[d])))
                return x[tuple(idx)]

            return unary(sslice)

        # ----- reductions (Max/Min/Sum/Prod loaders; Mean handled above)
        _REDUCE = {"Max": jnp.max, "Min": jnp.min, "Sum": jnp.sum,
                   "Prod": jnp.prod, "All": jnp.all, "Any": jnp.any}
        if op in _REDUCE:
            axes = const_of(data_inputs[1])
            keep = n.attr_b("keep_dims") or n.attr_b("keepdims")
            ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
            return unary(lambda x, a=ax, k=keep, f=_REDUCE[op]:
                         f(x, axis=a, keepdims=k))
        if op == "ArgMax":
            dim = int(np.asarray(const_of(data_inputs[1])).reshape(())[()])
            return unary(lambda x, d=dim: jnp.argmax(x, axis=d))

        # ----- gather/select/matmul family
        if op in ("Gather", "GatherV2"):
            axis = 0
            if op == "GatherV2" and len(data_inputs) > 2:
                axis = int(np.asarray(const_of(data_inputs[2])).reshape(())[()])
            ind = const_of(data_inputs[1])
            if ind is not None:
                return unary(lambda p, i=jnp.asarray(ind).astype(jnp.int32),
                             a=axis: jnp.take(p, i, axis=a))
            par = const_of(data_inputs[0])
            if par is not None:  # const table, computed indices
                return _Fn(lambda i, p=jnp.asarray(par), a=axis:
                           jnp.take(p, i.astype(jnp.int32), axis=a)
                           ).set_name(n.name).inputs(prev(1))
            return _Fn(lambda p, i, a=axis:
                       jnp.take(p, i.astype(jnp.int32), axis=a)
                       ).set_name(n.name).inputs(prev(0), prev(1))
        if op in ("Select", "SelectV2"):
            return _Fn(lambda c, t, e: jnp.where(c.astype(bool), t, e)
                       ).set_name(n.name).inputs(prev(0), prev(1), prev(2))
        if op in ("BatchMatMul", "BatchMatMulV2"):
            adj_x = n.attr_b("adj_x")
            adj_y = n.attr_b("adj_y")

            def bmm(a, b, ax=adj_x, ay=adj_y):
                if ax:
                    a = jnp.swapaxes(a, -1, -2)
                if ay:
                    b = jnp.swapaxes(b, -1, -2)
                return jnp.matmul(a, b)

            c1 = const_of(data_inputs[1])
            if c1 is not None:
                return unary(lambda a, c=jnp.asarray(c1): bmm(a, c))
            return _Fn(bmm).set_name(n.name).inputs(prev(0), prev(1))
        if op == "OneHot":
            depth = int(np.asarray(const_of(data_inputs[1])).reshape(())[()])
            on = float(np.asarray(const_of(data_inputs[2])).reshape(())[()])
            off = float(np.asarray(const_of(data_inputs[3])).reshape(())[()])
            axis = n.attr_i("axis", -1)
            return unary(lambda x, d=depth, o=on, f=off, a=axis:
                         jax.nn.one_hot(x.astype(jnp.int32), d, axis=a) * (o - f) + f)
        if op == "ResizeBilinear":
            size = np.asarray(const_of(data_inputs[1])).reshape(-1)
            align = n.attr_b("align_corners")
            from bigdl_tpu.nn.ops import ResizeBilinearOp

            return (ResizeBilinearOp(int(size[0]), int(size[1]), align)
                    .set_name(n.name).inputs(prev(0)))

        # ----- multi-output ops (emit a Table; load() adds :idx selectors)
        if op == "Split":
            num = n.attr_i("num_split", 1)
            axis = int(np.asarray(const_of(data_inputs[0])).reshape(())[()])
            from bigdl_tpu.utils.table import Table as _T

            return _Fn(lambda x, k=num, a=axis: _T(*jnp.split(x, k, axis=a))
                       ).set_name(n.name).inputs(prev(1))
        if op == "SplitV":
            sizes = tuple(int(s) for s in np.asarray(const_of(data_inputs[1])).reshape(-1))
            axis = int(np.asarray(const_of(data_inputs[2])).reshape(())[()])
            offsets = np.cumsum((0,) + sizes)[:-1]
            from bigdl_tpu.utils.table import Table as _T

            def splitv(x, offs=tuple(offsets), szs=sizes, a=axis):
                return _T(*[lax.dynamic_slice_in_dim(x, int(o), int(s), axis=a)
                            for o, s in zip(offs, szs)])

            return _Fn(splitv).set_name(n.name).inputs(prev(0))
        if op == "Unpack":
            num = n.attr_i("num", 1)
            axis = n.attr_i("axis", 0)
            from bigdl_tpu.utils.table import Table as _T

            return _Fn(lambda x, k=num, a=axis:
                       _T(*[jnp.take(x, i, axis=a) for i in range(k)])
                       ).set_name(n.name).inputs(prev(0))
        if op in ("TopK", "TopKV2"):
            if op == "TopKV2":
                k = int(np.asarray(const_of(data_inputs[1])).reshape(())[()])
            else:
                k = n.attr_i("k", 1)
            from bigdl_tpu.utils.table import Table as _T

            return _Fn(lambda x, kk=k: _T(*jax.lax.top_k(x, kk))
                       ).set_name(n.name).inputs(prev(0))

        # ----- misc math/shape/introspection loaders (utils/tf/loaders/)
        _UNARY2 = {
            "Expm1": jnp.expm1, "IsFinite": jnp.isfinite, "IsNan": jnp.isnan,
            "IsInf": jnp.isinf, "Lgamma": jax.scipy.special.gammaln,
            "Digamma": jax.scipy.special.digamma,
        }
        if op in _UNARY2:
            return unary(_UNARY2[op])
        if op in ("Mod", "TruncateMod"):
            return binop(lambda a, b: jnp.fmod(a, b))
        if op == "ApproximateEqual":
            tol = n.attr_f("tolerance", 1e-5)
            return binop(lambda a, b, t=tol: jnp.abs(a - b) < t)
        if op == "Shape":
            return unary(lambda x: jnp.asarray(jnp.shape(x), jnp.int32))
        if op == "Rank":
            return unary(lambda x: jnp.asarray(jnp.ndim(x), jnp.int32))
        if op == "Fill":
            dims = const_of(data_inputs[0])
            value = const_of(data_inputs[1])
            if dims is not None and value is not None:
                shape = tuple(int(d) for d in np.asarray(dims).reshape(-1))
                return _Fn(lambda x, s=shape, v=np.asarray(value).reshape(()):
                           jnp.full(s, v)).set_name(n.name).inputs(prev(0))
            if dims is not None:
                shape = tuple(int(d) for d in np.asarray(dims).reshape(-1))
                return unary(lambda v, s=shape: jnp.full(s, v.reshape(())))
            raise ValueError(f"Fill {n.name!r}: dynamic dims unsupported")
        if op == "Range":
            vals = [const_of(i) for i in data_inputs]
            if any(v is None for v in vals):
                raise ValueError(f"Range {n.name!r}: dynamic bounds unsupported")
            s, e, d = (np.asarray(v).reshape(()) for v in vals)
            return _Fn(lambda x, arr=jnp.arange(s, e, d): arr
                       ).set_name(n.name).inputs(prev(0))
        if op == "Slice":
            begin = const_of(data_inputs[1])
            size = const_of(data_inputs[2])
            if begin is None or size is None:
                # computed begin/size: resolved from runtime values
                # (eager-safe, like the dynamic Reshape path)
                def dyn_slice(x, bg, sz):
                    bg = [int(v) for v in np.asarray(bg).reshape(-1)]
                    sz = [int(v) for v in np.asarray(sz).reshape(-1)]
                    idx = tuple(slice(b, None if s == -1 else b + s)
                                for b, s in zip(bg, sz))
                    return x[idx]

                return (_Fn(dyn_slice).set_name(n.name)
                        .inputs(prev(0), prev(1), prev(2)))
            b = [int(v) for v in np.asarray(begin).reshape(-1)]
            sz = [int(v) for v in np.asarray(size).reshape(-1)]

            def slc(x, b=tuple(b), sz=tuple(sz)):
                idx = tuple(slice(bb, None if ss == -1 else bb + ss)
                            for bb, ss in zip(b, sz))
                return x[idx]

            return unary(slc)
        if op == "L2Loss":
            return unary(lambda x: jnp.sum(jnp.square(x)) / 2)
        if op == "SoftmaxCrossEntropyWithLogits":
            from bigdl_tpu.utils.table import Table as _T

            def smce(logits, labels):
                logp = jax.nn.log_softmax(logits, axis=-1)
                loss = -jnp.sum(labels * logp, axis=-1)
                grad = jax.nn.softmax(logits, axis=-1) - labels
                return _T(loss, grad)

            return _Fn(smce).set_name(n.name).inputs(prev(0), prev(1))
        if op == "Substr":
            pos = const_of(data_inputs[1])
            ln = const_of(data_inputs[2])
            p0 = int(np.asarray(pos).reshape(()))
            l0 = int(np.asarray(ln).reshape(()))

            def substr(x, p=p0, ln=l0):
                arr = np.asarray(x, object).reshape(-1)
                out = np.asarray([v[p:p + ln] for v in arr], object)
                return out.reshape(np.shape(x))

            return unary(substr)
        if op == "Conv3D":
            w = const_of(data_inputs[1])  # DHWIO
            strides = n.attr_ints("strides")  # NDHWC
            pad = n.attr_s("padding")

            def conv3d(x, w=jnp.asarray(w), s=tuple(strides[1:4]), p=pad):
                return lax.conv_general_dilated(
                    x, w, window_strides=s, padding=p,
                    dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))

            return unary(conv3d)
        if op == "DecodeRaw":
            out_t = n.attr_type("out_type") or np.float32

            def decode_raw(x, dt=np.dtype(out_t)):
                arr = np.asarray(x, object).reshape(-1)
                rows = [np.frombuffer(v, dtype=dt) for v in arr]
                return jnp.asarray(np.stack(rows)) if len(rows) > 1 \
                    else jnp.asarray(rows[0])

            return unary(decode_raw)
        if op == "VariableV2":
            raise ValueError(
                f"VariableV2 {n.name!r}: graph is not frozen — freeze "
                "variables to constants first (convert_variables_to_"
                "constants), matching the reference's frozen-graph contract")

        # ----- functional control flow (≙ nn/tf/ControlOps.scala; lowered to
        # lax.while_loop / lax.cond instead of Switch/Merge scheduling)
        def wire_call(module):
            """Wire a multi-arg functional module, binding const operands
            (loop counters, max_iterations, captured constants) in place."""
            module, dyn_refs = self._bind_consts(module, data_inputs, const_of)
            return module.set_name(n.name).inputs(*[build(r) for r in dyn_refs])

        if op in ("While", "StatelessWhile"):
            from bigdl_tpu.nn.tf_ops import WhileLoop

            cond_m = self._function_model(n.attr_func("cond"))
            body_m = self._function_model(n.attr_func("body"))
            return wire_call(WhileLoop(cond_m, body_m))
        if op in ("If", "StatelessIf"):
            from bigdl_tpu.nn.tf_ops import If

            then_m = self._function_model(n.attr_func("then_branch"))
            else_m = self._function_model(n.attr_func("else_branch"))
            return wire_call(If(then_m, else_m))
        if op in ("PartitionedCall", "StatefulPartitionedCall"):
            return wire_call(self._function_model(n.attr_func("f")))
        if op in ("NoOp", "ControlTrigger"):
            return prev()  # control anchors: identity on data
        if op == "Switch":
            # TF1 cond lowering: both outputs carry the data (pure branches
            # are evaluated unconditionally; Merge selects by the predicate)
            from bigdl_tpu.utils.table import Table as _T

            return (_Fn(lambda d, p: _T(d, d))
                    .set_name(n.name).inputs(prev(0), prev(1)))
        if op == "Merge":
            # TF1 cond Merge: select between branch values by the predicate
            # of the Switch that gates them (≙ MergeOps, ControlOps.scala:86,
            # minus the scheduler: both branches computed, jnp.where selects)
            from bigdl_tpu.utils.table import Table as _T

            side0 = self._branch_side(data_inputs[0])
            pred_ref = self._switch_pred(data_inputs[0]) or \
                self._switch_pred(data_inputs[1])
            if pred_ref is None:
                raise ValueError(
                    f"Merge {n.name!r}: cannot locate gating Switch predicate")
            prevs = [build(data_inputs[0]), build(data_inputs[1]),
                     build(pred_ref)]

            def mg(a, b, p, s0=side0):
                t, f = (a, b) if s0 else (b, a)
                val = jax.tree.map(lambda u, v: jnp.where(p, u, v), t, f)
                return _T(val, jnp.asarray(0, jnp.int32))

            return _Fn(mg).set_name(n.name).inputs(*prevs)

        # ----- tf.Example parsing (≙ nn/tf/ParsingOps.scala ParseExample)
        if op in ("ParseExample", "ParseExampleV2"):
            from bigdl_tpu.nn.tf_ops import ParseExample as _PE
            from bigdl_tpu.utils.table import Table as _T

            tdense = n.attr_types("Tdense")
            shapes = n.attr_shapes("dense_shapes")
            ndense = len(tdense)
            if op == "ParseExampleV2":
                keys = [k for k in np.asarray(const_of(data_inputs[3])).reshape(-1)]
                defaults = [const_of(i) for i in data_inputs[5:5 + ndense]]
            else:
                nsparse = n.attr_i("Nsparse", 0)
                ks = 2 + nsparse
                keys = [const_of(i) for i in data_inputs[ks:ks + ndense]]
                defaults = [const_of(i)
                            for i in data_inputs[ks + ndense:ks + 2 * ndense]]
            pe = _PE(ndense, tdense, shapes)

            def parse(serialized, pe=pe, keys=keys, defaults=defaults):
                return pe.forward(_T(serialized, None, *keys, *defaults))

            return _Fn(parse).set_name(n.name).inputs(prev(0))

        raise ValueError(f"unsupported tf op {op!r} ({n.name})")


def load_tf(graph_pb_path: str, inputs: List[str], outputs: List[str]):
    """≙ Module.loadTF (nn/Module.scala:94)."""
    return TensorflowLoader(graph_pb_path).load(inputs, outputs)
