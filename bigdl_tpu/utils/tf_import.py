"""TensorFlow GraphDef import (inference subset).

Reference: utils/tf/TensorflowLoader.scala:55 + the 159 per-op loaders in
utils/tf/loaders/ — parse a frozen graph.pb, convert nodes to modules,
build a Graph between user-named inputs and outputs. Here the GraphDef is
decoded with utils/protowire against the public tensorflow .proto field
numbers; constants fold into their consumers (weights), and the supported
op set covers frozen feed-forward inference graphs: Placeholder, Const,
Identity, MatMul, BiasAdd, Add/AddV2, Relu, Relu6, Tanh, Sigmoid, Softmax,
Conv2D (NHWC), DepthwiseConv2dNative, MaxPool, AvgPool, Mean, Reshape,
Squeeze, Pad, ConcatV2.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import protowire as pw

# tensorflow dtype enum (subset)
_DT = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 6: np.int8,
       9: np.int64, 10: bool}


def _parse_tensor(tensor_bytes: bytes) -> np.ndarray:
    msg = pw.decode(tensor_bytes)
    dtype = _DT.get(msg.get(1, [1])[0], np.float32)
    shape = []
    if 2 in msg:
        shape_msg = pw.decode(msg[2][0])
        for dim in shape_msg.get(2, []):
            shape.append(pw.as_signed(pw.decode(dim).get(1, [0])[0]))
    if 4 in msg and msg[4][0]:  # tensor_content: raw bytes
        arr = np.frombuffer(msg[4][0], dtype=dtype).copy()
    elif 5 in msg:  # float_val
        vals = []
        for v in msg[5]:
            vals.extend(pw.packed_floats(v) if isinstance(v, bytes)
                        else [struct.unpack("<f", struct.pack("<I", v))[0]])
        arr = np.asarray(vals, np.float32)
    elif 6 in msg:  # int_val
        arr = np.asarray(pw.repeated_varints(msg[6]), np.int32)
    elif 9 in msg:  # int64_val
        arr = np.asarray([pw.as_signed(v) for v in pw.repeated_varints(msg[9])],
                         np.int64)
    else:
        arr = np.zeros(shape or (0,), dtype)
    if shape:
        if arr.size == 1 and int(np.prod(shape)) > 1:
            arr = np.full(shape, arr.reshape(-1)[0])
        arr = arr.reshape(shape)
    return arr


class _TFNode:
    def __init__(self, node_bytes: bytes):
        msg = pw.decode(node_bytes)
        self.name = pw.as_string(msg.get(1, [b""])[0])
        self.op = pw.as_string(msg.get(2, [b""])[0])
        self.inputs = [pw.as_string(v) for v in msg.get(3, [])]
        self.attr: Dict[str, dict] = {}
        for entry in msg.get(5, []):
            em = pw.decode(entry)
            key = pw.as_string(em.get(1, [b""])[0])
            self.attr[key] = pw.decode(em[2][0]) if 2 in em else {}

    def attr_ints(self, key: str) -> List[int]:
        a = self.attr.get(key, {})
        if 1 not in a:
            return []
        lst = pw.decode(a[1][0])
        return [pw.as_signed(v) for v in pw.repeated_varints(lst.get(3, []))]

    def attr_s(self, key: str) -> Optional[str]:
        a = self.attr.get(key, {})
        return pw.as_string(a[2][0]) if 2 in a else None

    def attr_b(self, key: str, default=False) -> bool:
        a = self.attr.get(key, {})
        return bool(a[5][0]) if 5 in a else default

    def attr_tensor(self) -> Optional[np.ndarray]:
        a = self.attr.get("value", {})
        return _parse_tensor(a[8][0]) if 8 in a else None


def parse_graphdef(data: bytes) -> List[_TFNode]:
    return [_TFNode(nb) for nb in pw.decode(data).get(1, [])]


def _clean(name: str) -> str:
    name = name.lstrip("^")
    return name.split(":")[0]


# ------------------------------------------------------ NHWC math modules
class _Fn(Module):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        from bigdl_tpu.utils.table import Table

        if isinstance(x, Table):
            return self._fn(*list(x))
        return self._fn(x)


class _Conv2D(Module):
    def __init__(self, w_hwio, strides, padding, depthwise=False):
        super().__init__()
        self.register_parameter("weight", jnp.asarray(w_hwio))
        self.strides = strides
        self.padding = padding
        self.depthwise = depthwise

    def forward(self, x):
        w = self.weight
        groups = 1
        if self.depthwise:
            h, wd, c, m = w.shape
            w = w.reshape(h, wd, 1, c * m)
            groups = c
        return lax.conv_general_dilated(
            x, w, window_strides=tuple(self.strides[1:3]),
            padding=self.padding, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class _Pool(Module):
    def __init__(self, ksize, strides, padding, kind):
        super().__init__()
        self.ksize, self.strides, self.pad, self.kind = ksize, strides, padding, kind

    def forward(self, x):
        k = tuple(self.ksize)
        s = tuple(self.strides)
        if self.kind == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, k, s, self.pad)
        summed = lax.reduce_window(x, 0.0, lax.add, k, s, self.pad)
        if self.pad == "VALID":
            return summed / np.prod(self.ksize)
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, k, s, self.pad)
        return summed / counts


class _MatMul(Module):
    def __init__(self, w=None, transpose_a=False, transpose_b=False):
        super().__init__()
        if w is not None:
            self.register_parameter("weight", jnp.asarray(w))
        self.has_w = w is not None
        self.ta, self.tb = transpose_a, transpose_b

    def forward(self, input):
        if self.has_w:
            a, b = input, self.weight
        else:
            a, b = input[1], input[2]
        if self.ta:
            a = a.T
        if self.tb:
            b = b.T
        return a @ b


class _BiasAdd(Module):
    def __init__(self, b):
        super().__init__()
        self.register_parameter("bias", jnp.asarray(b))

    def forward(self, x):
        return x + self.bias


class TensorflowLoader:
    """≙ TensorflowLoader.load (utils/tf/TensorflowLoader.scala:55)."""

    def __init__(self, graph_pb_path: str):
        with open(graph_pb_path, "rb") as f:
            self.nodes = {n.name: n for n in parse_graphdef(f.read())}

    def load(self, inputs: List[str], outputs: List[str]):
        consts: Dict[str, np.ndarray] = {}
        for n in self.nodes.values():
            if n.op == "Const":
                consts[n.name] = n.attr_tensor()

        def const_of(name: str) -> Optional[np.ndarray]:
            name = _clean(name)
            if name in consts:
                return consts[name]
            n = self.nodes.get(name)
            if n is not None and n.op == "Identity":
                return const_of(n.inputs[0])
            return None

        graph_nodes: Dict[str, nn.Node] = {}
        input_nodes = []
        for name in inputs:
            node = nn.Input()
            graph_nodes[_clean(name)] = node
            input_nodes.append(node)

        def build(name: str) -> nn.Node:
            name = _clean(name)
            if name in graph_nodes:
                return graph_nodes[name]
            n = self.nodes[name]
            node = self._convert(n, build, const_of)
            graph_nodes[name] = node
            return node

        output_nodes = [build(o) for o in outputs]
        model = nn.Graph(input_nodes, output_nodes)
        return model

    def _convert(self, n: _TFNode, build, const_of) -> nn.Node:
        op = n.op
        data_inputs = [i for i in n.inputs if not i.startswith("^")]

        def prev(i=0):
            return build(data_inputs[i])

        if op in ("Identity", "StopGradient", "Cast", "CheckNumerics"):
            return prev()
        if op == "Placeholder":
            raise ValueError(
                f"placeholder {n.name!r} reached but not listed in inputs")
        if op == "Const":
            raise ValueError(
                f"const {n.name!r} must fold into a consumer; unsupported use")
        if op == "MatMul":
            w = const_of(data_inputs[1])
            m = _MatMul(w, n.attr_b("transpose_a"), n.attr_b("transpose_b"))
            m.set_name(n.name)
            return m.inputs(prev(0))
        if op == "BiasAdd" or (op in ("Add", "AddV2")
                               and const_of(data_inputs[1]) is not None):
            return _BiasAdd(const_of(data_inputs[1])).set_name(n.name).inputs(prev(0))
        if op in ("Add", "AddV2"):
            return nn.CAddTable().set_name(n.name).inputs(prev(0), prev(1))
        if op == "Conv2D":
            w = const_of(data_inputs[1])
            return _Conv2D(w, n.attr_ints("strides"), n.attr_s("padding")
                           ).set_name(n.name).inputs(prev(0))
        if op == "DepthwiseConv2dNative":
            w = const_of(data_inputs[1])
            return _Conv2D(w, n.attr_ints("strides"), n.attr_s("padding"),
                           depthwise=True).set_name(n.name).inputs(prev(0))
        if op == "MaxPool":
            return _Pool(n.attr_ints("ksize"), n.attr_ints("strides"),
                         n.attr_s("padding"), "max").set_name(n.name).inputs(prev(0))
        if op == "AvgPool":
            return _Pool(n.attr_ints("ksize"), n.attr_ints("strides"),
                         n.attr_s("padding"), "avg").set_name(n.name).inputs(prev(0))
        if op == "Relu":
            return nn.ReLU().set_name(n.name).inputs(prev(0))
        if op == "Relu6":
            return nn.ReLU6().set_name(n.name).inputs(prev(0))
        if op == "Tanh":
            return nn.Tanh().set_name(n.name).inputs(prev(0))
        if op == "Sigmoid":
            return nn.Sigmoid().set_name(n.name).inputs(prev(0))
        if op == "Softmax":
            return nn.SoftMax().set_name(n.name).inputs(prev(0))
        if op == "Reshape":
            shape = const_of(data_inputs[1])
            tgt = tuple(int(s) for s in np.asarray(shape).reshape(-1))
            return _Fn(lambda x, t=tgt: x.reshape(
                tuple(x.shape[0] if d == -1 else d for d in t))
            ).set_name(n.name).inputs(prev(0))
        if op == "Squeeze":
            dims = n.attr_ints("squeeze_dims")
            return _Fn(lambda x, d=tuple(dims): jnp.squeeze(x, axis=d or None)
                       ).set_name(n.name).inputs(prev(0))
        if op == "Mean":
            axes = const_of(data_inputs[1])
            keep = n.attr_b("keep_dims")
            ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
            return _Fn(lambda x, a=ax, k=keep: jnp.mean(x, axis=a, keepdims=k)
                       ).set_name(n.name).inputs(prev(0))
        if op == "Pad":
            pads = const_of(data_inputs[1])
            p = tuple((int(a), int(b)) for a, b in np.asarray(pads))
            return _Fn(lambda x, pp=p: jnp.pad(x, pp)).set_name(n.name).inputs(prev(0))
        if op == "ConcatV2":
            axis = int(np.asarray(const_of(data_inputs[-1])).reshape(())[()])
            prevs = [build(i) for i in data_inputs[:-1]]
            return _Fn(lambda *xs, a=axis: jnp.concatenate(xs, axis=a)
                       ).set_name(n.name).inputs(*prevs)
        raise ValueError(f"unsupported tf op {op!r} ({n.name})")


def load_tf(graph_pb_path: str, inputs: List[str], outputs: List[str]):
    """≙ Module.loadTF (nn/Module.scala:94)."""
    return TensorflowLoader(graph_pb_path).load(inputs, outputs)
