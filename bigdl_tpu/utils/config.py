"""Config/flag system — the ``bigdl.*`` property tiers as ``BIGDL_TPU_*``.

Reference (SURVEY.md §5 "Config / flag system"): three tiers of JVM system
properties — ``bigdl.engineType``, ``bigdl.localMode``, ``bigdl.coreNumber``,
``bigdl.check.singleton``, ``bigdl.failure.retryTimes`` /
``bigdl.failure.retryTimeInterval`` (optim/DistriOptimizer.scala:977-978),
``bigdl.Parameter.syncPoolSize/computePoolSize``
(parameters/AllReduceParameter.scala:36,47), ``bigdl.utils.Engine.defaultPoolSize``.

TPU-native mapping: one env-var tier.  A property ``bigdl.failure.retryTimes``
becomes ``BIGDL_TPU_FAILURE_RETRY_TIMES`` (dots → underscores, camelCase →
SNAKE).  ``set_property``/``get_property`` also keep an in-process override
map so tests and embedding apps can configure without touching the
environment (≙ System.setProperty).
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, Optional, TypeVar

T = TypeVar("T")

_overrides: Dict[str, str] = {}

#: Known properties and defaults (the reference's documented set; values are
#: strings exactly as System.getProperty returns them).
DEFAULTS = {
    "bigdl.engineType": "bfloat16",          # ≙ MklBlas/MklDnn → dtype policy
    "bigdl.localMode": "false",
    "bigdl.coreNumber": "",                  # ≙ local device override
    "bigdl.check.singleton": "false",
    "bigdl.failure.retryTimes": "5",         # DistriOptimizer.scala:977
    "bigdl.failure.retryTimeInterval": "120",  # seconds; :978
    "bigdl.Parameter.syncPoolSize": "4",
    "bigdl.Parameter.computePoolSize": "",
    "bigdl.utils.Engine.defaultPoolSize": "",
    "bigdl.log.interval": "1",               # TPU-native: host-sync/log cadence
}


def to_env_name(prop: str) -> str:
    """``bigdl.failure.retryTimes`` → ``BIGDL_TPU_FAILURE_RETRY_TIMES``."""
    body = prop[len("bigdl."):] if prop.startswith("bigdl.") else prop
    body = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", body.replace(".", "_"))
    return "BIGDL_TPU_" + body.upper()


def get_property(prop: str, default: Optional[str] = None) -> Optional[str]:
    """Resolution order: in-process override → env var → DEFAULTS → default."""
    if prop in _overrides:
        return _overrides[prop]
    env = os.environ.get(to_env_name(prop))
    if env is not None:
        return env
    if prop in DEFAULTS and DEFAULTS[prop] != "":
        return DEFAULTS[prop]
    return default


def set_property(prop: str, value) -> None:
    """≙ System.setProperty (in-process tier; wins over env)."""
    _overrides[prop] = str(value)


def clear_property(prop: str) -> None:
    _overrides.pop(prop, None)


def _typed(prop: str, default: T, cast: Callable[[str], T]) -> T:
    raw = get_property(prop)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        return default


def get_int(prop: str, default: int = 0) -> int:
    return _typed(prop, default, int)


def get_float(prop: str, default: float = 0.0) -> float:
    return _typed(prop, default, float)


def get_bool(prop: str, default: bool = False) -> bool:
    return _typed(prop, default, lambda s: s.strip().lower() in ("1", "true", "yes"))
