"""Torch7 .t7 binary serialization: reader + writer.

Reference: utils/TorchFile.scala (read/write of Lua Torch objects —
tensors, storages, tables, nn modules) backing ``Module.loadTorch`` /
``saveTorch`` (nn/Module.scala:64, AbstractModule.scala:565).

Format (binary mode): each object = int32 type tag then payload.
  0 nil | 1 number(double) | 2 string(int32 len + bytes) | 3 table
  4 torch object | 5 boolean | 6/7/8 functions (unsupported here)
Torch objects carry an int32 memo index, a version string ("V 1"), the
class name, then class payload: tensors = ndim/sizes/strides/offset +
storage ref; storages = int64 count + raw elements; nn modules = a table
of fields. ``load_torch`` maps the common torch nn classes onto
bigdl_tpu.nn modules.
"""

from __future__ import annotations

import struct
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_STORAGE_DTYPES = {
    "torch.FloatStorage": (np.float32, 4),
    "torch.DoubleStorage": (np.float64, 8),
    "torch.LongStorage": (np.int64, 8),
    "torch.IntStorage": (np.int32, 4),
    "torch.ByteStorage": (np.uint8, 1),
    "torch.CharStorage": (np.int8, 1),
    "torch.ShortStorage": (np.int16, 2),
}
_TENSOR_CLASSES = {f"torch.{p}Tensor": f"torch.{p}Storage"
                   for p in ("Float", "Double", "Long", "Int", "Byte", "Char", "Short")}


class TorchObject:
    """A non-tensor torch class instance: .torch_class + .fields table."""

    def __init__(self, torch_class: str, fields: dict):
        self.torch_class = torch_class
        self.fields = fields

    def __getitem__(self, k):
        return self.fields.get(k)

    def __repr__(self):
        return f"TorchObject({self.torch_class})"


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.memo: Dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        vals = struct.unpack_from("<" + fmt, self.data, self.pos)
        self.pos += size
        return vals[0] if len(vals) == 1 else vals

    def read_int(self) -> int:
        return self._read("i")

    def read_long(self) -> int:
        return self._read("q")

    def read_string(self) -> str:
        n = self.read_int()
        s = self.data[self.pos:self.pos + n].decode("latin-1")
        self.pos += n
        return s

    def read_object(self):
        t = self.read_int()
        if t == TYPE_NIL:
            return None
        if t == TYPE_NUMBER:
            return self._read("d")
        if t == TYPE_STRING:
            return self.read_string()
        if t == TYPE_BOOLEAN:
            return bool(self.read_int())
        if t == TYPE_TABLE:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            n = self.read_int()
            table: Dict[Any, Any] = {}
            self.memo[idx] = table
            for _ in range(n):
                k = self.read_object()
                v = self.read_object()
                table[k] = v
            return table
        if t == TYPE_TORCH:
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            version = self.read_string()  # "V 1"
            cls = self.read_string() if version.startswith("V") else version
            return self._read_torch_class(idx, cls)
        raise ValueError(f"unsupported t7 type tag {t} at {self.pos}")

    def _read_torch_class(self, idx: int, cls: str):
        if cls in _TENSOR_CLASSES:
            ndim = self.read_int()
            sizes = [self.read_long() for _ in range(ndim)]
            strides = [self.read_long() for _ in range(ndim)]
            offset = self.read_long() - 1  # 1-based
            placeholder = {}
            self.memo[idx] = placeholder
            storage = self.read_object()  # storage np array (or None)
            if storage is None or ndim == 0:
                arr = np.zeros(sizes, np.float32)
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=sizes,
                    strides=[s * storage.itemsize for s in strides]).copy()
            self.memo[idx] = arr
            return arr
        if cls in _STORAGE_DTYPES:
            dtype, itemsize = _STORAGE_DTYPES[cls]
            n = self.read_long()
            arr = np.frombuffer(self.data, dtype, n, self.pos).copy()
            self.pos += n * itemsize
            self.memo[idx] = arr
            return arr
        # generic class: payload is one table of fields
        obj = TorchObject(cls, {})
        self.memo[idx] = obj
        fields = self.read_object()
        obj.fields = fields if isinstance(fields, dict) else {}
        return obj


class _Writer:
    def __init__(self):
        self.out = bytearray()
        self.next_idx = 1

    def _w(self, fmt: str, *vals):
        self.out += struct.pack("<" + fmt, *vals)

    def write_string(self, s: str):
        b = s.encode("latin-1")
        self._w("i", len(b))
        self.out += b

    def write_object(self, obj):
        if obj is None:
            self._w("i", TYPE_NIL)
        elif isinstance(obj, bool):
            self._w("i", TYPE_BOOLEAN)
            self._w("i", int(obj))
        elif isinstance(obj, (int, float)):
            self._w("i", TYPE_NUMBER)
            self._w("d", float(obj))
        elif isinstance(obj, str):
            self._w("i", TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray) or hasattr(obj, "__array__"):
            self._write_tensor(np.asarray(obj))
        elif isinstance(obj, dict):
            self._w("i", TYPE_TABLE)
            self._w("i", self._idx())
            self._w("i", len(obj))
            for k, v in obj.items():
                self.write_object(k)
                self.write_object(v)
        elif isinstance(obj, TorchObject):
            self._w("i", TYPE_TORCH)
            self._w("i", self._idx())
            self.write_string("V 1")
            self.write_string(obj.torch_class)
            self.write_object(obj.fields)
        else:
            raise TypeError(f"cannot write {type(obj)} to t7")

    def _idx(self) -> int:
        i = self.next_idx
        self.next_idx += 1
        return i

    def _write_tensor(self, arr: np.ndarray):
        if arr.dtype == np.float64:
            tcls, scls = "torch.DoubleTensor", "torch.DoubleStorage"
        elif arr.dtype == np.int64:
            tcls, scls = "torch.LongTensor", "torch.LongStorage"
        else:
            arr = arr.astype(np.float32)
            tcls, scls = "torch.FloatTensor", "torch.FloatStorage"
        arr = np.ascontiguousarray(arr)
        self._w("i", TYPE_TORCH)
        self._w("i", self._idx())
        self.write_string("V 1")
        self.write_string(tcls)
        self._w("i", arr.ndim)
        for s in arr.shape:
            self._w("q", s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self._w("q", s)
        self._w("q", 1)  # storage offset (1-based)
        # storage
        self._w("i", TYPE_TORCH)
        self._w("i", self._idx())
        self.write_string("V 1")
        self.write_string(scls)
        self._w("q", arr.size)
        self.out += arr.tobytes()


def load(path: str):
    """Raw t7 read → python objects (np arrays / dicts / TorchObject)."""
    with open(path, "rb") as f:
        return _Reader(f.read()).read_object()


def save(path: str, obj) -> None:
    w = _Writer()
    w.write_object(obj)
    with open(path, "wb") as f:
        f.write(bytes(w.out))


# ------------------------------------------------- torch nn -> bigdl_tpu.nn
def _seq_children(fields: dict):
    mods = fields.get("modules", {})
    return [mods[k] for k in sorted(k for k in mods if isinstance(k, (int, float)))]


def _to_module(obj):
    from bigdl_tpu import nn

    if not isinstance(obj, TorchObject):
        raise TypeError(f"not a torch module: {obj!r}")
    cls = obj.torch_class.split(".")[-1]
    f = obj.fields

    def wb(m, wkey="weight", bkey="bias"):
        if f.get("weight") is not None:
            m._set_param(wkey, jnp.asarray(f["weight"]))
        if f.get("bias") is not None and bkey in m._parameters:
            m._set_param(bkey, jnp.asarray(f["bias"]))
        return m

    if cls == "Sequential":
        s = nn.Sequential()
        for child in _seq_children(f):
            s.add(_to_module(child))
        return s
    if cls in ("Concat",):
        c = nn.Concat(int(f.get("dimension", 2)))
        for child in _seq_children(f):
            c.add(_to_module(child))
        return c
    if cls == "ConcatTable":
        c = nn.ConcatTable()
        for child in _seq_children(f):
            c.add(_to_module(child))
        return c
    if cls == "Linear":
        w = np.asarray(f["weight"])
        m = nn.Linear(w.shape[1], w.shape[0], with_bias=f.get("bias") is not None)
        return wb(m)
    if cls in ("SpatialConvolution", "SpatialConvolutionMM"):
        m = nn.SpatialConvolution(
            int(f["nInputPlane"]), int(f["nOutputPlane"]),
            int(f["kW"]), int(f["kH"]), int(f.get("dW", 1)), int(f.get("dH", 1)),
            int(f.get("padW", 0)), int(f.get("padH", 0)),
            with_bias=f.get("bias") is not None)
        w = np.asarray(f["weight"]).reshape(np.asarray(m.weight).shape)
        m._set_param("weight", jnp.asarray(w))
        if f.get("bias") is not None:
            m._set_param("bias", jnp.asarray(f["bias"]))
        return m
    if cls == "SpatialMaxPooling":
        m = nn.SpatialMaxPooling(int(f["kW"]), int(f["kH"]),
                                 int(f.get("dW", 1)), int(f.get("dH", 1)),
                                 int(f.get("padW", 0)), int(f.get("padH", 0)))
        if f.get("ceil_mode"):
            m.ceil()
        return m
    if cls == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(int(f["kW"]), int(f["kH"]),
                                        int(f.get("dW", 1)), int(f.get("dH", 1)))
    if cls == "SpatialBatchNormalization":
        m = nn.SpatialBatchNormalization(
            int(f.get("nOutput") or len(np.asarray(f["running_mean"]))),
            float(f.get("eps", 1e-5)), float(f.get("momentum", 0.1)),
            affine=f.get("weight") is not None)
        if f.get("running_mean") is not None:
            m._set_buffer("running_mean", jnp.asarray(f["running_mean"]))
        if f.get("running_var") is not None:
            m._set_buffer("running_var", jnp.asarray(f["running_var"]))
        return wb(m)
    if cls == "ReLU":
        return nn.ReLU()
    if cls == "Tanh":
        return nn.Tanh()
    if cls == "Sigmoid":
        return nn.Sigmoid()
    if cls == "SoftMax":
        return nn.SoftMax()
    if cls == "LogSoftMax":
        return nn.LogSoftMax()
    if cls == "Dropout":
        return nn.Dropout(float(f.get("p", 0.5)))
    if cls == "Identity":
        return nn.Identity()
    if cls == "CAddTable":
        return nn.CAddTable()
    if cls == "JoinTable":
        return nn.JoinTable(int(f.get("dimension", 2)))
    if cls == "Reshape":
        size = f.get("size")
        return nn.Reshape(tuple(int(s) for s in np.asarray(size).reshape(-1)))
    if cls == "View":
        size = f.get("size")
        return nn.View(tuple(int(s) for s in np.asarray(size).reshape(-1)))
    raise ValueError(f"unsupported torch module class {obj.torch_class!r}")


def load_torch(path: str):
    """≙ Module.loadTorch (nn/Module.scala:64)."""
    return _to_module(load(path))
