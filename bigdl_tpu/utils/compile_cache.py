"""Persistent XLA compile cache — one policy for every perf/bench tool.

Over the axon tunnel a ResNet-50 or decode-loop compile can eat a
minute-plus of a short hardware window; a prior run (same code, same
shapes) turns it into a cache hit. Policy: ``BIGDL_TPU_COMPILE_CACHE``
overrides; otherwise anchor to the repo checkout (keeps the warmed cache
regardless of cwd — bench.py, tpu_sweep, flash_matrix and the perf CLI
all share one cache); fall back to cwd for installed-package runs.
"""

from __future__ import annotations

import os
import sys


def enable_persistent_cache() -> str | None:
    """Point jax at the shared on-disk compile cache. Returns the cache
    dir, or None (with a stderr note) if the config couldn't be applied."""
    import jax

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    default = (os.path.join(repo_root, ".jax_cache")
               if os.path.exists(os.path.join(repo_root, "bench.py"))
               else os.path.join(os.getcwd(), ".jax_cache"))
    cache_dir = os.environ.get("BIGDL_TPU_COMPILE_CACHE", default)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception as e:
        print(f"[bigdl_tpu] compile cache unavailable: {e}", file=sys.stderr)
        return None
