"""Module save/load — local AND object-store paths.

Reference: utils/File.scala:68-176 (Java-serialization save/load of any
module, transparently local/HDFS/S3). The pickle-based path is the
analog of the reference's ``save``/``Module.load``; the structured
protobuf-style format (``saveModule``/``loadModule``) lives in
bigdl_tpu.utils.serializer. Device arrays are converted to numpy on save
and restored with jnp.asarray on load, so checkpoints are host-portable.

Remote paths: anything with a URL scheme (``gs://``, ``s3://``, ...) is
routed through ``etils.epath`` (already a dependency via orbax) — the
TPU-pod analog of the reference's Hadoop-FS indirection. The
``open_file``/``exists``/``makedirs``/``listdir`` helpers below are the
single IO seam; checkpoint triggers and TrainSummary event writers go
through them, so both can target a bucket directly.
"""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np


def is_remote(path) -> bool:
    """True for URL-style paths (gs://, s3://, ...) that must go through
    epath instead of the local filesystem."""
    return "://" in str(path)


def _epath(path):
    from etils import epath  # ships with orbax; object-store capable

    return epath.Path(path)


def open_file(path, mode: str = "rb"):
    """open() that understands object-store URLs. Append mode on object
    stores degrades to a single streaming write ('ab' -> 'wb'): buckets
    have no append, and every writer here creates fresh files anyway."""
    if is_remote(path):
        return _epath(path).open(mode.replace("ab", "wb"))
    return open(path, mode)


def exists(path) -> bool:
    return _epath(path).exists() if is_remote(path) else os.path.exists(path)


def makedirs(path) -> None:
    if is_remote(path):
        _epath(path).mkdir(parents=True, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def listdir(path):
    if is_remote(path):
        return [p.name for p in _epath(path).iterdir()]
    return os.listdir(path)


def _to_host(module):
    for _, m in module.named_modules():
        for k in list(m._parameters):
            m._parameters[k] = np.asarray(m._parameters[k])
            object.__setattr__(m, k, m._parameters[k])
        for k in list(m._gradients):
            m._gradients[k] = np.asarray(m._gradients[k])
        for k in list(m._buffers):
            m._buffers[k] = np.asarray(m._buffers[k])
            object.__setattr__(m, k, m._buffers[k])


def _to_device(module):
    for _, m in module.named_modules():
        for k in list(m._parameters):
            m._set_param(k, jnp.asarray(m._parameters[k]))
        for k in list(m._gradients):
            m._gradients[k] = jnp.asarray(m._gradients[k])
        for k in list(m._buffers):
            m._set_buffer(k, jnp.asarray(m._buffers[k]))


def save_module(module, path: str, overwrite: bool = False) -> None:
    if exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    for _, m in module.named_modules():
        # drop recorded activations before deepcopy — they may be large or
        # (if a trace misbehaved) tracers that cannot be copied/pickled
        m.output = None
        m.grad_input = None
        m._forward_key = None
    clone = module.clone_module()
    _to_host(clone)
    with open_file(path, "wb") as f:
        pickle.dump(clone, f)


def load_module(path: str):
    with open_file(path, "rb") as f:
        module = pickle.load(f)
    _to_device(module)
    return module


def save(obj, path: str, overwrite: bool = False) -> None:
    """Generic save for optimizer state / tables (≙ File.save)."""
    if exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    import jax

    host = jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, obj)
    with open_file(path, "wb") as f:
        pickle.dump(host, f)


def load(path: str):
    with open_file(path, "rb") as f:
        return pickle.load(f)
