"""Module save/load.

Reference: utils/File.scala:68-176 (Java-serialization save/load of any
module). The pickle-based path is the analog of the reference's
``save``/``Module.load``; the structured protobuf-style format
(``saveModule``/``loadModule``) lives in bigdl_tpu.utils.serializer.
Device arrays are converted to numpy on save and restored with jnp.asarray
on load, so checkpoints are host-portable.
"""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np


def _to_host(module):
    for _, m in module.named_modules():
        for k in list(m._parameters):
            m._parameters[k] = np.asarray(m._parameters[k])
            object.__setattr__(m, k, m._parameters[k])
        for k in list(m._gradients):
            m._gradients[k] = np.asarray(m._gradients[k])
        for k in list(m._buffers):
            m._buffers[k] = np.asarray(m._buffers[k])
            object.__setattr__(m, k, m._buffers[k])


def _to_device(module):
    for _, m in module.named_modules():
        for k in list(m._parameters):
            m._set_param(k, jnp.asarray(m._parameters[k]))
        for k in list(m._gradients):
            m._gradients[k] = jnp.asarray(m._gradients[k])
        for k in list(m._buffers):
            m._set_buffer(k, jnp.asarray(m._buffers[k]))


def save_module(module, path: str, overwrite: bool = False) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    for _, m in module.named_modules():
        # drop recorded activations before deepcopy — they may be large or
        # (if a trace misbehaved) tracers that cannot be copied/pickled
        m.output = None
        m.grad_input = None
        m._forward_key = None
    clone = module.clone_module()
    _to_host(clone)
    with open(path, "wb") as f:
        pickle.dump(clone, f)


def load_module(path: str):
    with open(path, "rb") as f:
        module = pickle.load(f)
    _to_device(module)
    return module


def save(obj, path: str, overwrite: bool = False) -> None:
    """Generic save for optimizer state / tables (≙ File.save)."""
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    import jax

    host = jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, obj)
    with open(path, "wb") as f:
        pickle.dump(host, f)


def load(path: str):
    with open(path, "rb") as f:
        return pickle.load(f)
