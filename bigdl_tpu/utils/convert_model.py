"""ConvertModel CLI: convert models between bigdl/caffe/torch/tf/keras.

Reference: utils/ConvertModel.scala — scopt CLI with --from/--to/--input/
--output/--prototxt/--tf_inputs/--tf_outputs/--quantize, wiring
Module.load{Caffe,Torch,TF}/save{Caffe,TF} and the quantizer.

Usage:
  python -m bigdl_tpu.utils.convert_model \
      --from caffe --to bigdl --input net.caffemodel --prototxt net.prototxt \
      --output model.bigdl [--quantize]
  python -m bigdl_tpu.utils.convert_model \
      --from bigdl --to tf --input model.bigdl --output graph.pb \
      --input-shape 8,8,3
"""

from __future__ import annotations

import argparse
from typing import Optional


def load_model(fmt: str, path: str, prototxt: Optional[str] = None,
               tf_inputs=None, tf_outputs=None, keras_json: Optional[str] = None,
               input_shape=None):
    fmt = fmt.lower()
    if fmt == "bigdl":
        from bigdl_tpu.utils.file import load_module

        return load_module(path)
    if fmt == "torch":
        from bigdl_tpu.utils.torchfile import load_torch

        return load_torch(path)
    if fmt == "caffe":
        from bigdl_tpu.utils.caffe import load_caffe

        if not prototxt:
            raise ValueError("--prototxt is required for --from caffe")
        return load_caffe(prototxt, path)
    if fmt in ("tf", "tensorflow"):
        from bigdl_tpu.utils.tf_import import load_tf

        if not tf_inputs or not tf_outputs:
            raise ValueError("--tf-inputs/--tf-outputs are required "
                             "for --from tf")
        return load_tf(path, list(tf_inputs), list(tf_outputs))
    if fmt == "keras":
        from bigdl_tpu.keras.converter import load_keras

        # keras_json optional: model.save(...h5) embeds model_config
        return load_keras(json_path=keras_json or None, hdf5_path=path,
                          input_shape=input_shape)
    raise ValueError(f"unknown source format {fmt!r}")


def save_model(model, fmt: str, path: str, prototxt: Optional[str] = None,
               input_shape=None):
    fmt = fmt.lower()
    if fmt == "bigdl":
        from bigdl_tpu.utils.file import save_module

        save_module(model, path, overwrite=True)
        return
    if fmt == "torch":
        from bigdl_tpu.utils import torchfile

        torchfile.save(path, model)
        return
    if fmt == "caffe":
        if not prototxt:
            raise ValueError("--prototxt is required for --to caffe")
        from bigdl_tpu.utils.caffe_export import save_caffe

        save_caffe(model, prototxt, path, input_shape=input_shape)
        return
    if fmt in ("tf", "tensorflow"):
        if input_shape is None:
            raise ValueError("--input-shape is required for --to tf")
        from bigdl_tpu.utils.tf_export import save_tf

        save_tf(model, tuple(input_shape), path)
        return
    raise ValueError(f"unknown target format {fmt!r}")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Convert models between formats (≙ utils/ConvertModel.scala)")
    p.add_argument("--from", dest="src", required=True,
                   choices=["bigdl", "caffe", "torch", "tf", "keras"])
    p.add_argument("--to", dest="dst", required=True,
                   choices=["bigdl", "caffe", "torch", "tf"])
    p.add_argument("--input", required=True, help="source model path")
    p.add_argument("--output", required=True, help="target model path")
    p.add_argument("--prototxt", default=None,
                   help="caffe prototxt (source or target)")
    p.add_argument("--keras-json", default=None, help="keras json topology")
    p.add_argument("--tf-inputs", default=None,
                   help="comma-separated tf graph input names")
    p.add_argument("--tf-outputs", default=None,
                   help="comma-separated tf graph output names")
    p.add_argument("--input-shape", default=None,
                   help="comma-separated sample shape (tf/caffe export)")
    p.add_argument("--quantize", action="store_true",
                   help="int8-quantize before saving (bigdl target only)")
    args = p.parse_args(argv)

    shape = (tuple(int(d) for d in args.input_shape.split(","))
             if args.input_shape else None)
    model = load_model(args.src, args.input, prototxt=args.prototxt,
                       tf_inputs=args.tf_inputs.split(",") if args.tf_inputs
                       else None,
                       tf_outputs=args.tf_outputs.split(",") if args.tf_outputs
                       else None,
                       keras_json=args.keras_json, input_shape=shape)
    if args.quantize:
        if args.dst != "bigdl":
            raise ValueError("--quantize only supports --to bigdl "
                             "(≙ ConvertModel.scala's quantize gate)")
        from bigdl_tpu.nn.quantized import Quantizer

        model = Quantizer.quantize(model)
    save_model(model, args.dst, args.output, prototxt=args.prototxt,
               input_shape=shape)
    print(f"converted {args.src} -> {args.dst}: {args.output}")


if __name__ == "__main__":
    main()
