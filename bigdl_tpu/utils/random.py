"""Reproducible random number generation.

TPU-native analog of the reference's Mersenne-Twister ``RandomGenerator``
(reference: utils/RandomGenerator.scala:23,56). Instead of a global mutable
MT19937 stream, we keep one global :class:`RandomGenerator` that owns a JAX
PRNG key and hands out fresh subkeys. Inside a traced (pure) application the
generator is *scoped*: ``push_key``/``pop_key`` bind a caller-supplied key so
the same layer code is deterministic and jit-safe (the traced key is threaded
in from the training step).
"""

from __future__ import annotations

import jax


class RandomGenerator:
    """A splittable PRNG stream with Torch-style set_seed semantics."""

    def __init__(self, seed: int = 1):
        self._seed = seed
        self._key = jax.random.PRNGKey(seed)
        # Stack of externally pushed keys (used during pure/traced application).
        self._stack = []

    def set_seed(self, seed: int) -> "RandomGenerator":
        self._seed = seed
        self._key = jax.random.PRNGKey(seed)
        return self

    def get_seed(self) -> int:
        return self._seed

    def push_key(self, key) -> None:
        """Bind an explicit key (e.g. a tracer) for the duration of a pure apply."""
        self._stack.append(key)

    def pop_key(self) -> None:
        self._stack.pop()

    @property
    def scoped(self) -> bool:
        return bool(self._stack)

    def next_key(self):
        """Return a fresh subkey, advancing whichever stream is active.

        The global (unscoped) stream is split under
        ``ensure_compile_time_eval`` so that a module called inside a raw
        ``jax.jit`` (instead of the sanctioned pure_apply/bind path, which
        pushes a scoped key) cannot poison the global key with a tracer —
        the split runs eagerly and the successor stays concrete."""
        if self._stack:
            self._stack[-1], sub = jax.random.split(self._stack[-1])
            return sub
        with jax.ensure_compile_time_eval():
            self._key, sub = jax.random.split(self._key)
        return sub

    def peek_key(self):
        """Current stream state WITHOUT advancing it. Re-binding this state
        via push_key replays the exact draw sequence that followed it (used
        by Module.backward to replay forward-time stochastic masks)."""
        return self._stack[-1] if self._stack else self._key

    # -- convenience samplers (eager use: weight init, data shuffling) -------
    def uniform(self, shape, minval=0.0, maxval=1.0, dtype="float32"):
        return jax.random.uniform(
            self.next_key(), shape, minval=minval, maxval=maxval, dtype=dtype
        )

    def normal(self, shape, mean=0.0, stdv=1.0, dtype="float32"):
        return mean + stdv * jax.random.normal(self.next_key(), shape, dtype=dtype)

    def permutation(self, n: int):
        return jax.random.permutation(self.next_key(), n)

    def bernoulli(self, shape, p):
        return jax.random.bernoulli(self.next_key(), p, shape)


#: Global generator, mirrors the reference's ``RandomGenerator.RNG`` singleton.
RNG = RandomGenerator(1)


def set_seed(seed: int) -> None:
    RNG.set_seed(seed)


def next_key():
    return RNG.next_key()
