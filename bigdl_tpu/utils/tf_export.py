"""Export a module tree to a TensorFlow GraphDef.

Reference: utils/tf/BigDLToTensorflow.scala (per-layer converters) +
Module.saveTF (nn/Module.scala). The GraphDef is ENCODED with
utils/protowire against the public tensorflow .proto field numbers — the
mirror image of utils/tf_import's decoder.

Layout: TF's CPU kernels only run NHWC convs/pools, so spatial models must
be BUILT channels-last (``format="NHWC"`` on conv/pool/BN) to export —
_emit validates each spatial module's format against the export
data_format and raises on mismatch (≙ BigDLToTensorflow's NHWC
requirement). Weights stay OIHW in the module and are transposed to HWIO
at export. Layout-free models (MLPs) are unaffected.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import protowire as pw

_DT_FLOAT = 1
_DT_INT32 = 3


# ----------------------------------------------------------- proto encoding
def _shape_proto(shape) -> bytes:
    out = b""
    for d in shape:
        out += pw.enc_bytes(2, pw.enc_varint(1, int(d)))
    return out


def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = _DT_INT32 if arr.dtype in (np.int32, np.int64) else _DT_FLOAT
    arr = arr.astype(np.int32 if dt == _DT_INT32 else np.float32)
    out = pw.enc_varint(1, dt)
    out += pw.enc_bytes(2, _shape_proto(arr.shape))
    out += pw.enc_bytes(4, arr.tobytes())
    return out


def _attr(value_bytes: bytes) -> bytes:
    return value_bytes


def _attr_entry(key: str, value_bytes: bytes) -> bytes:
    return pw.enc_bytes(5, pw.enc_string(1, key) + pw.enc_bytes(2, value_bytes))


def _attr_type(key: str, dt: int) -> bytes:
    return _attr_entry(key, pw.enc_varint(6, dt))


def _attr_tensor(key: str, arr) -> bytes:
    return _attr_entry(key, pw.enc_bytes(8, _tensor_proto(arr)))


def _attr_shape(key: str, shape) -> bytes:
    return _attr_entry(key, pw.enc_bytes(7, _shape_proto(shape)))


def _attr_s(key: str, s: str) -> bytes:
    return _attr_entry(key, pw.enc_bytes(2, s.encode()))


def _attr_b(key: str, v: bool) -> bytes:
    return _attr_entry(key, pw.enc_varint(5, 1 if v else 0))


def _attr_ints(key: str, vals) -> bytes:
    lst = b"".join(pw.enc_varint(3, int(v)) for v in vals)
    return _attr_entry(key, pw.enc_bytes(1, lst))


def _node(name: str, op: str, inputs: List[str], *attrs: bytes) -> bytes:
    body = pw.enc_string(1, name) + pw.enc_string(2, op)
    for i in inputs:
        body += pw.enc_string(3, i)
    for a in attrs:
        body += a
    return pw.enc_bytes(1, body)


class GraphDefBuilder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self._names: Dict[str, int] = {}

    def fresh(self, base: str) -> str:
        n = self._names.get(base, 0)
        self._names[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    def const(self, name: str, arr) -> str:
        name = self.fresh(name)
        arr = np.asarray(arr)
        dt = _DT_INT32 if arr.dtype in (np.int32, np.int64) else _DT_FLOAT
        self.nodes.append(_node(name, "Const", [],
                                _attr_type("dtype", dt),
                                _attr_tensor("value", arr)))
        return name

    def op(self, op: str, name: str, inputs: List[str], *attrs: bytes,
           with_t: bool = True) -> str:
        name = self.fresh(name)
        alist = list(attrs)
        if with_t:
            alist.append(_attr_type("T", _DT_FLOAT))
        self.nodes.append(_node(name, op, inputs, *alist))
        return name

    def placeholder(self, name: str, shape) -> str:
        name = self.fresh(name)
        self.nodes.append(_node(name, "Placeholder", [],
                                _attr_type("dtype", _DT_FLOAT),
                                _attr_shape("shape", shape)))
        return name

    def build(self) -> bytes:
        out = b"".join(self.nodes)
        # versions: producer high enough for modern TF importers
        out += pw.enc_bytes(4, pw.enc_varint(1, 1087))
        return out


# ------------------------------------------------------------- module walk
def _flatten_modules(module: Module) -> List[Module]:
    from bigdl_tpu.nn.container import flatten_sequential

    return flatten_sequential(module)


def save_tf(module: Module, input_shape, path: str,
            input_name: str = "input", output_name: str = "output",
            data_format: str = "NHWC") -> Dict[str, str]:
    """Export ``module`` (a Sequential pipeline of supported layers) as a
    frozen GraphDef (≙ Module.saveTF / BigDLToTensorflow). ``input_shape``
    excludes batch; spatial models are exported NHWC (give the NHWC shape).
    Returns {"input": name, "output": name}."""
    g = GraphDefBuilder()
    cur = g.placeholder(input_name, (-1,) + tuple(input_shape))
    for m in _flatten_modules(module):
        cur = _emit(g, m, cur, data_format)
    out = g.op("Identity", output_name, [cur])
    with open(path, "wb") as f:
        f.write(g.build())
    return {"input": input_name, "output": out}


def _emit(g: GraphDefBuilder, m: Module, cur: str, fmt: str) -> str:
    name = type(m).__name__

    if isinstance(m, nn.Linear):
        w = np.asarray(m.weight)  # (out, in)
        wn = g.const(f"{name}/weight", w.T.copy())
        cur = g.op("MatMul", f"{name}/matmul", [cur, wn],
                   _attr_b("transpose_a", False), _attr_b("transpose_b", False))
        if getattr(m, "with_bias", True) and hasattr(m, "bias"):
            bn = g.const(f"{name}/bias", np.asarray(m.bias))
            cur = g.op("BiasAdd", f"{name}/biasadd", [cur, bn])
        return cur
    if isinstance(m, nn.SpatialConvolution):
        if m.n_group != 1:
            raise ValueError("grouped conv export is unsupported")
        if m.format != fmt:
            raise ValueError(
                f"conv module is {m.format} but export data_format is "
                f"{fmt}; build the model with format={fmt!r} (TF CPU "
                "kernels only run NHWC)")
        w = np.asarray(m.weight)  # OIHW
        hwio = np.transpose(w, (2, 3, 1, 0)).copy()
        wn = g.const(f"{name}/weight", hwio)
        if m.pad_w == -1 or m.pad_h == -1:
            padding = "SAME"
        elif (m.pad_w, m.pad_h) == (0, 0):
            padding = "VALID"
        else:
            raise ValueError(
                "explicit conv padding has no TF attr; use SAME/VALID")
        cur = g.op("Conv2D", f"{name}/conv", [cur, wn],
                   _attr_ints("strides", (1, m.stride_h, m.stride_w, 1)),
                   _attr_s("padding", padding),
                   _attr_s("data_format", fmt))
        if m.with_bias:
            bn = g.const(f"{name}/bias", np.asarray(m.bias))
            cur = g.op("BiasAdd", f"{name}/biasadd", [cur, bn],
                       _attr_s("data_format", fmt))
        return cur
    if isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        if m.format != fmt:
            raise ValueError(
                f"pool module is {m.format} but export data_format is {fmt}")
        if (m.pad_h, m.pad_w) != (0, 0):
            raise ValueError(
                "explicitly padded pooling has no TF attr (only VALID "
                "exports exactly); restructure with pad 0")
        if m.ceil_mode:
            raise ValueError("ceil-mode pooling does not export to TF "
                             "(VALID floors); use floor mode")
        op = "MaxPool" if isinstance(m, nn.SpatialMaxPooling) else "AvgPool"
        return g.op(op, f"{name}/pool", [cur],
                    _attr_ints("ksize", (1, m.kh, m.kw, 1)),
                    _attr_ints("strides", (1, m.dh, m.dw, 1)),
                    _attr_s("padding", "VALID"),
                    _attr_s("data_format", fmt))
    if isinstance(m, nn.ReLU):
        return g.op("Relu", f"{name}", [cur])
    if isinstance(m, nn.Tanh):
        return g.op("Tanh", f"{name}", [cur])
    if isinstance(m, nn.Sigmoid):
        return g.op("Sigmoid", f"{name}", [cur])
    if isinstance(m, nn.SoftMax):
        return g.op("Softmax", f"{name}", [cur])
    if isinstance(m, nn.LogSoftMax):
        return g.op("LogSoftmax", f"{name}", [cur])
    if isinstance(m, nn.Dropout):
        return cur  # inference export: identity
    if isinstance(m, (nn.View, nn.Reshape)):
        dims = [int(d) for d in
                (m.sizes if hasattr(m, "sizes") else m.size)]
        shape = g.const(f"{name}/shape",
                        np.asarray([-1] + dims, np.int32))
        return g.op("Reshape", f"{name}", [cur, shape],
                    _attr_entry("Tshape", pw.enc_varint(6, _DT_INT32)))
    if isinstance(m, (nn.SpatialBatchNormalization, nn.BatchNormalization)):
        if isinstance(m, nn.SpatialBatchNormalization) and m.format != fmt:
            raise ValueError(
                f"BN module is {m.format} but export data_format is {fmt}")
        # eval-mode BN folds to x*scale + offset (exported as Mul + Add)
        eps = m.eps
        mean = np.asarray(m.running_mean)
        var = np.asarray(m.running_var)
        gamma = np.asarray(m.weight) if m.affine else np.ones_like(mean)
        beta = np.asarray(m.bias) if m.affine else np.zeros_like(mean)
        scale = gamma / np.sqrt(var + eps)
        offset = beta - mean * scale
        sn = g.const(f"{name}/scale", scale.astype(np.float32))
        on = g.const(f"{name}/offset", offset.astype(np.float32))
        cur = g.op("Mul", f"{name}/mul", [cur, sn])
        return g.op("Add", f"{name}/add", [cur, on])
    if isinstance(m, nn.Identity):
        return cur
    raise ValueError(f"tf export: unsupported layer {name}")
