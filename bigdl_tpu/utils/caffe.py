"""Caffe model import: prototxt + caffemodel → bigdl_tpu Graph.

Reference: utils/caffe/CaffeLoader.scala:57-299 (+ Converter/
V1LayerConverter) — parse NetParameter (text or binary), convert each
layer to a module node wiring bottoms/tops, then copy blob weights.
Interpretation here is by field number against the public caffe.proto;
binary decoding rides utils/protowire. Supports the layer set the
reference converts for the BASELINE config-4 path (Inception-v1 predict):
Convolution, Pooling, InnerProduct, ReLU, LRN, Concat, Dropout, Softmax,
Eltwise, BatchNorm(+Scale), Sigmoid, TanH, Flatten, Input/Data.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.utils import protowire as pw


# --------------------------------------------------------------- prototxt
def parse_prototxt(text: str) -> Dict[str, list]:
    """Parse protobuf text format into nested {key: [values]} dicts."""
    text = re.sub(r"#[^\n]*", "", text)  # strip comments
    tokens = re.findall(r'"(?:\\.|[^"\\])*"|[{}:]|[^\s{}:]+', text)
    pos = 0

    def parse_block():
        nonlocal pos
        out: Dict[str, list] = {}
        while pos < len(tokens):
            t = tokens[pos]
            if t == "}":
                pos += 1
                return out
            key = t
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
                val = tokens[pos]
                pos += 1
                if val.startswith('"'):
                    val = val[1:-1]
                else:
                    val = _coerce(val)
                out.setdefault(key, []).append(val)
            elif pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                out.setdefault(key, []).append(parse_block())
            else:
                raise ValueError(f"prototxt parse error near {key!r}")
        return out

    return parse_block()


def _coerce(v: str):
    if v in ("true", "True"):
        return True
    if v in ("false", "False"):
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v  # enum name


def _g1(d: dict, key: str, default=None):
    vals = d.get(key)
    return vals[0] if vals else default


# ------------------------------------------------------- binary caffemodel
# LayerParameter field numbers (public caffe.proto)
_LP = {"name": 1, "type": 2, "bottom": 3, "top": 4, "blobs": 7,
       "concat": 104, "convolution": 106, "dropout": 108, "eltwise": 110,
       "inner_product": 117, "lrn": 118, "pooling": 121, "relu": 123,
       "batch_norm": 139, "scale": 142, "input": 143}

# V1LayerParameter (old `layers` field): name=4 type=5(enum) bottom=2 top=3 blobs=6
_V1_TYPES = {1: "Accuracy", 3: "Concat", 4: "Convolution", 5: "Data",
             6: "Dropout", 8: "Flatten", 14: "InnerProduct", 15: "LRN",
             17: "Pooling", 18: "ReLU", 19: "Sigmoid", 20: "Softmax",
             21: "SoftmaxWithLoss", 22: "Split", 23: "TanH", 25: "Eltwise"}
_V1_PARAM_FIELDS = {"concat": 9, "convolution": 10, "dropout": 12,
                    "inner_product": 17, "lrn": 18, "pooling": 19,
                    "relu": 30}


def _blob_to_array(blob_bytes: bytes) -> np.ndarray:
    msg = pw.decode(blob_bytes)
    if 7 in msg:  # shape: BlobShape{dim=1 packed int64}
        shape = pw.repeated_varints(pw.decode(msg[7][0]).get(1, []))
    else:  # legacy num/channels/height/width
        shape = [ _g1_int(msg, f, 1) for f in (1, 2, 3, 4) ]
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    data: List[float] = []
    for chunk in msg.get(5, []):  # data: packed floats
        data.extend(pw.packed_floats(chunk))
    arr = np.asarray(data, np.float32)
    return arr.reshape([int(s) for s in shape]) if shape else arr


def _g1_int(msg: dict, field: int, default: int = 0) -> int:
    vals = msg.get(field)
    return int(vals[0]) if vals else default


def _binary_layer_record(layer_bytes: bytes, v1: bool) -> dict:
    msg = pw.decode(layer_bytes)
    if v1:
        rec = {
            "name": pw.as_string(msg.get(4, [b""])[0]),
            "type": _V1_TYPES.get(_g1_int(msg, 5), f"V1_{_g1_int(msg, 5)}"),
            "bottom": [pw.as_string(v) for v in msg.get(2, [])],
            "top": [pw.as_string(v) for v in msg.get(3, [])],
            "blobs": [_blob_to_array(b) for b in msg.get(6, [])],
        }
    else:
        rec = {
            "name": pw.as_string(msg.get(1, [b""])[0]),
            "type": pw.as_string(msg.get(2, [b""])[0]),
            "bottom": [pw.as_string(v) for v in msg.get(3, [])],
            "top": [pw.as_string(v) for v in msg.get(4, [])],
            "blobs": [_blob_to_array(b) for b in msg.get(7, [])],
        }
    return rec


def parse_caffemodel(data: bytes) -> List[dict]:
    """NetParameter binary → list of layer records with blobs."""
    net = pw.decode(data)
    records = []
    for lb in net.get(100, []):  # layer (new)
        records.append(_binary_layer_record(lb, v1=False))
    for lb in net.get(2, []):  # layers (V1)
        records.append(_binary_layer_record(lb, v1=True))
    return records


# ---------------------------------------------------------------- building
class _CaffeNet:
    def __init__(self, proto: Dict[str, list]):
        self.proto = proto

    def layer_defs(self) -> List[dict]:
        return [l for l in self.proto.get("layer", []) + self.proto.get("layers", [])]

    def input_names(self) -> List[str]:
        return list(self.proto.get("input", []))


_TEST_SKIP_TYPES = {"Data", "ImageData", "HDF5Data", "Accuracy",
                    "SoftmaxWithLoss", "Silence", "Split"}


def _conv_module(p: dict) -> nn.Module:
    num_out = _g1(p, "num_output")
    ks = _g1(p, "kernel_size")
    kh = _g1(p, "kernel_h", ks)
    kw = _g1(p, "kernel_w", ks)
    stride = _g1(p, "stride", 1)
    sh = _g1(p, "stride_h", stride)
    sw = _g1(p, "stride_w", stride)
    pad = _g1(p, "pad", 0)
    ph = _g1(p, "pad_h", pad)
    pab = _g1(p, "pad_w", pad)
    group = _g1(p, "group", 1)
    bias = _g1(p, "bias_term", True)
    dilation = _g1(p, "dilation", 1)
    n_in = p["__n_in__"]
    if dilation and dilation > 1:
        return nn.SpatialDilatedConvolution(n_in, num_out, kw, kh, sw, sh,
                                            pab, ph, dilation, dilation)
    return nn.SpatialConvolution(n_in, num_out, kw, kh, sw, sh, pab, ph,
                                 n_group=group, with_bias=bool(bias))


def _pool_module(p: dict) -> nn.Module:
    mode = _g1(p, "pool", "MAX")
    if _g1(p, "global_pooling", False):
        return nn.SpatialAveragePooling(1, 1, global_pooling=True) \
            if mode in ("AVE", 1) else _GlobalMaxPool()
    ks = _g1(p, "kernel_size")
    kh = _g1(p, "kernel_h", ks)
    kw = _g1(p, "kernel_w", ks)
    stride = _g1(p, "stride", 1)
    sh = _g1(p, "stride_h", stride)
    sw = _g1(p, "stride_w", stride)
    pad = _g1(p, "pad", 0)
    ph = _g1(p, "pad_h", pad)
    pb = _g1(p, "pad_w", pad)
    # caffe defaults to CEIL; round_mode: FLOOR (enum 1) opts out
    ceil = _g1(p, "round_mode", "CEIL") not in ("FLOOR", 1)
    if mode in ("MAX", 0):
        mp = nn.SpatialMaxPooling(kw, kh, sw, sh, pb, ph)
        return mp.ceil() if ceil else mp
    return nn.SpatialAveragePooling(kw, kh, sw, sh, pb, ph, ceil_mode=ceil)


class _GlobalMaxPool(nn.Module):
    def forward(self, x):
        return jnp.max(x, axis=(2, 3), keepdims=True)


class _Flatten(nn.Module):
    def forward(self, x):
        return x.reshape(x.shape[0], -1)


class _InnerProduct(nn.Module):
    """Flatten trailing dims then Linear (caffe IP semantics, axis=1).
    With no caffemodel blobs the fan-in is unknown until the first call
    (prototxt-only load) — the Linear is then built lazily."""

    def __init__(self, n_in: Optional[int], n_out: int, bias: bool):
        super().__init__()
        self.n_out, self.with_bias = n_out, bias
        if n_in is not None:
            self.linear = nn.Linear(n_in, n_out, with_bias=bias)
        else:
            self.linear = None

    def forward(self, x):
        flat = x.reshape(x.shape[0], -1)
        if self.linear is None:
            self.linear = nn.Linear(int(flat.shape[1]), self.n_out,
                                    with_bias=self.with_bias)
        return self.linear(flat)


class CaffeLoader:
    """≙ CaffeLoader.loadCaffe (utils/caffe/CaffeLoader.scala:85-127)."""

    def __init__(self, def_path: str, model_path: Optional[str] = None):
        with open(def_path) as f:
            self.net = _CaffeNet(parse_prototxt(f.read()))
        self.weights: Dict[str, List[np.ndarray]] = {}
        if model_path is not None:
            with open(model_path, "rb") as f:
                for rec in parse_caffemodel(f.read()):
                    if rec["blobs"]:
                        self.weights[rec["name"]] = rec["blobs"]

    # ---------------------------------------------------------------- build
    def load(self, input_channels: int = 3):
        """Build the Graph and copy weights. Returns (model, input_names).
        ``input_dim`` lines in the prototxt (N, C, H, W) override the
        ``input_channels`` default."""
        dims = self.net.proto.get("input_dim", [])
        if len(dims) >= 2:
            input_channels = int(dims[1])
        defs = [d for d in self.net.layer_defs()
                if not self._is_train_only(d)]
        blob_node: Dict[str, nn.Node] = {}
        blob_channels: Dict[str, int] = {}
        inputs = []

        for name in self.net.input_names():
            node = nn.Input()
            blob_node[name] = node
            blob_channels[name] = input_channels
            inputs.append(node)

        named_modules: Dict[str, nn.Module] = {}
        outputs_order: List[nn.Node] = []
        consumed = set()

        for d in defs:
            ltype = str(_g1(d, "type", ""))
            name = str(_g1(d, "name", ""))
            if ltype in ("Input",):
                node = nn.Input()
                for top in d.get("top", []):
                    blob_node[top] = node
                    blob_channels[top] = input_channels
                inputs.append(node)
                continue
            if ltype in _TEST_SKIP_TYPES:
                # pass-through: map tops to bottom's node where possible
                bots = d.get("bottom", [])
                for top in d.get("top", []):
                    if bots and bots[0] in blob_node:
                        blob_node[top] = blob_node[bots[0]]
                        blob_channels[top] = blob_channels.get(bots[0], input_channels)
                continue

            bots = [b for b in d.get("bottom", [])]
            module, out_channels = self._convert(ltype, d, bots, blob_channels)
            if module is None:
                raise ValueError(f"unsupported caffe layer type {ltype!r} ({name})")
            module.set_name(name)
            named_modules[name] = module
            prev = [blob_node[b] for b in bots]
            consumed.update(id(p) for p in prev)
            node = module.inputs(*prev)
            for top in d.get("top", []):
                blob_node[top] = node
                blob_channels[top] = out_channels
            outputs_order.append(node)

        # outputs = nodes never consumed as a bottom at build time
        outs = [n for n in outputs_order if id(n) not in consumed] \
            or outputs_order[-1:]

        model = nn.Graph(inputs, outs)
        self._copy_weights(named_modules)
        return model, inputs

    def _is_train_only(self, d: dict) -> bool:
        for inc in d.get("include", []):
            if isinstance(inc, dict) and _g1(inc, "phase") in ("TRAIN", 0):
                return True
        return False

    def _convert(self, ltype: str, d: dict, bots, blob_channels):
        n_in = blob_channels.get(bots[0], 3) if bots else 3
        if ltype == "Convolution":
            p = _g1(d, "convolution_param", {})
            p = dict(p)
            p["__n_in__"] = n_in
            m = _conv_module(p)
            return m, _g1(p, "num_output")
        if ltype == "Pooling":
            return _pool_module(_g1(d, "pooling_param", {})), n_in
        if ltype == "InnerProduct":
            p = _g1(d, "inner_product_param", {})
            num_out = _g1(p, "num_output")
            blobs = self.weights.get(str(_g1(d, "name", "")))
            if blobs:
                in_features = int(np.prod(blobs[0].shape[1:])) \
                    if blobs[0].ndim > 1 else blobs[0].shape[0] // num_out
            else:
                in_features = None  # prototxt-only: lazy build on first call
            return _InnerProduct(in_features, num_out,
                                 bool(_g1(p, "bias_term", True))), num_out
        if ltype == "ReLU":
            return nn.ReLU(), n_in
        if ltype == "Sigmoid":
            return nn.Sigmoid(), n_in
        if ltype == "TanH":
            return nn.Tanh(), n_in
        if ltype == "LRN":
            p = _g1(d, "lrn_param", {})
            return nn.SpatialCrossMapLRN(
                _g1(p, "local_size", 5), _g1(p, "alpha", 1.0),
                _g1(p, "beta", 0.75), _g1(p, "k", 1.0)), n_in
        if ltype == "Concat":
            p = _g1(d, "concat_param", {})
            axis = _g1(p, "axis", _g1(p, "concat_dim", 1))
            total = sum(blob_channels.get(b, 0) for b in bots) if axis == 1 else n_in
            return nn.JoinTable(axis + 1), total
        if ltype == "Dropout":
            p = _g1(d, "dropout_param", {})
            return nn.Dropout(_g1(p, "dropout_ratio", 0.5)), n_in
        if ltype == "Softmax":
            return nn.SoftMax(), n_in
        if ltype == "Eltwise":
            p = _g1(d, "eltwise_param", {})
            op = _g1(p, "operation", "SUM")
            if op in ("SUM", 1):
                return nn.CAddTable(), n_in
            if op in ("PROD", 0):
                return nn.CMulTable(), n_in
            return nn.CMaxTable(), n_in
        if ltype == "BatchNorm":
            p = _g1(d, "batch_norm_param", {})
            return nn.SpatialBatchNormalization(
                n_in, _g1(p, "eps", 1e-5), affine=False), n_in
        if ltype == "Scale":
            p = _g1(d, "scale_param", {})
            return _ScaleModule(n_in, bool(_g1(p, "bias_term", False))), n_in
        if ltype == "Flatten":
            return _Flatten(), n_in
        return None, n_in

    # --------------------------------------------------------------- weights
    def _copy_weights(self, named_modules: Dict[str, nn.Module]) -> None:
        """≙ CaffeLoader.copyParameters (CaffeLoader.scala:255-299)."""
        for name, blobs in self.weights.items():
            m = named_modules.get(name)
            if m is None:
                continue
            target = m.linear if isinstance(m, _InnerProduct) else m
            if isinstance(target, (nn.SpatialConvolution,)):
                w = blobs[0].reshape(np.asarray(target.weight).shape)
                target._set_param("weight", jnp.asarray(w))
                if len(blobs) > 1 and "bias" in target._parameters:
                    target._set_param("bias", jnp.asarray(blobs[1].reshape(-1)))
            elif isinstance(target, nn.Linear):
                w = blobs[0].reshape(np.asarray(target.weight).shape)
                target._set_param("weight", jnp.asarray(w))
                if len(blobs) > 1 and "bias" in target._parameters:
                    target._set_param("bias", jnp.asarray(blobs[1].reshape(-1)))
            elif isinstance(target, nn.SpatialBatchNormalization):
                # caffe BatchNorm blobs: mean, var, scale_factor
                sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
                sf = 1.0 / sf if sf != 0 else 1.0
                target._set_buffer("running_mean", jnp.asarray(blobs[0].reshape(-1) * sf))
                target._set_buffer("running_var", jnp.asarray(blobs[1].reshape(-1) * sf))
            elif isinstance(target, _ScaleModule):
                target._set_param("weight", jnp.asarray(blobs[0].reshape(-1)))
                if len(blobs) > 1 and "bias" in target._parameters:
                    target._set_param("bias", jnp.asarray(blobs[1].reshape(-1)))


class _ScaleModule(nn.Module):
    """Per-channel affine (caffe Scale layer, usually after BatchNorm)."""

    def __init__(self, n: int, bias: bool):
        super().__init__()
        self.register_parameter("weight", jnp.ones((n,)))
        if bias:
            self.register_parameter("bias", jnp.zeros((n,)))
        self.has_bias = bias

    def forward(self, x):
        w = self.weight[None, :, None, None]
        out = x * w
        if self.has_bias:
            out = out + self.bias[None, :, None, None]
        return out


def load_caffe(def_path: str, model_path: Optional[str] = None,
               input_channels: int = 3):
    """≙ Module.loadCaffeModel (nn/Module.scala:80). Returns the Graph."""
    model, _ = CaffeLoader(def_path, model_path).load(input_channels)
    return model
