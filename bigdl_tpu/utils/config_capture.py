"""Constructor-config capture for serialization.

The reference serializes any layer by reflecting over its case-class
constructor (utils/serializer/ModuleSerializer.scala:34-118). The Python
analog: every subclass of an instrumented base records the (class, args,
kwargs) of its outermost ``__init__`` call on the instance, so the
serializer can re-create it with the same configuration.
"""

from __future__ import annotations

import functools

_SENTINEL = "_init_config"


def capture_init(cls) -> None:
    """Wrap cls.__init__ (if defined by cls itself) to record the outermost
    constructor call as ``self._init_config = (args, kwargs)``. Call from
    ``__init_subclass__`` of a base class to instrument a hierarchy."""
    orig = cls.__dict__.get("__init__")
    if orig is None or getattr(orig, "_captures_config", False):
        return

    @functools.wraps(orig)
    def wrapper(self, *args, **kwargs):
        if not hasattr(self, _SENTINEL):
            object.__setattr__(self, _SENTINEL, (args, kwargs))
        orig(self, *args, **kwargs)

    wrapper._captures_config = True
    cls.__init__ = wrapper


def get_init_config(obj):
    """(args, kwargs) of the outermost constructor call, or ((), {})."""
    return getattr(obj, _SENTINEL, ((), {}))


class ConfigCaptured:
    """Mixin: every subclass records its constructor args."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        capture_init(cls)
