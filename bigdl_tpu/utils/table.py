"""Torch-style activity Table.

TPU-native analog of the reference's heterogeneous key->value container
(reference: utils/Table.scala:34, factory ``T()`` at :318). Keys are 1-based
integers (Torch legacy, SURVEY.md Appendix B.1) or strings. Registered as a
JAX pytree so Tables flow through jit / grad / shard_map like any container.
"""

from __future__ import annotations

import jax


class Table:
    def __init__(self, *args, **kwargs):
        self._state = {}
        for i, v in enumerate(args):
            self._state[i + 1] = v
        self._state.update(kwargs)

    # -- mapping protocol ----------------------------------------------------
    def __getitem__(self, key):
        return self._state[key]

    def __setitem__(self, key, value):
        self._state[key] = value

    def __delitem__(self, key):
        del self._state[key]

    def __contains__(self, key):
        return key in self._state

    def __len__(self):
        return len(self._state)

    def __iter__(self):
        return iter(self._state.values())

    def keys(self):
        return self._state.keys()

    def values(self):
        return self._state.values()

    def items(self):
        return self._state.items()

    def get(self, key, default=None):
        return self._state.get(key, default)

    def update(self, other):
        if isinstance(other, Table):
            other = other._state
        self._state.update(other)
        return self

    def insert(self, *args):
        """``insert(value)`` appends at the next integer key; ``insert(pos, value)``."""
        if len(args) == 1:
            n = max([k for k in self._state if isinstance(k, int)] or [0])
            self._state[n + 1] = args[0]
        else:
            pos, value = args
            n = max([k for k in self._state if isinstance(k, int)] or [0])
            for i in range(n, pos - 1, -1):
                if i in self._state:
                    self._state[i + 1] = self._state[i]
            self._state[pos] = value
        return self

    def remove(self, pos=None):
        ints = sorted(k for k in self._state if isinstance(k, int))
        if not ints:
            return None
        if pos is None:
            pos = ints[-1]
        value = self._state.pop(pos, None)
        n = ints[-1]
        for i in range(pos + 1, n + 1):
            if i in self._state:
                self._state[i - 1] = self._state.pop(i)
        return value

    def __eq__(self, other):
        if not isinstance(other, Table):
            return NotImplemented
        if set(self._state.keys()) != set(other._state.keys()):
            return False
        import numpy as np

        for k, v in self._state.items():
            ov = other._state[k]
            if isinstance(v, Table) or isinstance(ov, Table):
                if v != ov:
                    return False
            else:
                try:
                    if not np.array_equal(v, ov):
                        return False
                except Exception:
                    if v != ov:
                        return False
        return True

    def __repr__(self):
        items = ", ".join(f"{k}: {type(v).__name__}" for k, v in self._state.items())
        return f"Table({items})"


def T(*args, **kwargs) -> Table:
    """Factory mirroring the reference's ``T()`` (utils/Table.scala:318)."""
    return Table(*args, **kwargs)


def _table_flatten(t: Table):
    keys = sorted(t._state.keys(), key=lambda k: (isinstance(k, str), k))
    return [t._state[k] for k in keys], tuple(keys)


def _table_unflatten(keys, children):
    t = Table()
    for k, v in zip(keys, children):
        t._state[k] = v
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
