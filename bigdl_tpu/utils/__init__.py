from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator

__all__ = ["Table", "T", "RandomGenerator"]
