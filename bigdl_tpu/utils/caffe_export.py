"""Export a module tree to Caffe prototxt + caffemodel.

Reference: utils/caffe/CaffePersister.scala (+ per-layer Converter
methods): walks the module graph, emits V2 LayerParameters with blobs.
Here the NetParameter binary is encoded with utils/protowire using the
same field numbers utils/caffe.py's importer reads (layer=100,
name=1/type=2/bottom=3/top=4/blobs=7; BlobProto shape=7/data=5), so
export -> import round-trips exactly.

Supported: Linear (InnerProduct), SpatialConvolution (Convolution),
SpatialMaxPooling/SpatialAveragePooling (Pooling), ReLU, Tanh, Sigmoid,
SoftMax (Softmax), Dropout, View/Reshape (Flatten when collapsing),
SpatialBatchNormalization (Scale with folded stats, inference-only).
"""

from __future__ import annotations

from typing import List

import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import protowire as pw


def _blob(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr, np.float32)
    shape = pw.enc_bytes(7, b"".join(pw.enc_varint(1, int(d))
                                     for d in arr.shape))
    # packed little-endian f32 IS the wire format — single tobytes
    data = pw.enc_bytes(5, np.ascontiguousarray(arr, "<f4").tobytes())
    return shape + data


def _layer_bin(name: str, type_: str, bottoms: List[str], tops: List[str],
               blobs: List[np.ndarray]) -> bytes:
    body = pw.enc_string(1, name) + pw.enc_string(2, type_)
    for b in bottoms:
        body += pw.enc_string(3, b)
    for t in tops:
        body += pw.enc_string(4, t)
    for blob in blobs:
        body += pw.enc_bytes(7, _blob(blob))
    return pw.enc_bytes(100, body)


def _flatten_modules(module: Module) -> List[Module]:
    from bigdl_tpu.nn.container import flatten_sequential

    return flatten_sequential(module)


def save_caffe(module: Module, prototxt_path: str, model_path: str,
               input_shape=None) -> None:
    """≙ Module.saveCaffe / CaffePersister.persist. ``input_shape`` is the
    sample shape sans batch for the prototxt input declaration."""
    proto_lines = ['name: "bigdl_tpu_export"', 'input: "data"']
    if input_shape is not None:
        for d in (1,) + tuple(input_shape):
            proto_lines.append(f"input_dim: {int(d)}")
    bins: List[bytes] = []
    bottom = "data"
    idx = 0

    def emit(type_: str, params: List[str], blobs: List[np.ndarray],
             name_hint: str):
        nonlocal bottom, idx
        idx += 1
        name = f"{name_hint}{idx}"
        top = name
        lines = ["layer {", f'  name: "{name}"', f'  type: "{type_}"',
                 f'  bottom: "{bottom}"', f'  top: "{top}"']
        lines += [f"  {p}" for p in params]
        lines.append("}")
        proto_lines.extend(lines)
        bins.append(_layer_bin(name, type_, [bottom], [top], blobs))
        bottom = top

    for m in _flatten_modules(module):
        cls = type(m).__name__
        if isinstance(m, nn.Linear):
            w = np.asarray(m.weight)  # (out, in) = caffe IP blob layout
            blobs = [w]
            if getattr(m, "with_bias", True) and hasattr(m, "bias"):
                blobs.append(np.asarray(m.bias))
            emit("InnerProduct",
                 ["inner_product_param {",
                  f"    num_output: {w.shape[0]}",
                  "  }"], blobs, "ip")
        elif isinstance(m, nn.SpatialConvolution):
            w = np.asarray(m.weight)  # OIHW = caffe conv blob layout
            blobs = [w]
            if m.with_bias:
                blobs.append(np.asarray(m.bias))
            pad_h, pad_w = m.pad_h, m.pad_w
            if pad_h == -1 or pad_w == -1:  # SAME sentinel
                if (m.stride_h, m.stride_w) != (1, 1) or \
                        m.kernel_h % 2 == 0 or m.kernel_w % 2 == 0:
                    raise ValueError(
                        "SAME conv padding only exports to caffe for "
                        "stride-1 odd kernels (symmetric pad)")
                pad_h = (m.kernel_h - 1) // 2
                pad_w = (m.kernel_w - 1) // 2
            emit("Convolution",
                 ["convolution_param {",
                  f"    num_output: {w.shape[0]}",
                  f"    kernel_h: {m.kernel_h}",
                  f"    kernel_w: {m.kernel_w}",
                  f"    stride_h: {m.stride_h}",
                  f"    stride_w: {m.stride_w}",
                  f"    pad_h: {pad_h}",
                  f"    pad_w: {pad_w}",
                  f"    group: {m.n_group}",
                  "  }"], blobs, "conv")
        elif isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            mode = "MAX" if isinstance(m, nn.SpatialMaxPooling) else "AVE"
            round_mode = "CEIL" if m.ceil_mode else "FLOOR"
            emit("Pooling",
                 ["pooling_param {", f"    pool: {mode}",
                  f"    kernel_h: {m.kh}", f"    kernel_w: {m.kw}",
                  f"    stride_h: {m.dh}", f"    stride_w: {m.dw}",
                  f"    pad_h: {m.pad_h}", f"    pad_w: {m.pad_w}",
                  f"    round_mode: {round_mode}",
                  "  }"], [], "pool")
        elif isinstance(m, nn.ReLU):
            emit("ReLU", [], [], "relu")
        elif isinstance(m, nn.Tanh):
            emit("TanH", [], [], "tanh")
        elif isinstance(m, nn.Sigmoid):
            emit("Sigmoid", [], [], "sigmoid")
        elif isinstance(m, nn.SoftMax):
            emit("Softmax", [], [], "prob")
        elif isinstance(m, nn.Dropout):
            continue  # inference export
        elif isinstance(m, (nn.View, nn.Reshape)):
            dims = getattr(m, "sizes", getattr(m, "size", None))
            if dims is not None and len(tuple(dims)) != 1:
                raise ValueError(
                    "only collapsing View/Reshape (rank-1 target) exports "
                    "as caffe Flatten")
            emit("Flatten", [], [], "flat")
        elif isinstance(m, (nn.SpatialBatchNormalization,
                            nn.BatchNormalization)):
            mean = np.asarray(m.running_mean)
            var = np.asarray(m.running_var)
            gamma = np.asarray(m.weight) if m.affine else np.ones_like(mean)
            beta = np.asarray(m.bias) if m.affine else np.zeros_like(mean)
            scale = gamma / np.sqrt(var + m.eps)
            emit("Scale", ["scale_param { bias_term: true }"],
                 [scale.astype(np.float32),
                  (beta - mean * scale).astype(np.float32)], "scale")
        elif isinstance(m, nn.Identity):
            continue
        else:
            raise ValueError(f"caffe export: unsupported layer {cls}")

    with open(prototxt_path, "w") as f:
        f.write("\n".join(proto_lines) + "\n")
    net = pw.enc_string(1, "bigdl_tpu_export") + b"".join(bins)
    with open(model_path, "wb") as f:
        f.write(net)
