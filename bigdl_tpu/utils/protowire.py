"""Schema-less protobuf wire-format codec.

Shared by the tensorboard event writer (bigdl_tpu.visualization.proto),
the Caffe binary loader (utils/caffe.py) and the TF GraphDef loader
(utils/tf_import.py). The reference ships generated Java protobuf classes
(spark/dl/src/main/java/caffe/Caffe.java, serialization/Bigdl.java); here
messages are decoded generically into {field_number: [values]} trees and
interpreted by field number against the public .proto schemas — no
protobuf runtime needed.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple


# ------------------------------------------------------------------ encode
def varint(n: int) -> bytes:
    out = bytearray()
    n &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def enc_varint(field: int, v: int) -> bytes:
    return tag(field, 0) + varint(int(v))


def enc_double(field: int, v: float) -> bytes:
    return tag(field, 1) + struct.pack("<d", v)


def enc_float(field: int, v: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", v)


def enc_bytes(field: int, v: bytes) -> bytes:
    return tag(field, 2) + varint(len(v)) + v


def enc_string(field: int, v: str) -> bytes:
    return enc_bytes(field, v.encode("utf-8"))


def enc_packed_floats(field: int, vals) -> bytes:
    return enc_bytes(field, b"".join(struct.pack("<f", float(v)) for v in vals))


def enc_packed_doubles(field: int, vals) -> bytes:
    return enc_bytes(field, b"".join(struct.pack("<d", float(v)) for v in vals))


def enc_packed_varints(field: int, vals) -> bytes:
    return enc_bytes(field, b"".join(varint(int(v)) for v in vals))


# ------------------------------------------------------------------ decode
def read_varint(data: bytes, i: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, object]]:
    """(field, wire_type, raw_value). Length-delimited -> bytes, varint ->
    int, fixed64/fixed32 -> raw bytes."""
    i, n = 0, len(data)
    while i < n:
        key, i = read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = read_varint(data, i)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, data[i:i + 8]
            i += 8
        elif wire == 5:
            yield field, wire, data[i:i + 4]
            i += 4
        elif wire == 2:
            ln, i = read_varint(data, i)
            yield field, wire, data[i:i + ln]
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")


def decode(data: bytes) -> Dict[int, List]:
    """One message level -> {field: [raw values in order]}."""
    out: Dict[int, List] = {}
    for field, _, value in iter_fields(data):
        out.setdefault(field, []).append(value)
    return out


# Typed readers over decode() results --------------------------------------
def as_string(v: bytes) -> str:
    return v.decode("utf-8")


def as_float(v) -> float:
    if isinstance(v, bytes):
        return struct.unpack("<f" if len(v) == 4 else "<d", v)[0]
    return float(v)


def as_signed(v: int, bits: int = 64) -> int:
    if v >= 1 << (bits - 1):
        v -= 1 << bits
    return v


def packed_floats(v: bytes) -> List[float]:
    return list(struct.unpack(f"<{len(v) // 4}f", v))


def packed_doubles(v: bytes) -> List[float]:
    return list(struct.unpack(f"<{len(v) // 8}d", v))


def packed_varints(v) -> List[int]:
    """Accepts either packed bytes or an already-decoded single varint."""
    if isinstance(v, int):
        return [v]
    out = []
    i = 0
    while i < len(v):
        val, i = read_varint(v, i)
        out.append(val)
    return out


def repeated_varints(values: List) -> List[int]:
    """Flatten a repeated scalar field that may mix packed and unpacked."""
    out: List[int] = []
    for v in values:
        out.extend(packed_varints(v))
    return out
