"""Orbax-backed training-state checkpointing.

The framework's own checkpoint format (pickle snapshots with atomic
rename, ≙ the reference's Checkpoint.save + File.saveBytes,
optim/Checkpoint.scala) is host-local. This module adds the TPU-native
alternative for mesh-sharded state: ``orbax.checkpoint`` writes each
array shard from the process that holds it (multi-host safe), restores
directly into the requested shardings, and supports async saves — the
production path for large sharded models (params/slots laid out by
DistriOptimizer's ZeRO-1 sharding never gather to one host).

API mirrors the train-state tuple the step functions carry::

    save_train_state(path, step, params, buffers, slots, state)
    step, params, buffers, slots, state = restore_train_state(
        path, like=(params, buffers, slots), shardings=None)
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax


_CKPTR = None


def _checkpointer():
    # one cached AsyncCheckpointer (it owns a background thread pool;
    # constructing one per call would leak threads over a long run)
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _norm(path: str) -> str:
    # URL-style paths (gs://, s3://) must pass through untouched
    return path if "://" in path else os.path.abspath(path)


def _open_meta(path: str, mode: str):
    if "://" in path:
        from etils import epath  # ships with orbax; object-store capable

        return epath.Path(path).open(mode)
    return open(path, mode)


def save_train_state(path: str, step: int, params, buffers, slots,
                     state: Optional[dict] = None) -> None:
    """Write one checkpoint directory (overwrites). Sharded arrays are
    written shard-by-shard from their owning devices/processes."""
    ckptr = _checkpointer()
    kept = {k: v for k, v in (state or {}).items()
            if isinstance(v, (bool, int, float, str))}
    path = _norm(path)
    meta = path + ".meta.json"
    # StandardCheckpointer stores arrays; step + driver-state scalars ride
    # in a sidecar json (its keys vary run-to-run anyway). Remove any STALE
    # meta first so a crash mid-overwrite is detected as incomplete rather
    # than silently pairing new arrays with the old step.
    if jax.process_index() == 0:
        try:
            if "://" in meta:
                from etils import epath

                epath.Path(meta).unlink()
            else:
                os.remove(meta)
        except FileNotFoundError:
            pass
    ckptr.save(path, {"params": params, "buffers": buffers, "slots": slots},
               force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:  # one writer on multi-host pods
        if "://" in meta:  # object stores have atomic single-shot puts
            with _open_meta(meta, "w") as f:
                json.dump({"step": int(step), "state": kept}, f)
        else:  # local/NFS: write-then-rename, never a torn meta
            with open(meta + ".tmp", "w") as f:
                json.dump({"step": int(step), "state": kept}, f)
            os.replace(meta + ".tmp", meta)
    if jax.process_count() > 1:
        # no process may return (and possibly restore) before process 0's
        # meta hits storage
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("bigdl_tpu_ckpt_meta")


def restore_train_state(path: str, like, shardings=None):
    """Restore (step, params, buffers, slots, state).

    ``like`` is a (params, buffers, slots) template pytree of arrays (for
    structure/dtype/shape); ``shardings`` — an optional matching pytree of
    ``jax.sharding.Sharding`` — restores each array DIRECTLY into its
    mesh placement (no host gather)."""
    params, buffers, slots = like
    ckptr = _checkpointer()

    def as_abstract(leaf, sh):
        leaf = jax.numpy.asarray(leaf)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if shardings is None:
        sh_tree = jax.tree.map(lambda _: None, (params, buffers, slots))
    else:
        sh_tree = shardings
    a_params, a_buffers, a_slots = jax.tree.map(
        as_abstract, (params, buffers, slots), sh_tree)
    path = _norm(path)
    tree = ckptr.restore(
        path, {"params": a_params, "buffers": a_buffers, "slots": a_slots})
    try:
        with _open_meta(path + ".meta.json", "r") as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise ValueError(
            f"{path}.meta.json missing: the checkpoint is incomplete "
            "(interrupted save?) — refusing to guess step 0 on trained "
            "weights") from None
    return (int(meta["step"]), tree["params"], tree["buffers"],
            tree["slots"], meta.get("state", {}))
