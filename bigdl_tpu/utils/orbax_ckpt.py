"""Orbax-backed training-state checkpointing.

The framework's own checkpoint format (pickle snapshots with atomic
rename, ≙ the reference's Checkpoint.save + File.saveBytes,
optim/Checkpoint.scala) is host-local. This module adds the TPU-native
alternative for mesh-sharded state: ``orbax.checkpoint`` writes each
array shard from the process that holds it (multi-host safe), restores
directly into the requested shardings, and supports async saves — the
production path for large sharded models (params/slots laid out by
DistriOptimizer's ZeRO-1 sharding never gather to one host).

API mirrors the train-state tuple the step functions carry::

    save_train_state(path, step, params, buffers, slots, state)
    step, params, buffers, slots, state = restore_train_state(
        path, like=(params, buffers, slots), shardings=None)
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax


_CKPTR = None


def _checkpointer():
    # one cached AsyncCheckpointer (it owns a background thread pool;
    # constructing one per call would leak threads over a long run)
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


# remote-path dispatch rides the single IO seam in utils/file.py;
# only _remove/_rename (swap-protocol specifics) live here
from bigdl_tpu.utils.file import (exists as _exists, is_remote as _is_remote,
                                  open_file as _open_meta)


def _norm(path: str) -> str:
    # URL-style paths (gs://, s3://) must pass through untouched
    return path if _is_remote(path) else os.path.abspath(path)


def _remove(path: str) -> None:
    """Remove a file or directory tree if present (no-op otherwise)."""
    if _is_remote(path):
        from etils import epath

        p = epath.Path(path)
        if p.exists():
            p.rmtree() if p.is_dir() else p.unlink()
        return
    if os.path.isdir(path):
        import shutil

        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def _rename(src: str, dst: str) -> None:
    # only the local-path swap protocol renames; object-store saves
    # never do (a gs:// prefix can't be renamed atomically)
    os.replace(src, dst)


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _swap_in(path: str) -> None:
    """Promote a COMPLETE ``path + ".tmp-save"`` pair to ``path``: retire
    the live pair to ``.old`` (arrays first, then meta), rename tmp in
    (arrays first, then meta), drop the retired pair. Every interruption
    window leaves a complete pair under a name _resolve_restore_path
    knows."""
    tmp, old = path + ".tmp-save", path + ".old"
    _remove(old)
    _remove(old + ".meta.json")
    if _exists(path):
        _rename(path, old)
    if _exists(path + ".meta.json"):
        _rename(path + ".meta.json", old + ".meta.json")
    _rename(tmp, path)
    _rename(tmp + ".meta.json", path + ".meta.json")
    _remove(old)
    _remove(old + ".meta.json")


def save_train_state(path: str, step: int, params, buffers, slots,
                     state: Optional[dict] = None) -> None:
    """Write one checkpoint directory at ``path``. Local paths replace any
    previous checkpoint ATOMICALLY: arrays land in ``path + ".tmp-save"``
    first, then a rename dance promotes them — an interruption at any
    point leaves the previous checkpoint or the new one fully restorable,
    never neither (restore_train_state knows the fallback names, newest
    first). Object-store paths (gs://, s3://) can't rename a prefix
    atomically, so they keep the meta-last protocol instead: old meta
    removed (marks the checkpoint detectably incomplete during the
    overwrite), arrays rewritten in place, meta put in one shot last.
    Sharded arrays are written shard-by-shard from their owning
    devices/processes."""
    ckptr = _checkpointer()
    kept = {k: v for k, v in (state or {}).items()
            if isinstance(v, (bool, int, float, str))}
    path = _norm(path)

    if _is_remote(path):
        meta = path + ".meta.json"
        if jax.process_index() == 0:
            _remove(meta)
        _barrier("bigdl_tpu_ckpt_pre")
        ckptr.save(path,
                   {"params": params, "buffers": buffers, "slots": slots},
                   force=True)
        ckptr.wait_until_finished()
        if jax.process_index() == 0:  # single-shot put: atomic on GCS/S3
            with _open_meta(meta, "w") as f:
                json.dump({"step": int(step), "state": kept}, f)
        _barrier("bigdl_tpu_ckpt_meta")
        return

    tmp = path + ".tmp-save"
    if jax.process_index() == 0:
        if _exists(tmp) and _exists(tmp + ".meta.json"):
            # a previous save crashed mid-swap AFTER fully writing the new
            # checkpoint: finish its swap (it is the newest state — the one
            # a restart restored from) rather than deleting it
            _swap_in(path)
        else:  # partial leftovers from a crash mid-write
            _remove(tmp)
            _remove(tmp + ".meta.json")
        # orbax itself stages into sibling '<tmp>.orbax-checkpoint-tmp-<ts>'
        # dirs and renames into place; a crash mid array-write orphans one
        # (with no '<tmp>' dir at all) — sweep them or they leak a full
        # checkpoint of disk per crashed save
        import glob

        for orphan in glob.glob(glob.escape(tmp) + ".orbax-checkpoint-tmp-*"):
            _remove(orphan)
    _barrier("bigdl_tpu_ckpt_pre")  # cleanup lands before shard writes
    ckptr.save(tmp, {"params": params, "buffers": buffers, "slots": slots},
               force=True)
    ckptr.wait_until_finished()
    if jax.process_index() == 0:  # one writer on multi-host pods
        # meta AFTER arrays: a (dir, meta) pair present => pair complete.
        # Step + driver-state scalars ride in a sidecar json
        # (StandardCheckpointer stores arrays; these keys vary run-to-run).
        # Local/NFS: write-then-rename, never a torn meta.
        with open(tmp + ".meta.json.part", "w") as f:
            json.dump({"step": int(step), "state": kept}, f)
        os.replace(tmp + ".meta.json.part", tmp + ".meta.json")
        _swap_in(path)
    # no process may return (and possibly restore) before process 0's
    # swap completes
    _barrier("bigdl_tpu_ckpt_meta")


def restore_train_state(path: str, like, shardings=None):
    """Restore (step, params, buffers, slots, state).

    ``like`` is a (params, buffers, slots) template pytree of arrays (for
    structure/dtype/shape); ``shardings`` — an optional matching pytree of
    ``jax.sharding.Sharding`` — restores each array DIRECTLY into its
    mesh placement (no host gather)."""
    params, buffers, slots = like
    ckptr = _checkpointer()

    def as_abstract(leaf, sh):
        leaf = jax.numpy.asarray(leaf)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if shardings is None:
        sh_tree = jax.tree.map(lambda _: None, (params, buffers, slots))
    else:
        sh_tree = shardings
    a_params, a_buffers, a_slots = jax.tree.map(
        as_abstract, (params, buffers, slots), sh_tree)
    path = _resolve_restore_path(_norm(path))
    tree = ckptr.restore(
        path, {"params": a_params, "buffers": a_buffers, "slots": a_slots})
    with _open_meta(path + ".meta.json", "r") as f:
        meta = json.load(f)
    return (int(meta["step"]), tree["params"], tree["buffers"],
            tree["slots"], meta.get("state", {}))


def _resolve_restore_path(path: str) -> str:
    """Pick the newest COMPLETE (arrays dir, meta) pair among the primary
    path and the atomic-swap leftovers a mid-save crash can leave.
    ``.tmp-save`` wins over the primary: its meta is only written after
    its arrays land, and the pair is renamed away the moment a swap
    completes — so a complete ``.tmp-save`` pair is always a newer
    checkpoint than whatever sits at ``path``. ``.old`` (previous
    checkpoint retired but not yet deleted) is the last resort."""
    for cand in (path + ".tmp-save", path, path + ".old"):
        if _exists(cand) and _exists(cand + ".meta.json"):
            if cand != path:
                import logging

                logging.getLogger("bigdl_tpu").warning(
                    "checkpoint save at %s was interrupted; restoring "
                    "the newest intact copy at %s", path, cand)
            return cand
    raise ValueError(
        f"{path}: checkpoint incomplete — no complete (arrays, meta) pair "
        "at the path or its .tmp-save/.old fallbacks; refusing to guess "
        "step 0 on trained weights")
