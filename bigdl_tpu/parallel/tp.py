"""Tensor-parallel sharding rules (GSPMD).

No reference analog — the reference's only parallelism is data parallel
(SURVEY.md §2.5). TPU-native TP is expressed as NamedSharding annotations
on the params pytree: jit/GSPMD then inserts the all-gathers/reduce-
scatters over ICI (scaling-book recipe: pick a mesh, annotate shardings,
let XLA place collectives).

``spec_for_params(params, rules)`` maps dotted param paths to
PartitionSpecs by first-match regex; ``transformer_tp_rules`` implements
the Megatron-style column/row split for the transformer stack:
  qkv / fc1  (out, in)  -> shard dim 0 (column parallel)
  out_proj / fc2        -> shard dim 1 (row parallel)
  tok_embed  (vocab, d) -> shard dim 0
  everything else       -> replicated

``kv_pool_spec`` / ``kv_pool_sharding`` lay out slot-pooled KV caches
(``(rows, H_kv, T, D)``) along the model axis on the heads dimension —
the layout the column-parallel QKV projection writes with ZERO
communication (each device computes exactly its own heads' K/V), used
by the serving engine's SPMD decode loop
(``bigdl_tpu.serving.engine.ContinuousBatchingEngine(mesh=...)``).
"""

from __future__ import annotations

import re
from typing import List, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def tree_paths(params, prefix=""):
    if isinstance(params, dict):
        for k, v in params.items():
            yield from tree_paths(v, f"{prefix}/{k}")
    else:
        yield prefix, params


def spec_for_params(params, rules: List[Tuple[str, P]], default: P = P()):
    """Pytree of PartitionSpec matching ``params``; first regex match wins."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def build(sub, prefix):
        if isinstance(sub, dict):
            return {k: build(v, f"{prefix}/{k}") for k, v in sub.items()}
        for pat, spec in compiled:
            if pat.search(prefix):
                return spec
        return default

    return build(params, "")


def transformer_tp_rules(model_axis: str = "model", data_axis: str = None):
    """Megatron-style rules for TransformerLM param paths. Pass
    ``data_axis`` to ADDITIONALLY shard each weight matrix over that
    axis on the dimension the model split leaves free (the zero-style
    2-D ``fsdp x tp`` layout: qkv/fc1 become ``P(model, data)``,
    out_proj/fc2 ``P(data, model)``), and to shard the otherwise-
    replicated positional table's first dim. Every sharded dimension
    must divide by its mesh-axis size (embed_dim, mlp hidden,
    qkv-out, and — with ``data_axis`` — vocab_size and max_len)."""
    mp, dp = model_axis, data_axis
    rules = [
        (r"attn/qkv/~params/weight$", P(mp, dp)),
        (r"attn/qkv/~params/bias$", P(mp)),
        (r"fc1/~params/weight$", P(mp, dp)),
        (r"fc1/~params/bias$", P(mp)),
        (r"attn/out_proj/~params/weight$", P(dp, mp)),
        (r"fc2/~params/weight$", P(dp, mp)),
        (r"~params/tok_embed$", P(mp, dp)),
        (r"head/~params/weight$", P(mp, dp)),
    ]
    if dp is not None:
        # the learned positional table is the one big replicated leaf
        # left; zero-style, its rows spread over the data axis
        rules.append((r"~params/pos_embed$", P(dp, None)))
    return rules


def shard_params(params, mesh, rules, default=P()):
    """device_put every leaf with its NamedSharding. (Manual walk:
    PartitionSpec is itself a pytree, so jax.tree.map would descend into it.)"""
    specs = spec_for_params(params, rules, default)

    def walk(p, s):
        if isinstance(p, dict):
            return {k: walk(v, s[k]) for k, v in p.items()}
        return jax.device_put(p, NamedSharding(mesh, s))

    return walk(params, specs)


def replicate(tree, mesh):
    """device_put every leaf fully replicated over ``mesh`` — host
    inputs and buffers entering an SPMD program with a committed,
    call-stable layout (one compiled signature, no per-call GSPMD
    resharding guesswork)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def kv_pool_spec(model_axis: str = "model") -> P:
    """PartitionSpec for a slot-pooled KV cache buffer
    ``(rows, H_kv, T, D)``: heads sharded along the model axis,
    rows/time/head-dim replicated — matches the column-parallel QKV
    split, so cache writes need no collective."""
    return P(None, model_axis, None, None)


def fetch_to_host(tree):
    """One bulk device->host move of a buffer tree: a single blocking
    ``device_get`` per leaf, no per-chunk round trips ("RPC Considered
    Harmful": serialize once, move once). For a mesh-sharded leaf each
    device ships ONLY its own shard — per-link transfer bytes scale
    down with the mesh — and the shards reassemble into one contiguous
    host ndarray, so the host copy is layout-free and can later be
    ``put_from_host`` under ANY sharding. Used by the serving engine
    to demote prefix-KV rows into the host tier."""
    import numpy as np

    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def put_from_host(tree, sharding=None):
    """The reverse move: one async ``device_put`` per leaf, started
    immediately and overlapped with whatever the caller does next
    (the engine starts it while the request still waits in the
    admission queue). With ``sharding`` (e.g. the KV pool's heads-
    sharded NamedSharding) each device receives ONLY its shard slice.
    Returns the (possibly still in-flight) device tree."""
    if sharding is None:
        return jax.tree.map(jax.device_put, tree)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def kv_pool_sharding(mesh, num_kv_heads: int,
                     model_axis: str = "model") -> NamedSharding:
    """NamedSharding for ``TransformerLM.init_cache`` pool buffers,
    validating that the KV head count divides the model-axis size (an
    uneven head split would leave ragged shards and break the
    zero-communication cache-write layout)."""
    if model_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh axes {tuple(mesh.axis_names)} have no "
            f"{model_axis!r} axis to shard KV heads over")
    shards = int(mesh.shape[model_axis])
    if num_kv_heads % shards != 0:
        raise ValueError(
            f"num_kv_heads ({num_kv_heads}) must divide evenly over "
            f"the {shards}-way {model_axis!r} mesh axis; choose a "
            f"mesh the head count divides or bring more KV heads")
    return NamedSharding(mesh, kv_pool_spec(model_axis))
