"""Tensor-parallel sharding rules (GSPMD).

No reference analog — the reference's only parallelism is data parallel
(SURVEY.md §2.5). TPU-native TP is expressed as NamedSharding annotations
on the params pytree: jit/GSPMD then inserts the all-gathers/reduce-
scatters over ICI (scaling-book recipe: pick a mesh, annotate shardings,
let XLA place collectives).

``spec_for_params(params, rules)`` maps dotted param paths to
PartitionSpecs by first-match regex; ``transformer_tp_rules`` implements
the Megatron-style column/row split for the transformer stack:
  qkv / fc1  (out, in)  -> shard dim 0 (column parallel)
  out_proj / fc2        -> shard dim 1 (row parallel)
  tok_embed  (vocab, d) -> shard dim 0
  everything else       -> replicated
"""

from __future__ import annotations

import re
from typing import List, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def tree_paths(params, prefix=""):
    if isinstance(params, dict):
        for k, v in params.items():
            yield from tree_paths(v, f"{prefix}/{k}")
    else:
        yield prefix, params


def spec_for_params(params, rules: List[Tuple[str, P]], default: P = P()):
    """Pytree of PartitionSpec matching ``params``; first regex match wins."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def build(sub, prefix):
        if isinstance(sub, dict):
            return {k: build(v, f"{prefix}/{k}") for k, v in sub.items()}
        for pat, spec in compiled:
            if pat.search(prefix):
                return spec
        return default

    return build(params, "")


def transformer_tp_rules(model_axis: str = "model", data_axis: str = None):
    """Megatron-style rules for TransformerLM param paths. Pass ``data_axis``
    to additionally FSDP-shard the replicated leaves' first dim (zero-style)."""
    mp = model_axis
    rules = [
        (r"attn/qkv/~params/weight$", P(mp, None)),
        (r"attn/qkv/~params/bias$", P(mp)),
        (r"fc1/~params/weight$", P(mp, None)),
        (r"fc1/~params/bias$", P(mp)),
        (r"attn/out_proj/~params/weight$", P(None, mp)),
        (r"fc2/~params/weight$", P(None, mp)),
        (r"~params/tok_embed$", P(mp, None)),
        (r"head/~params/weight$", P(mp, None)),
    ]
    return rules


def shard_params(params, mesh, rules, default=P()):
    """device_put every leaf with its NamedSharding. (Manual walk:
    PartitionSpec is itself a pytree, so jax.tree.map would descend into it.)"""
    specs = spec_for_params(params, rules, default)

    def walk(p, s):
        if isinstance(p, dict):
            return {k: walk(v, s[k]) for k, v in p.items()}
        return jax.device_put(p, NamedSharding(mesh, s))

    return walk(params, specs)
