"""Flat-parameter collectives — the TPU-native AllReduceParameter.

Reference: parameters/AllReduceParameter.scala:84 — the model's flat
parameter vector is sliced into one chunk per executor; each iteration does
putGradients (FP16-compressed scatter) → aggregateGradientPartition (sum/N)
→ optimizer update on the owned slice → sendWeightPartition / getWeights
(all-gather). That algorithm IS reduce_scatter + shard-update + all_gather,
so here it is expressed directly with XLA collectives over ICI inside
``shard_map`` (SURVEY.md §2.5 "TPU-native equivalent").

The reference's "FP16" wire format keeps the upper 16 bits of the float32
pattern (parameters/FP16CompressedTensor.scala:270-278) — i.e. bfloat16
truncation, TPU's native dtype — reproduced by ``compress_dtype=bfloat16``.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def flatten_params(params) -> Tuple[jnp.ndarray, Any]:
    """Pytree → (flat 1-D vector, spec) (≙ getParameters flattening,
    nn/abstractnn/AbstractModule.scala:963)."""
    leaves, treedef = jax.tree.flatten(params)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves]) \
        if leaves else jnp.zeros((0,), jnp.float32)
    return flat, (treedef, shapes, dtypes, sizes)


def unflatten_params(flat: jnp.ndarray, spec) -> Any:
    treedef, shapes, dtypes, sizes = spec
    leaves = []
    off = 0
    for shape, dtype, size in zip(shapes, dtypes, sizes):
        leaves.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(treedef, leaves)


def pad_to_multiple(flat: jnp.ndarray, n: int) -> Tuple[jnp.ndarray, int]:
    """Pad so the vector splits evenly into n slices (the reference instead
    gives the last partition the remainder, AllReduceParameter.scala:84)."""
    size = flat.shape[0]
    padded = (size + n - 1) // n * n
    if padded != size:
        flat = jnp.concatenate([flat, jnp.zeros((padded - size,), flat.dtype)])
    return flat, padded


def compress(t: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """≙ FP16CompressedTensor.compress — bf16 truncation of f32."""
    return t.astype(dtype)


def decompress(t: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    return t.astype(dtype)


class AllReduceParameter:
    """Sharded flat-parameter update executed inside ``shard_map`` over a
    mesh axis. Each device owns flat_size/axis_size contiguous elements
    (≙ one executor's weightPartition)."""

    def __init__(self, axis_name: str = "data", compress_dtype=jnp.bfloat16):
        self.axis_name = axis_name
        self.compress_dtype = compress_dtype

    def aggregate(self, local_grad_flat: jnp.ndarray) -> jnp.ndarray:
        """putGradients + aggregateGradientPartition: reduce_scatter of the
        (compressed) gradient; returns this device's owned slice, already
        averaged over the axis (÷N, AllReduceParameter.scala:269).

        The ``named_scope`` tags the collective's HLO so per-op profiles
        (xprof) attribute all-reduce time to this phase — the device-side
        half of the observability story (host spans can't see inside one
        XLA dispatch)."""
        with jax.named_scope("bigdl/grad_reduce_scatter"):
            n = jax.lax.psum(1, self.axis_name)
            g = compress(local_grad_flat, self.compress_dtype) \
                if self.compress_dtype is not None else local_grad_flat
            owned = jax.lax.psum_scatter(g, self.axis_name, tiled=True)
            return decompress(owned) / n

    def all_gather_weights(self, owned_slice: jnp.ndarray) -> jnp.ndarray:
        """sendWeightPartition + getWeights: republish the updated owned
        slice and gather the full vector (AllReduceParameter.scala:193-220,
        307-320)."""
        with jax.named_scope("bigdl/weight_all_gather"):
            w = compress(owned_slice, self.compress_dtype) \
                if self.compress_dtype is not None else owned_slice
            full = jax.lax.all_gather(w, self.axis_name, tiled=True)
            return decompress(full)
