"""Mixture-of-Experts with expert parallelism (the 'ep' in dp/tp/pp/sp/ep).

No reference analog (SURVEY.md §2.5: the reference is DP-only) — this is
beyond-parity capability from the driver contract. The formulation is the
GShard/Mesh-TensorFlow dense-dispatch recipe, which is the TPU-native way
to route: top-1 gating builds a (tokens, experts, capacity) one-hot
dispatch tensor and routing becomes einsums (MXU work, static shapes)
instead of gather/scatter. Tokens over capacity are dropped (output 0 for
the expert contribution), the standard trade.

Expert parallelism: inside ``shard_map`` over an 'expert' axis, each
device holds E/n experts and T/n tokens; ``moe_spmd`` dispatches with
``lax.all_to_all`` (source-shard buffers travel to the expert's owner and
back), the canonical MoE comm pattern over ICI.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as bt_init
from bigdl_tpu.nn.module import Module


def _top1_dispatch(gates, capacity):
    """gates (T, E) -> (dispatch (T, E, C) one-hot, combine (T, E, C)).

    Position within an expert's buffer = rank of the token among tokens
    routed to that expert (in token order); tokens past capacity drop."""
    t, e = gates.shape
    expert = jnp.argmax(gates, axis=1)                     # (T,)
    onehot = jax.nn.one_hot(expert, e, dtype=gates.dtype)  # (T, E)
    # position of each token in its expert's buffer (exclusive cumsum)
    pos = jnp.cumsum(onehot, axis=0) - onehot              # (T, E)
    pos = jnp.sum(pos * onehot, axis=1).astype(jnp.int32)  # (T,)
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity),
                            capacity, dtype=gates.dtype)   # (T, C)
    dispatch = onehot[:, :, None] * pos_oh[:, None, :]     # (T, E, C)
    gate_val = jnp.sum(gates * onehot, axis=1)             # (T,)
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine


class MoEMLP(Module):
    """Top-1 gated mixture of expert MLPs (GELU, (D -> H -> D) each).

    Eager/jit path runs all experts dense (dispatch einsums); inside
    ``shard_map`` over ``expert_parallel`` the experts and tokens are
    sharded and dispatch goes through all_to_all (``moe_spmd``)."""

    def __init__(self, embed_dim: int, hidden_dim: int, n_experts: int,
                 capacity_factor: float = 1.25,
                 expert_parallel: Optional[str] = None):
        super().__init__()
        self.embed_dim, self.hidden_dim = embed_dim, hidden_dim
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.expert_parallel = expert_parallel
        xav = bt_init.Xavier()
        self.register_parameter("gate_w", xav((embed_dim, n_experts),
                                              fan_in=embed_dim,
                                              fan_out=n_experts))
        self.register_parameter(
            "w1", jnp.stack([xav((embed_dim, hidden_dim), fan_in=embed_dim,
                                 fan_out=hidden_dim)
                             for _ in range(n_experts)]))
        self.register_parameter("b1", jnp.zeros((n_experts, hidden_dim)))
        self.register_parameter(
            "w2", jnp.stack([xav((hidden_dim, embed_dim), fan_in=hidden_dim,
                                 fan_out=embed_dim)
                             for _ in range(n_experts)]))
        self.register_parameter("b2", jnp.zeros((n_experts, embed_dim)))

    #: Switch-style load-balancing loss from the LAST forward: add
    #: ``moe.l_aux`` (times a small coefficient) to the training objective
    #: to keep experts from collapsing. Computed from gates + the pre-
    #: capacity top-1 assignment, so it is identical in dense and spmd
    #: modes. Read it INSIDE the same trace/loss function that called
    #: forward (the intended use); after a jitted step returns, the stashed
    #: value is a dead tracer — rerun forward eagerly to refresh it.
    l_aux = 0.0

    def _aux_loss(self, gates):
        me = jnp.mean(gates, axis=0)             # mean gate prob per expert
        assign = jax.nn.one_hot(jnp.argmax(gates, axis=1), self.n_experts,
                                dtype=gates.dtype)
        ce = jnp.mean(assign, axis=0)            # fraction routed per expert
        return self.n_experts * jnp.sum(me * ce)

    def expert_params(self) -> dict:
        """The expert-sharded params (leading dim = expert) as a dict —
        shard these over the 'expert' axis for ``moe_spmd``."""
        return {"w1": self.w1, "b1": self.b1, "w2": self.w2, "b2": self.b2}

    def forward_with_aux(self, input):
        """(output, l_aux) WITHOUT the ``self.l_aux`` side-channel stash —
        use this inside ``jax.checkpoint``/remat regions, where a stashed
        inner tracer would outlive its trace and break clone/save later."""
        x = input
        shp = x.shape
        x2 = x.reshape(-1, self.embed_dim)
        t = x2.shape[0]
        gates = jax.nn.softmax(
            (x2 @ self.gate_w.astype(x2.dtype)).astype(jnp.float32), axis=-1)
        aux = self._aux_loss(gates)
        if self.expert_parallel is not None:
            out = moe_spmd(self.expert_params(), x2, gates,
                           self.expert_parallel, self.capacity_factor)
            return out.reshape(shp).astype(x.dtype), aux
        capacity = max(1, math.ceil(t / self.n_experts
                                    * self.capacity_factor))
        dispatch, combine = _top1_dispatch(gates, capacity)
        dispatch = dispatch.astype(x2.dtype)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x2)
        expert_out = _expert_fwd(self.expert_params(), expert_in)
        out = jnp.einsum("ecd,tec->td", expert_out,
                         combine.astype(expert_out.dtype))
        return out.reshape(shp).astype(x.dtype), aux

    def forward(self, input):
        out, aux = self.forward_with_aux(input)
        self.l_aux = aux
        return out


def _expert_fwd(p: dict, inp):
    """inp (E, C, D) -> (E, C, D): every expert's GELU MLP on its buffer."""
    h = jnp.einsum("ecd,edh->ech", inp, p["w1"]) + p["b1"][:, None]
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, p["w2"]) + p["b2"][:, None]


def moe_spmd(expert_params: dict, x2, gates, axis_name: str,
             capacity_factor: float = 1.25):
    """Expert-parallel dispatch inside shard_map over ``axis_name``.

    Device layout: tokens sharded (x2 is this device's (T/n, D) shard),
    experts sharded (``expert_params``' leading expert dim is the local
    E/n slice; global expert i lives on device i // (E/n)). Dispatch
    buffers (E, C, D) are built locally against ALL global experts, then
    ``all_to_all`` re-shards from expert-major to source-major so each
    device computes its own experts over every source's tokens; the
    reverse all_to_all brings results home."""
    n = lax.psum(1, axis_name)
    t_local = x2.shape[0]
    e_global = gates.shape[1]
    if e_global % n:
        raise ValueError(
            f"n_experts {e_global} not divisible by the {axis_name!r} axis "
            f"size {n}")
    e_local = e_global // n
    capacity = max(1, math.ceil(t_local / e_global * capacity_factor))
    dispatch, combine = _top1_dispatch(gates, capacity)
    dispatch = dispatch.astype(x2.dtype)
    # (T/n, E, C) x (T/n, D) -> (E, C, D): buffers for every global expert
    buf = jnp.einsum("tec,td->ecd", dispatch, x2)
    buf = buf.reshape(n, e_local, capacity, buf.shape[-1])
    # exchange: device d receives the buffers targeting ITS experts from
    # every source shard -> (n_src, e_local, C, D)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    inp = jnp.moveaxis(buf, 0, 1).reshape(e_local, n * capacity, -1)
    out = _expert_fwd(expert_params, inp)
    out = jnp.moveaxis(out.reshape(e_local, n, capacity, -1), 1, 0)
    # send results back to the token owners
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(e_global, capacity, -1)
    return jnp.einsum("ecd,tec->td", out, combine.astype(out.dtype))
