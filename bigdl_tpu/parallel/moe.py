"""Mixture-of-Experts with expert parallelism (the 'ep' in dp/tp/pp/sp/ep).

No reference analog (SURVEY.md §2.5: the reference is DP-only) — this is
beyond-parity capability from the driver contract. The formulation is the
GShard/Mesh-TensorFlow dense-dispatch recipe, which is the TPU-native way
to route: top-1 (Switch) or top-2 (GShard) gating builds a
(tokens, experts, capacity) one-hot dispatch tensor and routing becomes
einsums (MXU work, static shapes) instead of gather/scatter. Tokens over
capacity are dropped (output 0 for the expert contribution), the standard
trade; the drop rate and per-expert load are exposed as routing stats
(``record_moe_metrics``).

Expert parallelism: inside ``shard_map`` over an 'expert' axis, each
device holds E/n experts and T/n tokens; ``moe_spmd`` dispatches with
``lax.all_to_all`` (source-shard buffers travel to the expert's owner and
back), the canonical MoE comm pattern over ICI.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as bt_init
from bigdl_tpu.nn.module import Module


def _topk_dispatch(gates, capacity, k: int = 1):
    """gates (T, E) -> (dispatch (T, E, C), combine (T, E, C), stats).

    GShard sequential assignment: choice j's positions within an expert's
    buffer start after ALL of choice j-1's assignments to that expert
    (GShard alg. 1); within a choice, position = rank of the token among
    tokens routed to that expert in token order. Tokens past capacity drop.
    For k > 1 the combine weights are the chosen gate probs normalized over
    the kept choices; for k == 1 they are the raw gate prob (Switch).

    stats: ``drop_rate`` (fraction of (token, choice) routes dropped) and
    ``expert_fraction`` (E,) (fraction of routes per expert, pre-drop)."""
    t, e = gates.shape
    remaining = gates
    counts = jnp.zeros((e,), gates.dtype)
    disps, weights = [], []
    kept_total = jnp.zeros((), gates.dtype)
    expert_fraction = jnp.zeros((e,), gates.dtype)
    for j in range(k):
        expert = jnp.argmax(remaining, axis=1)                 # (T,)
        onehot = jax.nn.one_hot(expert, e, dtype=gates.dtype)  # (T, E)
        if j > 0:
            # a saturated router can underflow every non-top gate to 0.0;
            # argmax would then re-pick arbitrarily — void such phantom
            # routes so they neither occupy capacity nor skew the stats
            valid = jnp.sum(remaining * onehot, axis=1) > 0
            onehot = onehot * valid[:, None].astype(gates.dtype)
        # position in the expert's buffer (exclusive cumsum + choice offset)
        pos = jnp.cumsum(onehot, axis=0) - onehot + counts[None, :]
        pos = jnp.sum(pos * onehot, axis=1).astype(jnp.int32)  # (T,)
        routed = jnp.sum(onehot, axis=1) > 0                   # (T,)
        keep = (pos < capacity) & routed
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity),
                                capacity, dtype=gates.dtype)   # (T, C)
        disps.append(onehot[:, :, None] * pos_oh[:, None, :])  # (T, E, C)
        weights.append(jnp.sum(gates * onehot, axis=1)
                       * keep.astype(gates.dtype))
        counts = counts + jnp.sum(onehot, axis=0)
        kept_total = kept_total + jnp.sum(keep.astype(gates.dtype))
        expert_fraction = expert_fraction + jnp.mean(onehot, axis=0) / k
        remaining = remaining * (1.0 - onehot)
    dispatch = sum(disps)
    if k == 1:
        combine = disps[0] * weights[0][:, None, None]
    else:
        denom = sum(weights) + 1e-9
        combine = sum(d * (w / denom)[:, None, None]
                      for d, w in zip(disps, weights))
    stats = {"drop_rate": 1.0 - kept_total / (t * k),
             "expert_fraction": expert_fraction}
    return dispatch, combine, stats


def _top1_dispatch(gates, capacity):
    """Back-compat wrapper: top-1 (Switch) routing."""
    dispatch, combine, _ = _topk_dispatch(gates, capacity, 1)
    return dispatch, combine


class MoEMLP(Module):
    """Top-k gated mixture of expert MLPs (GELU, (D -> H -> D) each);
    ``n_top=1`` is Switch routing, ``n_top=2`` the GShard recipe with
    normalized combine weights.

    Eager/jit path runs all experts dense (dispatch einsums); inside
    ``shard_map`` over ``expert_parallel`` the experts and tokens are
    sharded and dispatch goes through all_to_all (``moe_spmd``)."""

    def __init__(self, embed_dim: int, hidden_dim: int, n_experts: int,
                 capacity_factor: float = 1.25,
                 expert_parallel: Optional[str] = None, n_top: int = 1):
        super().__init__()
        if n_top < 1 or n_top > n_experts:
            raise ValueError(f"n_top={n_top} must be in [1, {n_experts}]")
        self.embed_dim, self.hidden_dim = embed_dim, hidden_dim
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.expert_parallel = expert_parallel
        self.n_top = n_top
        xav = bt_init.Xavier()
        self.register_parameter("gate_w", xav((embed_dim, n_experts),
                                              fan_in=embed_dim,
                                              fan_out=n_experts))
        self.register_parameter(
            "w1", jnp.stack([xav((embed_dim, hidden_dim), fan_in=embed_dim,
                                 fan_out=hidden_dim)
                             for _ in range(n_experts)]))
        self.register_parameter("b1", jnp.zeros((n_experts, hidden_dim)))
        self.register_parameter(
            "w2", jnp.stack([xav((hidden_dim, embed_dim), fan_in=hidden_dim,
                                 fan_out=embed_dim)
                             for _ in range(n_experts)]))
        self.register_parameter("b2", jnp.zeros((n_experts, embed_dim)))

    #: Switch-style load-balancing loss from the LAST forward: add
    #: ``moe.l_aux`` (times a small coefficient) to the training objective
    #: to keep experts from collapsing. Computed from gates + the pre-
    #: capacity top-1 assignment, so it is identical in dense and spmd
    #: modes. Read it INSIDE the same trace/loss function that called
    #: forward (the intended use); after a jitted step returns, the stashed
    #: value is a dead tracer — rerun forward eagerly to refresh it.
    l_aux = 0.0

    #: Routing stats from the last eager forward (``forward_with_stats``
    #: returns them explicitly for jitted steps): drop_rate scalar +
    #: expert_fraction (E,). Feed to ``record_moe_metrics``.
    last_stats = None

    def _aux_loss(self, gates):
        me = jnp.mean(gates, axis=0)             # mean gate prob per expert
        assign = jax.nn.one_hot(jnp.argmax(gates, axis=1), self.n_experts,
                                dtype=gates.dtype)
        ce = jnp.mean(assign, axis=0)            # fraction routed per expert
        return self.n_experts * jnp.sum(me * ce)

    def expert_params(self) -> dict:
        """The expert-sharded params (leading dim = expert) as a dict —
        shard these over the 'expert' axis for ``moe_spmd``."""
        return {"w1": self.w1, "b1": self.b1, "w2": self.w2, "b2": self.b2}

    def forward_with_stats(self, input):
        """(output, l_aux, stats) WITHOUT any side-channel stash — safe
        inside ``jax.checkpoint``/remat regions, where a stashed inner
        tracer would outlive its trace and break clone/save later.
        stats: drop_rate scalar + expert_fraction (E,) — feed to
        ``record_moe_metrics`` outside the jitted step."""
        x = input
        shp = x.shape
        x2 = x.reshape(-1, self.embed_dim)
        t = x2.shape[0]
        gates = jax.nn.softmax(
            (x2 @ self.gate_w.astype(x2.dtype)).astype(jnp.float32), axis=-1)
        aux = self._aux_loss(gates)
        if self.expert_parallel is not None:
            # moe_spmd derives its own capacity from the LOCAL token count
            out, stats = moe_spmd(self.expert_params(), x2, gates,
                                  self.expert_parallel, self.capacity_factor,
                                  n_top=self.n_top, with_stats=True)
            return out.reshape(shp).astype(x.dtype), aux, stats
        capacity = max(1, math.ceil(self.n_top * t / self.n_experts
                                    * self.capacity_factor))
        dispatch, combine, stats = _topk_dispatch(gates, capacity, self.n_top)
        dispatch = dispatch.astype(x2.dtype)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x2)
        expert_out = _expert_fwd(self.expert_params(), expert_in)
        out = jnp.einsum("ecd,tec->td", expert_out,
                         combine.astype(expert_out.dtype))
        return out.reshape(shp).astype(x.dtype), aux, stats

    def forward_with_aux(self, input):
        """(output, l_aux) — see forward_with_stats."""
        out, aux, _ = self.forward_with_stats(input)
        return out, aux

    def forward(self, input):
        out, aux, stats = self.forward_with_stats(input)
        self.l_aux = aux
        self.last_stats = stats
        return out


def _expert_fwd(p: dict, inp):
    """inp (E, C, D) -> (E, C, D): every expert's GELU MLP on its buffer."""
    h = jnp.einsum("ecd,edh->ech", inp, p["w1"]) + p["b1"][:, None]
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, p["w2"]) + p["b2"][:, None]


def record_moe_metrics(metrics, stats, prefix: str = "moe") -> None:
    """Publish routing stats from the last (eager or returned) forward into
    an ``optim.metrics.Metrics`` table: drop rate + max expert fraction
    (1/E is perfectly balanced).

    These are dimensionless fractions — read them back with
    ``metrics.get(name)[0]``; ``Metrics.summary()`` assumes nanosecond
    timings and would scale them into nonsense."""
    metrics.set(f"{prefix} drop rate", float(stats["drop_rate"]))
    metrics.set(f"{prefix} max expert fraction",
                float(jnp.max(stats["expert_fraction"])))


def moe_spmd(expert_params: dict, x2, gates, axis_name: str,
             capacity_factor: float = 1.25, n_top: int = 1,
             with_stats: bool = False):
    """Expert-parallel dispatch inside shard_map over ``axis_name``.

    Device layout: tokens sharded (x2 is this device's (T/n, D) shard),
    experts sharded (``expert_params``' leading expert dim is the local
    E/n slice; global expert i lives on device i // (E/n)). Dispatch
    buffers (E, C, D) are built locally against ALL global experts, then
    ``all_to_all`` re-shards from expert-major to source-major so each
    device computes its own experts over every source's tokens; the
    reverse all_to_all brings results home."""
    n = lax.psum(1, axis_name)
    t_local = x2.shape[0]
    e_global = gates.shape[1]
    if e_global % n:
        raise ValueError(
            f"n_experts {e_global} not divisible by the {axis_name!r} axis "
            f"size {n}")
    e_local = e_global // n
    capacity = max(1, math.ceil(n_top * t_local / e_global * capacity_factor))
    dispatch, combine, stats = _topk_dispatch(gates, capacity, n_top)
    dispatch = dispatch.astype(x2.dtype)
    # (T/n, E, C) x (T/n, D) -> (E, C, D): buffers for every global expert
    buf = jnp.einsum("tec,td->ecd", dispatch, x2)
    buf = buf.reshape(n, e_local, capacity, buf.shape[-1])
    # exchange: device d receives the buffers targeting ITS experts from
    # every source shard -> (n_src, e_local, C, D)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    inp = jnp.moveaxis(buf, 0, 1).reshape(e_local, n * capacity, -1)
    out = _expert_fwd(expert_params, inp)
    out = jnp.moveaxis(out.reshape(e_local, n, capacity, -1), 1, 0)
    # send results back to the token owners
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
    out = out.reshape(e_global, capacity, -1)
    res = jnp.einsum("ecd,tec->td", out, combine.astype(out.dtype))
    if with_stats:
        # average routing stats over the token shards
        stats = {"drop_rate": lax.pmean(stats["drop_rate"], axis_name),
                 "expert_fraction": lax.pmean(stats["expert_fraction"],
                                              axis_name)}
        return res, stats
    return res
