"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

No reference analog (the reference's only parallelism is synchronous data
parallelism, SURVEY.md §2.5) — this is beyond-parity capability from the
driver contract (tp/pp/dp/sp/ep). Design is the standard SPMD pipelining
recipe (scaling-book "pipelining" chapter shape): every device holds ONE
stage's parameters (a shard of a stacked params pytree), activations
rotate down the ring via ``lax.ppermute`` once per tick, and a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks drives the schedule.
Bubbles are computed-but-masked (SPMD lockstep; the same trade every
GPipe implementation makes). Autodiff flows through scan + ppermute, so
jax.grad of a pipelined loss is the correct pipelined backward.

Use inside ``shard_map`` over the pipe axis:

    stacked = stack_stage_params([blk.params_dict() for blk in blocks])
    # shard stacked over 'pipe' (leading stage dim), x replicated
    y = pipeline_spmd(stage_fn, my_stage_params, x, 'pipe', n_micro)

Constraint: every stage must map activations to the SAME shape/dtype
(true for transformer blocks, the realistic pipeline workload).
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(stage_params: List):
    """Stack S same-structure pytrees into one pytree with a leading stage
    dim — shard that dim over the pipe axis so each device holds its stage."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)


def pipeline_spmd(stage_fn: Callable, my_params, x, axis_name: str,
                  n_microbatches: int, remat: bool = False):
    """Run the pipelined forward inside shard_map.

    ``stage_fn(params, x_micro) -> y_micro`` is one stage; ``my_params`` is
    this device's stage params (the shard_map-sliced stage dim, squeezed or
    not — a leading dim of 1 is squeezed here); ``x`` is the full
    (replicated) batch (B, ...); returns the full (B, ...) output, valid on
    every device (masked psum broadcast from the last stage).

    ``remat=True`` wraps each tick's stage computation in
    ``jax.checkpoint``: the pipelined backward then stores only the
    per-tick carries and recomputes stage internals — the activation-
    memory profile 1F1B schedules exist for (peak stage-activation
    memory O(1) per live microbatch instead of every intermediate of
    every tick), traded for one extra forward per tick.
    """
    s = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = n_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by n_microbatches {m}")
    my_params = jax.tree.map(
        lambda a: a[0] if a.ndim and a.shape[0] == 1 else a, my_params)
    xm = x.reshape((m, b // m) + x.shape[1:])

    # stage i sends to i+1; the wrap-around edge feeds stage 0, which
    # ignores it (selects the fresh microbatch instead)
    perm = [(i, (i + 1) % s) for i in range(s)]
    ticks = m + s - 1
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def tick(carry, t):
        buf, ys = carry
        x_t = lax.dynamic_index_in_dim(xm, jnp.clip(t, 0, m - 1), 0,
                                       keepdims=False)
        feed = jnp.where(t < m, x_t, jnp.zeros_like(x_t))
        inp = jnp.where(idx == 0, feed, buf)
        out = stage_fn(my_params, inp)
        # collect the microbatch leaving the LAST stage at this tick
        mb = t - (s - 1)
        valid = jnp.logical_and(mb >= 0, jnp.logical_and(mb < m, idx == s - 1))
        upd = lax.dynamic_update_index_in_dim(
            ys, out, jnp.clip(mb, 0, m - 1), 0)
        ys = jnp.where(valid, upd, ys)
        return (lax.ppermute(out, axis_name, perm), ys), None

    probe = jax.eval_shape(stage_fn, my_params, xm[0])
    # the carry is device-varying (each device holds different activations):
    # mark it so under shard_map's manual-axes tracking
    buf0 = lax.pcast(jnp.zeros(probe.shape, probe.dtype), (axis_name,),
                     to="varying")
    ys0 = lax.pcast(jnp.zeros((m,) + probe.shape, probe.dtype), (axis_name,),
                    to="varying")
    (_, ys), _ = lax.scan(tick, (buf0, ys0), jnp.arange(ticks))
    # broadcast the last stage's collected outputs to every device
    ys = lax.psum(jnp.where(idx == s - 1, ys, jnp.zeros_like(ys)), axis_name)
    return ys.reshape((b,) + ys.shape[2:])
