"""Runtime engine: device/mesh discovery and global config.

Reference: utils/Engine.scala:41 — detects executor count/cores from
SparkConf for every cluster manager (Engine.scala:460-541), owns thread
pools, checks required conf, and switches engine type. TPU-native redesign:

- "executors" ≙ JAX processes (one per TPU host, ``jax.process_count()``),
  "cores per executor" ≙ local devices (``jax.local_device_count()``);
- the thread pools are absorbed by XLA's async dispatch + the host input
  pipeline (bigdl_tpu.dataset prefetch);
- the engine-type switch (MklBlas/MklDnn) maps to dtype/backend policy
  (float32 vs bfloat16 compute on the MXU);
- ``Engine.init`` ≙ jax.distributed.initialize for multi-host pods
  (SURVEY.md §2.5 "control plane"), a no-op single-host.

Config tiers mirror the reference's ``bigdl.*`` system properties
(SURVEY.md §5 "Config / flag system") as ``BIGDL_TPU_*`` env vars.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger("bigdl_tpu.engine")


class EngineType:
    """≙ MklBlas / MklDnn switch (utils/Engine.scala:35-47): on TPU the
    analogous choice is the compute dtype policy fed to the MXU."""

    FLOAT32 = "float32"
    BFLOAT16 = "bfloat16"


class Engine:
    _initialized = False
    _mesh: Optional[Mesh] = None
    _engine_type = os.environ.get("BIGDL_TPU_ENGINE_TYPE", EngineType.FLOAT32)

    @classmethod
    def init(cls, coordinator_address: Optional[str] = None,
             num_processes: Optional[int] = None,
             process_id: Optional[int] = None) -> None:
        """≙ Engine.init (utils/Engine.scala:105-118). Multi-host: wires the
        JAX distributed runtime (one controller per TPU host ≙ one executor
        JVM per Spark node); single-host: records devices."""
        if cls._initialized:
            return  # singleton-per-process (≙ Engine.checkSingleton, Engine.scala:248)
        if coordinator_address is not None:
            jax.distributed.initialize(coordinator_address, num_processes, process_id)
        cls._initialized = True
        logger.info(
            "Engine.init: %d process(es), %d local device(s), platform=%s",
            cls.node_number(), jax.local_device_count(),
            jax.devices()[0].platform)
        from bigdl_tpu import observability as obs

        # one-shot topology gauges: forced past the disable switch —
        # init runs once, and a later enable() must not read frozen zeros
        ins = obs.engine_instruments()
        ins.processes.set(cls.node_number(), force=True)
        ins.local_devices.set(jax.local_device_count(), force=True)
        ins.total_devices.set(jax.device_count(), force=True)

    @classmethod
    def node_number(cls) -> int:
        """≙ Engine.nodeNumber (executor count)."""
        return jax.process_count()

    @classmethod
    def core_number(cls) -> int:
        """≙ Engine.coreNumber (cores per executor → local chips per host)."""
        return jax.local_device_count()

    @classmethod
    def total_devices(cls) -> int:
        return jax.device_count()

    @classmethod
    def get_engine_type(cls) -> str:
        return cls._engine_type

    @classmethod
    def set_engine_type(cls, t: str) -> None:
        cls._engine_type = t

    @classmethod
    def compute_dtype(cls):
        import jax.numpy as jnp

        return jnp.bfloat16 if cls._engine_type == EngineType.BFLOAT16 else jnp.float32

    # ------------------------------------------------------------------ mesh
    @classmethod
    def create_mesh(cls, axes: Optional[Sequence[Tuple[str, int]]] = None,
                    devices=None) -> Mesh:
        """Build the device mesh that replaces cluster topology discovery
        (utils/Engine.scala:460-541). Default: all devices on one ``data``
        axis (the reference's only parallelism is data parallel, SURVEY.md
        §2.5). Pass axes like [("data", 4), ("model", 2)] for dp×tp."""
        devices = devices if devices is not None else jax.devices()
        if axes is None:
            axes = [("data", len(devices))]
        names = [a for a, _ in axes]
        sizes = [s for _, s in axes]
        if int(np.prod(sizes)) != len(devices):
            raise ValueError(
                f"mesh axes {axes} do not cover {len(devices)} devices")
        dev_array = np.asarray(devices).reshape(sizes)
        return Mesh(dev_array, names)

    @classmethod
    def default_mesh(cls) -> Mesh:
        if cls._mesh is None:
            cls._mesh = cls.create_mesh()
        return cls._mesh

    @classmethod
    def set_default_mesh(cls, mesh: Mesh) -> None:
        cls._mesh = mesh

    @classmethod
    def reset(cls) -> None:
        cls._initialized = False
        cls._mesh = None
