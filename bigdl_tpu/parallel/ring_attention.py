"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

No reference analog (SURVEY.md §5: sequence parallelism is absent in the
reference) — this is the long-context capability the north star adds.
Design follows the public Ring Attention recipe (blockwise attention with
flash-style running softmax statistics; K/V blocks rotate around the ICI
ring via ``lax.ppermute``) and DeepSpeed-Ulysses (all-to-all swaps the
sharded axis from sequence to heads so each device runs full-sequence
attention on a head subset).

Both run inside ``shard_map`` over a mesh axis whose size divides the
sequence (ring) or heads (ulysses). Softmax statistics accumulate in f32
regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, scale, mask):
    """Unnormalized block attention: returns (o_block, row_sum, row_max)
    with f32 statistics. q:(B,H,Tq,D) k,v:(B,H,Tk,D) mask:(Tq,Tk) or None."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                      # (B,H,Tq)
    # guard fully-masked rows: exp(-inf - -inf) would be nan
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])           # (B,H,Tq,Tk) f32
    l = jnp.sum(p, axis=-1)                      # (B,H,Tq)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o.astype(jnp.float32), l, m_safe, jnp.isfinite(m)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, use_flash: bool = False,
                   interpret: Optional[bool] = None):
    """Blockwise ring attention inside shard_map.

    Each device holds one sequence block of Q/K/V (B, H, T/n, D). K/V
    rotate n-1 times around the ring; output accumulates with running
    (max, denom) flash statistics so the result equals full softmax
    attention over the whole sequence.

    ``use_flash=True`` computes each ring step's block attention with the
    pallas flash kernel (O(T_blk·block) memory instead of the dense
    (T_blk, T_blk) scores) and merges blocks in logsumexp space — the
    composition for long context ON TOP of sequence sharding. Requires
    the local block length to tile into the kernel blocks (otherwise the
    dense ring below is used, mirroring flash_attention's own fallback);
    gradients flow through a custom vjp carrying the lse cotangent. K/V
    may carry fewer (grouped-query) heads — the flash path rotates them
    UN-expanded (group-factor less ring traffic); the dense path expands."""
    if use_flash:
        from bigdl_tpu.ops.flash_attention import auto_block

        blk = min(auto_block(q.shape[2]), q.shape[2])
        if q.shape[2] % blk == 0:
            return _ring_attention_flash(q, k, v, axis_name, causal, scale,
                                         interpret, block=blk)
    if k.shape[1] != q.shape[1]:  # dense path needs materialized kv heads
        rep = q.shape[1] // k.shape[1]
        k, v = jnp.repeat(k, rep, 1), jnp.repeat(v, rep, 1)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    tq = q.shape[2]

    def local_mask(src_block):
        """(Tq, Tk) mask for attending my Q block to K block ``src_block``."""
        if not causal:
            return None
        # global positions: my block rows my*tq + i, source cols src*tk + j
        rows = my * tq + jnp.arange(tq)[:, None]
        cols = src_block * k.shape[2] + jnp.arange(k.shape[2])[None, :]
        return rows >= cols

    # accumulators (f32)
    o_acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    l_acc = jnp.zeros(q.shape[:3], jnp.float32)
    m_acc = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(carry, block):
        o_acc, l_acc, m_acc = carry
        o_b, l_b, m_b, valid = block
        # rows with no valid cols in this block contribute nothing
        m_b = jnp.where(valid, m_b, -jnp.inf)
        m_new = jnp.maximum(m_acc, m_b)
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        c_old = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new_safe), 0.0)
        c_b = jnp.where(valid, jnp.exp(m_b - m_new_safe), 0.0)
        o_new = o_acc * c_old[..., None] + o_b * c_b[..., None]
        l_new = l_acc * c_old + l_b * c_b
        return o_new, l_new, m_new

    def step(t, carry):
        o_acc, l_acc, m_acc, k_cur, v_cur = carry
        src = (my - t) % n  # block id currently held after t rotations
        if causal:
            # skip blocks strictly in the future (mask everything out)
            mask = local_mask(src)
        else:
            mask = None
        o_b, l_b, m_b, valid = _block_attend(q, k_cur, v_cur, scale, mask)
        o_acc, l_acc, m_acc = merge((o_acc, l_acc, m_acc),
                                    (o_b, l_b, m_b, valid))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_acc, l_acc, m_acc, k_nxt, v_nxt

    carry = (o_acc, l_acc, m_acc, k, v)
    # static python loop: n is a trace-time constant; XLA overlaps the
    # ppermute of step t+1 with the matmuls of step t
    for t in range(n):
        carry = step(t, carry)
    o_acc, l_acc, m_acc, _, _ = carry
    denom = jnp.where(l_acc > 0, l_acc, 1.0)
    return (o_acc / denom[..., None]).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float],
                          interpret: Optional[bool] = None,
                          block: Optional[int] = None):
    """Flash-kernel ring steps merged in logsumexp space. Per step the
    held K/V block is (relative to my Q block) strictly past -> full
    attention, diagonal -> causal, strictly future -> skipped; the three
    cases dispatch via lax.switch on the traced source-block id. GQA K/V
    (fewer heads) rotate un-expanded; the kernel reads shared heads via
    its group index map. ``block`` is the kernel block size the caller's
    tiling gate validated (ring_attention computes it via auto_block)."""
    from bigdl_tpu.ops.flash_attention import (auto_block, default_interpret,
                                               flash_with_lse)

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if block is None:
        block = min(auto_block(t), t)
    qf = q.reshape(b * h, t, d)
    if interpret is None:
        # host-platform default; cross-lowering (jax.export for TPU from a
        # CPU host) passes interpret=False explicitly for real Mosaic
        interpret = default_interpret()
    flash = partial(flash_with_lse, scale=scale, block_q=block,
                    block_k=block, interpret=interpret, group=group)

    def attend_full(k_cur, v_cur):
        o, lse = flash(qf, k_cur.reshape(b * h_kv, t, d),
                       v_cur.reshape(b * h_kv, t, d), causal=False)
        return o.astype(jnp.float32), lse[..., 0]

    def attend_diag(k_cur, v_cur):
        o, lse = flash(qf, k_cur.reshape(b * h_kv, t, d),
                       v_cur.reshape(b * h_kv, t, d), causal=True)
        return o.astype(jnp.float32), lse[..., 0]

    def attend_skip(k_cur, v_cur):
        return (jnp.zeros((b * h, t, d), jnp.float32),
                jnp.full((b * h, t), -jnp.inf, jnp.float32))

    o_acc = jnp.zeros((b * h, t, d), jnp.float32)
    lse_acc = jnp.full((b * h, t), -jnp.inf, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o_a, lse_a, o_b, lse_b):
        m = jnp.maximum(lse_a, lse_b)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        ca = jnp.where(jnp.isfinite(lse_a), jnp.exp(lse_a - m_safe), 0.0)
        cb = jnp.where(jnp.isfinite(lse_b), jnp.exp(lse_b - m_safe), 0.0)
        denom = ca + cb
        safe = jnp.maximum(denom, 1e-37)
        o = (o_a * ca[..., None] + o_b * cb[..., None]) / safe[..., None]
        lse = jnp.where(denom > 0, m_safe + jnp.log(safe), -jnp.inf)
        return o, lse

    k_cur, v_cur = k, v
    for step in range(n):
        src = (my - step) % n
        if causal:
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            o_b, lse_b = lax.switch(branch,
                                    [attend_full, attend_diag, attend_skip],
                                    k_cur, v_cur)
        else:
            o_b, lse_b = attend_full(k_cur, v_cur)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_b, lse_b)
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
    return o_acc.reshape(b, h, t, d).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None):
    """DeepSpeed-Ulysses style: all_to_all converts the sequence shard into
    a head shard, runs full-sequence attention locally, converts back.
    Requires num_heads % axis_size == 0."""
    n = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # (B, H, T/n, D) -> (B, H/n, T, D): device i keeps head-group i,
        # gathers every device's sequence block along time (source order ==
        # global order). tiled=True splits/concats in place.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        # (B, H/n, T, D) -> (B, H, T/n, D): inverse
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    from bigdl_tpu.nn.attention import dot_product_attention

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(oh)
