"""Distributed (SPMD) training loop.

Reference: optim/DistriOptimizer.scala:839 — THE distributed hot path
(SURVEY.md §3.1): per-iteration getWeights → thread-replica
forward/backward → putGradients → aggregateGradientPartition → per-slice
optimizer update → sendWeightPartition, all over Spark BlockManager.

TPU-native redesign: ONE jitted SPMD step over a ``jax.sharding.Mesh``.
Two parameter-sync modes:

- ``allreduce``: params replicated, batch sharded on the ``data`` axis;
  XLA inserts the gradient all-reduce over ICI. Simplest, fastest for
  small/medium models.
- ``sharded`` (default; the reference's exact algorithm, ZeRO-1 style):
  inside ``shard_map`` the flat gradient is reduce-scattered in bf16
  (≙ FP16-compressed putGradients), each device updates only its owned
  slice of the flat parameter/optimizer state (≙ weightPartition +
  optimMethod.optimize on the slice, DistriOptimizer.scala:343-373), then
  all-gathers updated weights (≙ getWeights). Optimizer slots are sharded
  → per-device memory scales down with mesh size.

Straggler dropping (DistriOptimizer.scala:243-247) has no SPMD equivalent —
lockstep collectives make it unnecessary (SURVEY.md §2.5); the fault story
is checkpoint/resume (utils/Engine + checkpoint triggers).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module, pure_apply
from bigdl_tpu.optim.optimizer import (
    Optimizer, LocalOptimizer, _clip_constant, _clip_by_global_norm, _mask_frozen,
)
from bigdl_tpu.parallel.all_reduce import (
    AllReduceParameter, flatten_params, unflatten_params, pad_to_multiple,
)
from bigdl_tpu.parallel.engine import Engine
from bigdl_tpu.utils import random as bt_random

logger = logging.getLogger("bigdl_tpu.optim")


class DistriOptimizer(LocalOptimizer):
    """Data-parallel SPMD optimizer (reference: optim/DistriOptimizer.scala)."""

    def set_gradient_accumulation(self, n_micro_batches: int):
        raise NotImplementedError(
            "gradient accumulation is local-optimizer only for now: the "
            "distributed step's batch axis is mesh-sharded, and an in-step "
            "micro-batch reshape would re-layout the shards; lower the "
            "per-device batch or grow the mesh instead")

    def __init__(self, *args, mesh: Optional[Mesh] = None,
                 parameter_sync: str = "sharded",
                 compress_dtype=jnp.bfloat16,
                 sync_batch_norm: bool = False,
                 log_interval: Optional[int] = None, **kw):
        super().__init__(*args, **kw)
        self.mesh = mesh if mesh is not None else Engine.default_mesh()
        if "data" not in self.mesh.axis_names:
            raise ValueError("mesh must have a 'data' axis for data parallelism")
        self.parameter_sync = parameter_sync
        self.compress_dtype = compress_dtype
        # Buffer semantics (≙ utils/ParameterSynchronizer.scala:29): by
        # default every data shard keeps its OWN running stats, like the
        # reference's thread-replicas; sync_batch_norm=True pmeans buffers
        # each step (the opt-in sync-BN path).
        self.sync_batch_norm = sync_batch_norm
        # Host-sync cadence: loss is fetched to host (a device→host sync
        # that serializes dispatch — expensive over thin links) only every
        # log_interval iterations (bigdl.log.interval; 1 = reference parity).
        # Loss-based Triggers see a value at most log_interval-1 iters stale.
        if log_interval is None:
            from bigdl_tpu.utils import config as bt_config
            log_interval = bt_config.get_int("bigdl.log.interval", 1)
        self.log_interval = max(1, int(log_interval))
        #: test/ops hook called once per iteration with the state dict —
        #: raising from it simulates a mid-training failure (≙ the
        #: reference's fault-injection specs, DistriOptimizerSpec)
        self._fault_hook = None
        self._restored_slots = None

    # ------------------------------------------------------------ step build
    def _build_sharded_step(self, model: Module, criterion, method, grad_clip,
                            slots_example):
        """The reference's exact algorithm as one shard_map'd XLA program."""
        apply_fn = pure_apply(model)
        mesh = self.mesh
        n_data = mesh.shape["data"]
        arp = AllReduceParameter("data", self.compress_dtype)
        trainable = model.trainable_dict()
        any_frozen = not all(
            t for t in jax.tree.leaves(trainable, is_leaf=lambda x: isinstance(x, bool)))

        def loss_fn(params, buffers, x, y, rng):
            out, new_buffers = apply_fn(params, buffers, x, rng=rng, training=True)
            loss = criterion.forward(out, y)
            loss = loss + model.regularization_loss(params)
            return loss, new_buffers

        sync_bn = self.sync_batch_norm

        def shard_step(params, buffers, flat_slice, slot_slice, x, y, lr, rng):
            # distinct rng per data shard (dropout masks differ per replica,
            # matching per-thread-replica behavior in the reference)
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            if not sync_bn:
                # per-shard stats arrive stacked (n_data, ...) sharded on
                # axis 0 → this shard's local slice has leading dim 1
                buffers = jax.tree.map(lambda b: b[0], buffers)
            (loss, new_buffers), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, buffers, x, y, rng)
            flat_grad, spec = flatten_params(grads)
            flat_grad, _ = pad_to_multiple(flat_grad, n_data)
            # reduce_scatter (bf16 wire) → owned slice, averaged
            owned_grad = arp.aggregate(flat_grad)
            # clipping operates on the AGGREGATED gradient, matching the
            # local path and the reference's ParameterProcessors which run
            # between aggregation and update (ParameterOperations.scala:33-124)
            if grad_clip:
                if "constant" in grad_clip:
                    lo, hi = grad_clip["constant"]
                    owned_grad = jnp.clip(owned_grad, lo, hi)
                if "l2norm" in grad_clip:
                    # global norm across the full (sharded) gradient — ≙
                    # L2NormClippingProcessor's cross-partition norm
                    sq = jax.lax.psum(jnp.sum(owned_grad ** 2), "data")
                    scale = jnp.minimum(1.0, grad_clip["l2norm"] / (jnp.sqrt(sq) + 1e-12))
                    owned_grad = owned_grad * scale
            # optimizer update on the owned slice only (ZeRO-1)
            new_slice, new_slots = method.step(flat_slice, owned_grad, slot_slice, lr)
            # all-gather updated weights (bf16 wire) → full flat vector
            new_flat = arp.all_gather_weights(new_slice)
            new_params = unflatten_params(new_flat[:spec_size], param_spec)
            if any_frozen:
                new_params = _mask_frozen(new_params, params, trainable)
            if sync_bn:
                # opt-in sync-BN: running stats averaged across shards each
                # step (≙ utils/ParameterSynchronizer.scala:29)
                new_buffers = jax.lax.pmean(new_buffers, "data")
            else:
                # default: each shard keeps local stats (≙ per-thread
                # replica stats in the reference) — re-stack for P("data")
                new_buffers = jax.tree.map(lambda b: b[None], new_buffers)
            loss = jax.lax.pmean(loss, "data")
            return loss, new_params, new_buffers, new_slice, new_slots

        # capture the flatten spec once from the real params
        params0 = model.params_dict()
        _flat0, param_spec = flatten_params(params0)
        spec_size = _flat0.shape[0]

        # optimizer slots mirror the flat slice (sharded) except rank-0
        # counters (e.g. Adam's t), which stay replicated
        slot_specs = jax.tree.map(
            lambda s: P("data") if getattr(s, "ndim", 0) else P(), slots_example)
        buf_spec = P() if sync_bn else P("data")
        mapped = jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), buf_spec, P("data"), slot_specs, P("data"), P("data"), P(), P()),
            out_specs=(P(), P(), buf_spec, P("data"), slot_specs),
            check_vma=False)
        # donate params/buffers/flat/slots: in-place buffer reuse instead
        # of a full params+slots HBM copy per step (callers read only the
        # post-step outputs, donated no earlier than the NEXT call)
        return (jax.jit(mapped, donate_argnums=(0, 1, 2, 3)),
                param_spec, spec_size)

    def _build_allreduce_step(self, model, criterion, method, grad_clip):
        from bigdl_tpu.optim.optimizer import make_train_step

        ts = make_train_step(model, criterion, method, grad_clip,
                             self.sub_optim_methods)
        data_sharding = NamedSharding(self.mesh, P("data"))
        repl = NamedSharding(self.mesh, P())
        jitted = jax.jit(
            ts.step,
            in_shardings=(repl, repl, repl, data_sharding, data_sharding, repl, repl),
            out_shardings=(repl, repl, repl, repl),
            donate_argnums=(0, 1, 2))  # params/buffers/slots reuse in place
        return jitted, ts

    # ---------------------------------------------------------- data feeding
    @staticmethod
    def _dataset_base(dataset):
        from bigdl_tpu.dataset.dataset import dataset_base

        return dataset_base(dataset)

    def _minibatches(self, dataset, batch_size, train=True):
        """Per-host batch = global batch / process_count (≙ per-partition
        batch, dataset/Utils.scala:25-38). Single-host keeps the full batch.

        Multi-host guard (≙ the reference's RDD partitioning making shards
        disjoint BY CONSTRUCTION, dataset/DataSet.scala:358-367): a
        non-sharded dataset iterated on every host would feed IDENTICAL
        samples to each — silently destroying data parallelism. Sample
        streams are auto-sharded by striding: host k keeps records where
        i%nproc==k. PRECONDITION (documented in the warning): every host
        must build the dataset from the same records in the same order with
        the same seed — disjointness follows from identical streams, which
        auto-striding cannot itself verify. Pre-batched MiniBatch streams
        can't be split safely and raise; so does a ShardedDataSet whose
        num_shards doesn't match the process count."""
        nproc = jax.process_count()
        base = self._dataset_base(dataset)
        pre_sharded = hasattr(base, "shard_id")  # ShardedDataSet/RecordFile
        if pre_sharded and getattr(base, "num_shards", nproc) != nproc:
            raise ValueError(
                f"dataset is sharded {base.num_shards}-way but the run has "
                f"{nproc} processes; shards would overlap or go unread — "
                "rebuild with num_shards matching jax.process_count()")
        it = dataset.data(train=train)
        first = next(iter(it), None)
        if first is None:
            return iter(())

        def chain():
            yield first
            yield from it

        from bigdl_tpu.dataset.minibatch import MiniBatch
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        if isinstance(first, MiniBatch):
            if nproc > 1 and not pre_sharded:
                raise ValueError(
                    "multi-host training with a pre-batched non-sharded "
                    "dataset would feed identical batches to every host; "
                    "build a ShardedDataSet/RecordFileDataSet instead")
            return chain()
        stream = chain()
        if nproc > 1 and not pre_sharded:
            if not getattr(self, "_warned_autoshard", False):
                self._warned_autoshard = True
                logger.warning(
                    "multi-host run with a non-sharded dataset: auto-"
                    "sharding the sample stream by process (stride %d, "
                    "offset %d). This is only disjoint if EVERY host built "
                    "the dataset from the same records in the same order "
                    "with the same seed; for IO-scalable, verified-disjoint "
                    "input use ShardedDataSet/RecordFileDataSet",
                    nproc, jax.process_index())
            rank = jax.process_index()

            def strided(src=stream, k=nproc, r=rank):
                for i, s in enumerate(src):
                    if i % k == r:
                        yield s

            stream = strided()
        return SampleToMiniBatch(batch_size, parallelism=nproc)(stream)

    def _to_global(self, host_array: np.ndarray, sharding):
        """Assemble the global device array from this process's local rows
        (multi-host: ≙ each executor contributing its partition's batch)."""
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, host_array)
        return jax.device_put(host_array, sharding)

    # -------------------------------------------------------------- optimize
    def optimize(self) -> Module:
        """Retry-with-checkpoint-restore driver (≙ the fault-tolerance loop
        wrapping the reference's DistriOptimizer.optimize,
        optim/DistriOptimizer.scala:976-1057).

        On an exception inside the training loop: reload the newest
        (model, optimMethod[, slots]) snapshot from ``checkpoint_path`` and
        re-enter the loop.  ``bigdl.failure.retryTimes`` bounds consecutive
        failures; a failure more than ``bigdl.failure.retryTimeInterval``
        seconds after the previous one starts a fresh streak (the
        reference's retry-window semantics).  Without a checkpoint path the
        failure propagates immediately — there is nothing to restore.
        """
        from bigdl_tpu.utils import config as bt_config

        max_retry = bt_config.get_int("bigdl.failure.retryTimes", 5)
        retry_window = bt_config.get_float("bigdl.failure.retryTimeInterval", 120.0)
        retry_count = 0
        last_failure = None
        while True:
            try:
                return self._optimize_impl()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                now = time.time()
                retry_count = (retry_count + 1
                               if last_failure is not None
                               and now - last_failure < retry_window else 1)
                last_failure = now
                if self.checkpoint_path is None or retry_count > max_retry:
                    raise
                from bigdl_tpu.optim.optimizer import load_latest_checkpoint

                # never read a checkpoint an async writer is still producing
                try:
                    self.join_pending_checkpoint()
                except Exception:
                    logger.warning("pending async checkpoint write failed; "
                                   "restoring from the previous snapshot")
                model, method, tag = load_latest_checkpoint(self.checkpoint_path)
                if model is None:
                    raise
                logger.warning(
                    "Training failed (%s: %s); retry %d/%d from checkpoint "
                    "%s (iteration %s)", type(e).__name__, e, retry_count,
                    max_retry, self.checkpoint_path, tag)
                self.model = model
                self.optim_method = method
                self._restored_slots = self._load_slots_snapshot(tag)

    def join_pending_checkpoint(self):
        super().join_pending_checkpoint()
        if getattr(self, "checkpoint_slots_backend", "pickle") == "orbax":
            from bigdl_tpu.utils import orbax_ckpt

            if orbax_ckpt._CKPTR is not None:  # in-flight async slot write
                orbax_ckpt._CKPTR.wait_until_finished()

    def _load_slots_snapshot(self, tag):
        from bigdl_tpu.utils import file as bt_file

        opath = os.path.join(self.checkpoint_path, f"optimSlots.{tag}.orbax")
        if not bt_file.is_remote(opath):
            opath = os.path.abspath(opath)
        if bt_file.exists(opath):
            # deferred: restored later DIRECTLY into the live slot
            # shardings (template built from the freshly-initialized
            # slots), so no host ever materializes the full state
            return ("__orbax__", opath)
        import pickle

        path = os.path.join(self.checkpoint_path, f"optimSlots.{tag}")
        if not bt_file.exists(path):
            return None
        with bt_file.open_file(path, "rb") as f:
            return pickle.load(f)

    @staticmethod
    def _restore_orbax_slots(opath, like):
        """Restore slots into the exact placements of ``like`` (the fresh
        init_slots tree, already laid out on the mesh)."""
        from bigdl_tpu.utils.orbax_ckpt import _checkpointer

        target = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=s.sharding), like)
        return _checkpointer().restore(opath, {"slots": target})["slots"]

    def _run_checkpoint(self, state):
        """Extends the base snapshot (model + optimMethod) with the
        functional optimizer slots so momentum/Adam state survives a
        failure-restore (the reference persists them inside OptimMethod's
        state table; here they live outside the method)."""
        super()._run_checkpoint(state)
        if not self._ckpt_now or self.checkpoint_path is None:
            return
        if getattr(self, "_live_slots", None) is not None:
            tag = f"{state['neval'] - 1}"
            if getattr(self, "checkpoint_slots_backend", "pickle") == "orbax":
                # shard-wise write from the owning devices — no host gather;
                # async_write leaves the write in flight (joined by
                # join_pending_checkpoint, which the retry path calls
                # before any restore)
                from bigdl_tpu.utils import file as bt_file
                from bigdl_tpu.utils.orbax_ckpt import _checkpointer

                base = self.checkpoint_path
                if not bt_file.is_remote(base):
                    base = os.path.abspath(base)
                ckptr = _checkpointer()
                ckptr.save(os.path.join(base, f"optimSlots.{tag}.orbax"),
                           {"slots": self._live_slots}, force=True)
                if not getattr(self, "checkpoint_async", False):
                    ckptr.wait_until_finished()
                return
            import pickle

            from bigdl_tpu.utils import file as bt_file

            host = jax.tree.map(np.asarray, jax.device_get(self._live_slots))
            with bt_file.open_file(os.path.join(self.checkpoint_path,
                                                f"optimSlots.{tag}"),
                                   "wb") as f:
                pickle.dump(host, f)

    def _optimize_impl(self) -> Module:
        model, criterion, method = self.model, self.criterion, self.optim_method
        state = method.state
        state.setdefault("epoch", 1)
        state.setdefault("neval", 1)
        state.setdefault("recordsProcessedThisEpoch", 0)

        mesh = self.mesh
        n_data = mesh.shape["data"]
        nproc = jax.process_count()
        data_sharding = NamedSharding(mesh, P("data"))
        repl = NamedSharding(mesh, P())

        # jnp.copy after device_put: placement can ALIAS the model's own
        # arrays (same-device no-op), and step-1 donation must never
        # invalidate them
        params = jax.tree.map(jnp.copy,
                              jax.device_put(model.params_dict(), repl))
        host_buffers = model.buffers_dict()
        stacked_buffers = (self.parameter_sync == "sharded"
                           and not self.sync_batch_norm)
        if stacked_buffers:
            # one running-stats copy per data shard (≙ per-thread-replica
            # stats in the reference; no per-step collective on buffers)
            buffers = jax.device_put(
                jax.tree.map(
                    lambda b: jnp.broadcast_to(b[None], (n_data,) + b.shape),
                    host_buffers),
                data_sharding)
        else:
            buffers = jax.tree.map(jnp.copy,
                                   jax.device_put(host_buffers, repl))

        def buffers_for_model(bufs):
            """Host view for validation/checkpoint: replica 0's stats (≙
            the reference copying the head thread-model's state back)."""
            if stacked_buffers:
                return jax.tree.map(lambda b: b[0], jax.device_get(bufs))
            return bufs

        if self.parameter_sync == "sharded":
            if self.sub_optim_methods:
                raise NotImplementedError(
                    "per-submodule optim methods require parameter_sync='allreduce' "
                    "(the sharded flat vector spans all groups)")
            flat, _ = flatten_params(params)
            flat, _ = pad_to_multiple(flat, n_data)
            flat = jax.device_put(flat, data_sharding)
            slots = method.init_slots(flat)  # sharded like the flat vector
            step, param_spec, spec_size = self._build_sharded_step(
                model, criterion, method, self.grad_clip, slots)
            ts = None
            if self._restored_slots is not None:
                if (isinstance(self._restored_slots, tuple)
                        and self._restored_slots
                        and self._restored_slots[0] == "__orbax__"):
                    slots = self._restore_orbax_slots(
                        self._restored_slots[1], slots)
                else:
                    slot_shardings = jax.tree.map(
                        lambda s: (data_sharding if getattr(s, "ndim", 0)
                                   else repl),
                        slots)
                    slots = jax.device_put(self._restored_slots,
                                           slot_shardings)
                self._restored_slots = None
        else:
            step, ts = self._build_allreduce_step(
                model, criterion, method, self.grad_clip)
            if (isinstance(self._restored_slots, tuple)
                    and self._restored_slots
                    and self._restored_slots[0] == "__orbax__"):
                slots = self._restore_orbax_slots(
                    self._restored_slots[1],
                    jax.device_put(ts.init_slots(params), repl))
            else:
                slots = jax.device_put(
                    self._restored_slots if self._restored_slots is not None
                    else ts.init_slots(params), repl)
            self._restored_slots = None
            flat = None

        # /debug/memory attribution for the distributed run: the
        # replicated/sharded params and the optimizer slot tree
        # (shape-derived constant sizes; unregistered fn-guarded on
        # EVERY exit — a crashed run must not leave stale pool sizes
        # misattributing freed HBM).
        from bigdl_tpu.observability import memory as obs_memory

        with obs_memory.static_pools({
                "train/params": obs_memory.tree_bytes(params),
                "train/optimizer_slots": obs_memory.tree_bytes(slots)}):
            num_samples = self.dataset.size()

            def prepare(batch):
                # host stack + divisibility check + sharded H2D, all on the
                # prefetch thread so they overlap the device step
                x = np.asarray(batch.get_input())
                y = np.asarray(batch.get_target())
                if (x.shape[0] * nproc) % n_data != 0:
                    raise ValueError(
                        f"global batch {x.shape[0] * nproc} must divide mesh "
                        f"data axis {n_data} (≙ batch divisibility invariant, "
                        "SURVEY.md Appendix B.2)")
                return (self._to_global(x, data_sharding),
                        self._to_global(y, data_sharding), batch.size())

            data_iter = self._prepared_batches(prepare)
            wall_start = time.time()
            # windowed throughput accounting: no per-step device→host sync —
            # loss is fetched only at log/aux points (VERDICT round-1 weak #3;
            # XLA's async dispatch pipelines the intervening steps)
            window_records = 0
            window_iters = 0
            window_start = time.time()
            loss = None
            from bigdl_tpu import observability as obs

            obs_on = obs.enabled()
            ins = obs.train_instruments() if obs_on else None
            host = str(jax.process_index())
            pins = obs.parallel_instruments() if obs_on else None

            while not self.end_when(state):
                x, y, n_local = next(data_iter)
                if ts is not None:
                    lrs = ts.current_lrs()
                    lr = float(lrs[0])
                else:
                    lr = method.get_current_rate()
                    lrs = jnp.asarray(lr, jnp.float32)
                rng = bt_random.next_key()
                with obs.trace.span("train/step"):
                    if self.parameter_sync == "sharded":
                        loss, params, buffers, flat, slots = step(
                            params, buffers, flat, slots, x, y, lrs, rng)
                    else:
                        loss, params, buffers, slots = step(
                            params, buffers, slots, x, y, lrs, rng)
                self._live_slots = slots
                if self._fault_hook is not None:
                    self._fault_hook(state)
                n = n_local * nproc  # global records this iteration
                state["recordsProcessedThisEpoch"] += n
                state["LearningRate"] = lr
                window_records += n
                window_iters += 1
                state["neval"] += 1
                aux_now = self._should_fire_aux(state)
                log_now = (state["neval"] - 1) % self.log_interval == 0
                if log_now or aux_now:
                    loss_v = float(loss)  # the only host sync in the loop
                    dt = time.time() - window_start
                    state["Loss"] = loss_v
                    self.metrics.add("computing time", dt * 1e9)
                    if obs_on:
                        ins.records_total.inc(window_records)
                        ins.throughput.set(window_records / max(dt, 1e-9))
                        ins.loss.set(loss_v)
                        ins.learning_rate.set(lr)
                        ins.epoch.set(state["epoch"])
                        cache_size = getattr(step, "_cache_size", None)
                        if cache_size is not None:
                            ins.jit_compiles.set(cache_size())
                        # per-host SPMD timings: the whole pipelined window,
                        # and its per-iteration average (the step-time proxy
                        # when dispatch overlaps host work)
                        pins.sync_window_seconds.labels(host).observe(dt)
                        pins.step_seconds.labels(host).observe(
                            dt / max(window_iters, 1))
                    logger.info(
                        "[Epoch %d %d/%d][Iteration %d][Wall Clock %.3fs] "
                        "Trained %d records in %.4f seconds. "
                        "Throughput is %.1f records/second. Loss is %.4f.",
                        state["epoch"], state["recordsProcessedThisEpoch"],
                        num_samples, state["neval"] - 1, time.time() - wall_start,
                        window_records, dt, window_records / max(dt, 1e-9), loss_v)
                    if self.train_summary is not None:
                        it = state["neval"] - 1
                        self.train_summary.add_scalar("Loss", loss_v, it)
                        self.train_summary.add_scalar("LearningRate", lr, it)
                        self.train_summary.add_scalar(
                            "Throughput", window_records / max(dt, 1e-9), it)
                    window_records = 0
                    window_iters = 0
                    window_start = time.time()
                if state["recordsProcessedThisEpoch"] >= num_samples:
                    state["epoch"] += 1
                    state["recordsProcessedThisEpoch"] = 0
                    # reshuffle + restart happen inside _batch_stream (producer
                    # side, ordered ahead of the prefetched batches)
                if ts is not None:
                    kv = dict(neval=state["neval"], epoch=state["epoch"])
                    if "Loss" in state:
                        kv["Loss"] = state["Loss"]
                    ts.update_states(**kv)
                if aux_now:
                    # NOTE (Appendix B.5 contract decision): the reference
                    # validates with start-of-iteration weights; this build
                    # validates with the just-updated weights — strictly
                    # fresher, documented as an intentional deviation.
                    model.load_params_dict(params)
                    model.load_buffers_dict(buffers_for_model(buffers))
                    with obs.trace.span("train/validation"):
                        self._run_validation(state)
                    ck_hist = (ins.checkpoint_seconds
                               if obs_on and self._ckpt_now
                               and self.checkpoint_path is not None else None)
                    with obs.trace.span("train/checkpoint", histogram=ck_hist):
                        self._run_checkpoint(state)

            if obs_on and window_records:
                # the partial window between the last log sync and loop exit
                # still counts toward the records counter
                ins.records_total.inc(window_records)
            model.load_params_dict(params)
            model.load_buffers_dict(buffers_for_model(buffers))
            self.join_pending_checkpoint()
            return model
