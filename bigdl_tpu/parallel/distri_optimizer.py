"""Distributed (SPMD) training loop.

Reference: optim/DistriOptimizer.scala:839 — THE distributed hot path
(SURVEY.md §3.1): per-iteration getWeights → thread-replica
forward/backward → putGradients → aggregateGradientPartition → per-slice
optimizer update → sendWeightPartition, all over Spark BlockManager.

TPU-native redesign: ONE jitted SPMD step over a ``jax.sharding.Mesh``.
Two parameter-sync modes:

- ``allreduce``: params replicated, batch sharded on the ``data`` axis;
  XLA inserts the gradient all-reduce over ICI. Simplest, fastest for
  small/medium models.
- ``sharded`` (default; the reference's exact algorithm, ZeRO-1 style):
  inside ``shard_map`` the flat gradient is reduce-scattered in bf16
  (≙ FP16-compressed putGradients), each device updates only its owned
  slice of the flat parameter/optimizer state (≙ weightPartition +
  optimMethod.optimize on the slice, DistriOptimizer.scala:343-373), then
  all-gathers updated weights (≙ getWeights). Optimizer slots are sharded
  → per-device memory scales down with mesh size.

Straggler dropping (DistriOptimizer.scala:243-247) has no SPMD equivalent —
lockstep collectives make it unnecessary (SURVEY.md §2.5); the fault story
is checkpoint/resume (utils/Engine + checkpoint triggers).
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.module import Module, pure_apply
from bigdl_tpu.optim.optimizer import (
    Optimizer, LocalOptimizer, _clip_constant, _clip_by_global_norm, _mask_frozen,
)
from bigdl_tpu.parallel.all_reduce import (
    AllReduceParameter, flatten_params, unflatten_params, pad_to_multiple,
)
from bigdl_tpu.parallel.engine import Engine
from bigdl_tpu.utils import random as bt_random

logger = logging.getLogger("bigdl_tpu.optim")


class DistriOptimizer(LocalOptimizer):
    """Data-parallel SPMD optimizer (reference: optim/DistriOptimizer.scala)."""

    def __init__(self, *args, mesh: Optional[Mesh] = None,
                 parameter_sync: str = "sharded",
                 compress_dtype=jnp.bfloat16, **kw):
        super().__init__(*args, **kw)
        self.mesh = mesh if mesh is not None else Engine.default_mesh()
        if "data" not in self.mesh.axis_names:
            raise ValueError("mesh must have a 'data' axis for data parallelism")
        self.parameter_sync = parameter_sync
        self.compress_dtype = compress_dtype

    # ------------------------------------------------------------ step build
    def _build_sharded_step(self, model: Module, criterion, method, grad_clip,
                            slots_example):
        """The reference's exact algorithm as one shard_map'd XLA program."""
        apply_fn = pure_apply(model)
        mesh = self.mesh
        n_data = mesh.shape["data"]
        arp = AllReduceParameter("data", self.compress_dtype)
        trainable = model.trainable_dict()
        any_frozen = not all(
            t for t in jax.tree.leaves(trainable, is_leaf=lambda x: isinstance(x, bool)))

        def loss_fn(params, buffers, x, y, rng):
            out, new_buffers = apply_fn(params, buffers, x, rng=rng, training=True)
            loss = criterion.forward(out, y)
            loss = loss + model.regularization_loss(params)
            return loss, new_buffers

        def shard_step(params, buffers, flat_slice, slot_slice, x, y, lr, rng):
            # distinct rng per data shard (dropout masks differ per replica,
            # matching per-thread-replica behavior in the reference)
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            (loss, new_buffers), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, buffers, x, y, rng)
            flat_grad, spec = flatten_params(grads)
            flat_grad, _ = pad_to_multiple(flat_grad, n_data)
            # reduce_scatter (bf16 wire) → owned slice, averaged
            owned_grad = arp.aggregate(flat_grad)
            # clipping operates on the AGGREGATED gradient, matching the
            # local path and the reference's ParameterProcessors which run
            # between aggregation and update (ParameterOperations.scala:33-124)
            if grad_clip:
                if "constant" in grad_clip:
                    lo, hi = grad_clip["constant"]
                    owned_grad = jnp.clip(owned_grad, lo, hi)
                if "l2norm" in grad_clip:
                    # global norm across the full (sharded) gradient — ≙
                    # L2NormClippingProcessor's cross-partition norm
                    sq = jax.lax.psum(jnp.sum(owned_grad ** 2), "data")
                    scale = jnp.minimum(1.0, grad_clip["l2norm"] / (jnp.sqrt(sq) + 1e-12))
                    owned_grad = owned_grad * scale
            # optimizer update on the owned slice only (ZeRO-1)
            new_slice, new_slots = method.step(flat_slice, owned_grad, slot_slice, lr)
            # all-gather updated weights (bf16 wire) → full flat vector
            new_flat = arp.all_gather_weights(new_slice)
            new_params = unflatten_params(new_flat[:spec_size], param_spec)
            if any_frozen:
                new_params = _mask_frozen(new_params, params, trainable)
            # replicate buffer updates (running stats averaged ≙ sync-BN,
            # utils/ParameterSynchronizer.scala)
            new_buffers = jax.lax.pmean(new_buffers, "data")
            loss = jax.lax.pmean(loss, "data")
            return loss, new_params, new_buffers, new_slice, new_slots

        # capture the flatten spec once from the real params
        params0 = model.params_dict()
        _flat0, param_spec = flatten_params(params0)
        spec_size = _flat0.shape[0]

        # optimizer slots mirror the flat slice (sharded) except rank-0
        # counters (e.g. Adam's t), which stay replicated
        slot_specs = jax.tree.map(
            lambda s: P("data") if getattr(s, "ndim", 0) else P(), slots_example)
        mapped = jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), P(), P("data"), slot_specs, P("data"), P("data"), P(), P()),
            out_specs=(P(), P(), P(), P("data"), slot_specs),
            check_vma=False)
        return jax.jit(mapped), param_spec, spec_size

    def _build_allreduce_step(self, model, criterion, method, grad_clip):
        from bigdl_tpu.optim.optimizer import make_train_step

        ts = make_train_step(model, criterion, method, grad_clip,
                             self.sub_optim_methods)
        data_sharding = NamedSharding(self.mesh, P("data"))
        repl = NamedSharding(self.mesh, P())
        jitted = jax.jit(
            ts.step,
            in_shardings=(repl, repl, repl, data_sharding, data_sharding, repl, repl),
            out_shardings=(repl, repl, repl, repl))
        return jitted, ts

    # ---------------------------------------------------------- data feeding
    def _minibatches(self, dataset, batch_size, train=True):
        """Per-host batch = global batch / process_count (≙ per-partition
        batch, dataset/Utils.scala:25-38). Single-host keeps the full batch."""
        nproc = jax.process_count()
        it = dataset.data(train=train)
        first = next(iter(it), None)
        if first is None:
            return iter(())

        def chain():
            yield first
            yield from it

        from bigdl_tpu.dataset.minibatch import MiniBatch
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        if isinstance(first, MiniBatch):
            return chain()
        return SampleToMiniBatch(batch_size, parallelism=nproc)(chain())

    def _to_global(self, host_array: np.ndarray, sharding):
        """Assemble the global device array from this process's local rows
        (multi-host: ≙ each executor contributing its partition's batch)."""
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, host_array)
        return jax.device_put(host_array, sharding)

    # -------------------------------------------------------------- optimize
    def optimize(self) -> Module:
        model, criterion, method = self.model, self.criterion, self.optim_method
        state = method.state
        state.setdefault("epoch", 1)
        state.setdefault("neval", 1)
        state.setdefault("recordsProcessedThisEpoch", 0)

        mesh = self.mesh
        n_data = mesh.shape["data"]
        nproc = jax.process_count()
        data_sharding = NamedSharding(mesh, P("data"))
        repl = NamedSharding(mesh, P())

        params = jax.device_put(model.params_dict(), repl)
        buffers = jax.device_put(model.buffers_dict(), repl)

        if self.parameter_sync == "sharded":
            if self.sub_optim_methods:
                raise NotImplementedError(
                    "per-submodule optim methods require parameter_sync='allreduce' "
                    "(the sharded flat vector spans all groups)")
            flat, _ = flatten_params(params)
            flat, _ = pad_to_multiple(flat, n_data)
            flat = jax.device_put(flat, data_sharding)
            slots = method.init_slots(flat)  # sharded like the flat vector
            step, param_spec, spec_size = self._build_sharded_step(
                model, criterion, method, self.grad_clip, slots)
            ts = None
        else:
            step, ts = self._build_allreduce_step(
                model, criterion, method, self.grad_clip)
            slots = jax.device_put(ts.init_slots(params), repl)
            flat = None

        num_samples = self.dataset.size()
        data_iter = self._minibatches(self.dataset, self.batch_size)
        wall_start = time.time()

        while not self.end_when(state):
            try:
                batch = next(data_iter)
            except StopIteration:
                data_iter = self._minibatches(self.dataset, self.batch_size)
                batch = next(data_iter)
            x = np.asarray(batch.get_input())
            y = np.asarray(batch.get_target())
            if (x.shape[0] * nproc) % n_data != 0:
                raise ValueError(
                    f"global batch {x.shape[0] * nproc} must divide mesh data "
                    f"axis {n_data} (≙ batch divisibility invariant, SURVEY.md "
                    "Appendix B.2)")
            x = self._to_global(x, data_sharding)
            y = self._to_global(y, data_sharding)
            if ts is not None:
                lrs = ts.current_lrs()
                lr = float(lrs[0])
            else:
                lr = method.get_current_rate()
                lrs = jnp.asarray(lr, jnp.float32)
            rng = bt_random.next_key()
            t0 = time.time()
            if self.parameter_sync == "sharded":
                loss, params, buffers, flat, slots = step(
                    params, buffers, flat, slots, x, y, lrs, rng)
            else:
                loss, params, buffers, slots = step(params, buffers, slots, x, y, lrs, rng)
            loss = float(loss)
            dt = time.time() - t0
            n = batch.size() * nproc  # global records this iteration
            state["recordsProcessedThisEpoch"] += n
            state["Loss"] = loss
            state["LearningRate"] = lr
            self.metrics.add("computing time", dt * 1e9)
            logger.info(
                "[Epoch %d %d/%d][Iteration %d][Wall Clock %.3fs] "
                "Trained %d records in %.4f seconds. Throughput is %.1f records/second. "
                "Loss is %.4f.",
                state["epoch"], state["recordsProcessedThisEpoch"], num_samples,
                state["neval"], time.time() - wall_start, n, dt, n / max(dt, 1e-9), loss)
            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss, state["neval"])
                self.train_summary.add_scalar("LearningRate", lr, state["neval"])
                self.train_summary.add_scalar("Throughput", n / max(dt, 1e-9), state["neval"])
            state["neval"] += 1
            if state["recordsProcessedThisEpoch"] >= num_samples:
                state["epoch"] += 1
                state["recordsProcessedThisEpoch"] = 0
                self.dataset.shuffle()
                data_iter = self._minibatches(self.dataset, self.batch_size)
            if ts is not None:
                ts.update_states(neval=state["neval"], epoch=state["epoch"], Loss=loss)
            if self._should_fire_aux(state):
                model.load_params_dict(params)
                model.load_buffers_dict(buffers)
                self._run_validation(state)
                self._run_checkpoint(state)

        model.load_params_dict(params)
        model.load_buffers_dict(buffers)
        return model
