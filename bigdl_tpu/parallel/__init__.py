"""bigdl_tpu.parallel — distributed engine (reference: parameters/ +
optim/DistriOptimizer + utils/Engine, SURVEY.md §2.5): device mesh discovery,
flat-parameter collectives over ICI, and the SPMD training loop."""

from bigdl_tpu.parallel.engine import Engine, EngineType
from bigdl_tpu.parallel.all_reduce import (
    AllReduceParameter, flatten_params, unflatten_params, pad_to_multiple,
    compress, decompress,
)
from bigdl_tpu.parallel.distri_optimizer import DistriOptimizer
from bigdl_tpu.parallel.ring_attention import ring_attention, ulysses_attention
from bigdl_tpu.parallel.tp import (
    fetch_to_host, kv_pool_sharding, kv_pool_spec, put_from_host,
    replicate, spec_for_params, transformer_tp_rules, shard_params,
)
from bigdl_tpu.parallel.pipeline import pipeline_spmd, stack_stage_params
from bigdl_tpu.parallel.moe import MoEMLP, moe_spmd
