"""ctypes bindings for the native C++ runtime components.

Reference native inventory (SURVEY.md §2.12): MKL/MKL-DNN/BigQuant JNI are
absorbed by XLA; what remains native here is (a) the CRC32C/TFRecord codec
(≙ java/netty/Crc32c.java + visualization/tensorboard/RecordWriter.scala +
utils/tf/TFRecordIterator.scala) and (b) the multithreaded IO staging
reader (≙ the Engine "io" thread pool feeding input pipelines).

The shared library is built on demand from ``native/`` with g++; every
entry point has a pure-Python fallback so the framework degrades gracefully
where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB_NAME = "libbigdl_native.so"
_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    makefile = os.path.join(_REPO, "native", "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(["make", "-C", os.path.join(_REPO, "native")],
                       check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded native library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = os.path.join(_HERE, _LIB_NAME)
        # always offer make a chance: it is a no-op when the .so is newer
        # than the sources, and it rebuilds a stale .so that predates a
        # newly added entry point (the load below would otherwise bind a
        # library missing symbols)
        if not _build() and not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.bigdl_crc32c.restype = ctypes.c_uint32
        lib.bigdl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.bigdl_masked_crc32c.restype = ctypes.c_uint32
        lib.bigdl_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.bigdl_tfrecord_frame.restype = ctypes.c_uint64
        lib.bigdl_tfrecord_frame.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
        lib.bigdl_loader_create.restype = ctypes.c_void_p
        lib.bigdl_loader_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.bigdl_loader_submit.restype = ctypes.c_int64
        lib.bigdl_loader_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.bigdl_loader_next.restype = ctypes.c_int64
        lib.bigdl_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int)]
        lib.bigdl_loader_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bigdl_loader_destroy.argtypes = [ctypes.c_void_p]
        try:  # absent from .so files built before augment.cc existed
            lib.bigdl_fused_augment.restype = None
            lib.bigdl_fused_augment.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # h, w, c
                ctypes.c_int64, ctypes.c_int64,                  # top, left
                ctypes.c_int64, ctypes.c_int64,                  # ch, cw
                ctypes.c_int,                                    # flip
                ctypes.POINTER(ctypes.c_float),                  # mean
                ctypes.POINTER(ctypes.c_float),                  # 1/std
                ctypes.POINTER(ctypes.c_float)]                  # out
        except AttributeError:
            pass
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------- crc32c
_CRC_TABLE = None


def _py_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            tbl.append(crc)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    lib = get_lib()
    if lib is not None:
        return lib.bigdl_crc32c(data, len(data))
    tbl = _py_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ tbl[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    lib = get_lib()
    if lib is not None:
        return lib.bigdl_masked_crc32c(data, len(data))
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------- tfrecord
import struct as _struct


def tfrecord_frame(payload: bytes) -> bytes:
    """Frame one TFRecord: len u64le | masked_crc(len) | data | masked_crc(data)."""
    lib = get_lib()
    if lib is not None:
        out = ctypes.create_string_buffer(len(payload) + 16)
        n = lib.bigdl_tfrecord_frame(payload, len(payload), out)
        return out.raw[:n]
    header = _struct.pack("<Q", len(payload))
    return (header + _struct.pack("<I", masked_crc32c(header)) + payload +
            _struct.pack("<I", masked_crc32c(payload)))


def tfrecord_iter(data: bytes):
    """Yield payloads from a concatenation of framed records
    (≙ utils/tf/TFRecordIterator.scala)."""
    off = 0
    n = len(data)
    while off + 12 <= n:
        (length,) = _struct.unpack_from("<Q", data, off)
        (lcrc,) = _struct.unpack_from("<I", data, off + 8)
        if masked_crc32c(data[off:off + 8]) != lcrc:
            raise ValueError(f"tfrecord length crc mismatch at {off}")
        if off + 16 + length > n:
            raise ValueError("truncated tfrecord")
        payload = data[off + 12: off + 12 + length]
        (dcrc,) = _struct.unpack_from("<I", data, off + 12 + length)
        if masked_crc32c(payload) != dcrc:
            raise ValueError(f"tfrecord data crc mismatch at {off}")
        yield payload
        off += 16 + length


# ------------------------------------------------------------ data loader
class PrefetchReader:
    """Ordered multithreaded byte-range reader backed by the C++ pool;
    falls back to synchronous Python reads when the library is absent."""

    def __init__(self, n_threads: int = 4, capacity: int = 32):
        self._lib = get_lib()
        self._handle = (self._lib.bigdl_loader_create(n_threads, capacity)
                        if self._lib is not None else None)
        self._py_queue = []

    def submit(self, path: str, offset: int = 0, length: int = 0) -> int:
        if self._handle is not None:
            return self._lib.bigdl_loader_submit(
                self._handle, path.encode(), offset, length)
        self._py_queue.append((path, offset, length))
        return len(self._py_queue) - 1

    def next(self) -> bytes:
        """Next completed read, in submission order. Raises IOError on a
        failed read, IndexError when nothing is outstanding."""
        if self._handle is not None:
            data = ctypes.POINTER(ctypes.c_uint8)()
            length = ctypes.c_uint64()
            err = ctypes.c_int()
            jid = self._lib.bigdl_loader_next(
                self._handle, ctypes.byref(data), ctypes.byref(length),
                ctypes.byref(err))
            if jid < 0:
                raise IndexError("no outstanding reads")
            try:
                if err.value != 0:
                    raise IOError(f"native read failed (code {err.value})")
                return ctypes.string_at(data, length.value)
            finally:
                self._lib.bigdl_loader_free(self._handle, jid)
        if not self._py_queue:
            raise IndexError("no outstanding reads")
        path, offset, length = self._py_queue.pop(0)
        with open(path, "rb") as f:
            if offset:
                f.seek(offset)
            return f.read(length) if length else f.read()

    def close(self):
        if self._handle is not None:
            self._lib.bigdl_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------- fused augment
def fused_augment_available() -> bool:
    lib = get_lib()
    return lib is not None and hasattr(lib, "bigdl_fused_augment") \
        and getattr(lib.bigdl_fused_augment, "argtypes", None) is not None


def fused_augment(img, top: int, left: int, crop_h: int, crop_w: int,
                  flip: bool, means, inv_stds):
    """One-pass native crop+flip+normalize: (h, w, c) uint8 C-contiguous
    -> (crop_h, crop_w, c) float32. Returns None when the native kernel
    is unavailable or the input does not qualify (caller falls back to
    the composed numpy ops) — including an out-of-bounds crop window or
    means/inv_stds whose length differs from c: the C kernel trusts its
    arguments and would read past the buffers for a bad caller."""
    import numpy as np

    lib = get_lib()
    if not fused_augment_available():
        return None
    if (img.dtype != np.uint8 or img.ndim != 3
            or not img.flags.c_contiguous):
        return None
    h, w, c = img.shape
    mean = np.ascontiguousarray(means, np.float32)
    inv = np.ascontiguousarray(inv_stds, np.float32)
    if mean.shape != (c,) or inv.shape != (c,):
        return None
    if not (0 <= top and 0 <= left and crop_h >= 1 and crop_w >= 1
            and top + crop_h <= h and left + crop_w <= w):
        return None
    out = np.empty((crop_h, crop_w, c), np.float32)
    lib.bigdl_fused_augment(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        h, w, c, top, left, crop_h, crop_w, int(bool(flip)),
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        inv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out
