"""bigdl_tpu.visualization — TensorBoard summaries (SURVEY.md §2.11).

Reference: visualization/{Summary,TrainSummary,ValidationSummary}.scala +
tensorboard writers. ``TrainSummary``/``ValidationSummary`` plug into the
Optimizer via ``set_train_summary``/``set_validation_summary`` and are
readable back with ``read_scalar`` for tests/python parity.
"""

from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.visualization.tensorboard import FileWriter, read_scalar


class Summary:
    """Base writer bound to logDir/appName (≙ visualization/Summary.scala:32)."""

    folder = ""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = log_dir
        self.app_name = app_name
        self._dir = os.path.join(log_dir, app_name, self.folder)
        self._writer = FileWriter(self._dir)

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self._writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self._writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str):
        self._writer.flush()
        return read_scalar(self._dir, tag)

    def flush(self) -> "Summary":
        self._writer.flush()
        return self

    def close(self) -> None:
        self._writer.close()


class TrainSummary(Summary):
    """Training-side scalars: Loss / Throughput / LearningRate (+ optional
    Parameters histograms; ≙ visualization/TrainSummary.scala:32)."""

    folder = "train"

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name)
        self._triggers = {}

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        """≙ TrainSummary.setSummaryTrigger — gate optional tags
        ("Parameters", "LearningRate") on a Trigger."""
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Validation metric scalars (≙ visualization/ValidationSummary.scala)."""

    folder = "validation"
