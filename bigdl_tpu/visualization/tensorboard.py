"""TensorBoard-compatible event file writers.

Reference: visualization/tensorboard/{FileWriter,EventWriter,RecordWriter}.scala
— TFRecord-framed event protos with CRC32C masking (Crc32c.java), written by
a background thread. Framing/CRC here ride the native C++ codec
(bigdl_tpu.native) with a Python fallback.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import List, Optional

from bigdl_tpu import native
from bigdl_tpu.visualization import proto


class RecordWriter:
    """Append TFRecord-framed payloads to a file (≙ RecordWriter.scala)."""

    def __init__(self, path: str):
        from bigdl_tpu.utils import file as bt_file

        self.path = path
        # fresh file per run (timestamped name): 'ab' locally, one
        # streaming 'wb' on object stores (buckets have no append)
        self._f = bt_file.open_file(path, "ab")

    def write(self, payload: bytes) -> None:
        self._f.write(native.tfrecord_frame(payload))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class EventWriter:
    """Queue + background thread draining events to a RecordWriter
    (≙ EventWriter.scala). The first record is the file_version event."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        from bigdl_tpu.utils import file as bt_file

        bt_file.makedirs(log_dir)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(log_dir, fname)
        self._writer = RecordWriter(self.path)
        self._writer.write(proto.event(time.time(), file_version="brain.Event:2"))
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._flush_secs = flush_secs
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event_bytes: bytes) -> "EventWriter":
        self._q.put(event_bytes)
        return self

    def flush(self) -> "EventWriter":
        """Block until everything queued so far is on disk."""
        done = threading.Event()
        self._q.put(done)
        done.wait(timeout=10)
        return self

    def _run(self):
        last_flush = time.time()
        while True:
            try:
                item = self._q.get(timeout=self._flush_secs)
            except queue.Empty:
                item = ()
            if item is None:
                break
            if isinstance(item, threading.Event):
                self._writer.flush()
                item.set()
                continue
            if item:
                self._writer.write(item)
            if time.time() - last_flush >= self._flush_secs:
                self._writer.flush()
                last_flush = time.time()
        self._writer.flush()
        self._writer.close()

    def close(self):
        self._q.put(None)
        self._thread.join(timeout=10)


class FileWriter:
    """User-facing writer (≙ FileWriter.scala): add scalar/histogram
    summaries by (tag, value, step)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        self.log_dir = log_dir
        self._events = EventWriter(log_dir, flush_secs)

    def add_scalar(self, tag: str, value: float, step: int) -> "FileWriter":
        s = proto.summary([proto.scalar_value(tag, float(value))])
        self._events.add_event(proto.event(time.time(), step=step, summary_bytes=s))
        return self

    def add_histogram(self, tag: str, values, step: int) -> "FileWriter":
        import numpy as np

        arr = np.asarray(values, dtype=np.float64).reshape(-1)
        limits = _exp_bucket_limits()
        counts, _ = np.histogram(arr, bins=[-np.inf] + limits) if arr.size else (
            np.zeros(len(limits)), None)
        h = proto.histogram_proto(
            float(arr.min()) if arr.size else 0.0,
            float(arr.max()) if arr.size else 0.0,
            float(arr.size), float(arr.sum()), float((arr ** 2).sum()),
            limits, counts.tolist())
        s = proto.summary([proto.histo_value(tag, h)])
        self._events.add_event(proto.event(time.time(), step=step, summary_bytes=s))
        return self

    def flush(self):
        self._events.flush()
        return self

    def close(self):
        self._events.close()


_BUCKETS: Optional[List[float]] = None


def _exp_bucket_limits() -> List[float]:
    """Exponential histogram buckets (≙ Summary.scala:144-172): ±1e-12·1.1^k
    out to 1e20, mirrored negative, with 0 between."""
    global _BUCKETS
    if _BUCKETS is None:
        pos = []
        v = 1e-12
        while v < 1e20:
            pos.append(v)
            v *= 1.1
        _BUCKETS = [-x for x in reversed(pos)] + pos
    return _BUCKETS


def read_scalar(log_dir: str, tag: str):
    """Read back (step, wall_time, value) triples for a tag from all event
    files (≙ Summary.readScalar, visualization/Summary.scala:77)."""
    from bigdl_tpu.utils import file as bt_file

    out = []
    if not bt_file.is_remote(log_dir) and not os.path.isdir(log_dir):
        return out
    try:
        names = sorted(bt_file.listdir(log_dir))
    except (FileNotFoundError, NotADirectoryError, OSError):
        return out
    for fname in names:
        if ".tfevents." not in fname:
            continue
        with bt_file.open_file(os.path.join(log_dir, fname), "rb") as f:
            data = f.read()
        for payload in native.tfrecord_iter(data):
            ev = proto.parse_event(payload)
            for t, v in ev["values"]:
                if t == tag:
                    out.append((ev["step"], ev["wall_time"], v))
    out.sort(key=lambda r: r[0])
    return out
