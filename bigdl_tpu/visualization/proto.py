"""Minimal protobuf wire-format codec for TensorBoard event files.

The reference writes TF summary/event protos from Scala with checked-in
generated classes (visualization/Summary.scala:32-108, tensorboard/
FileWriter.scala). Python analog: hand-rolled varint/wire encoding of the
few message types TensorBoard needs — no protobuf runtime dependency.

Messages (field numbers from the public tensorflow event.proto /
summary.proto):
  Event:   wall_time=1(double) step=2(int64) file_version=3(string)
           summary=5(message)
  Summary: value=1(repeated message)
  Value:   tag=1(string) simple_value=2(float) histo=5(message)
  HistogramProto: min=1 max=2 num=3 sum=4 sum_squares=5 (double)
           bucket_limit=6(packed double) bucket=7(packed double)
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_free_int64(n: int) -> int:
    return n & 0xFFFFFFFFFFFFFFFF  # proto int64 negative -> 10-byte varint


def tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def enc_double(field: int, v: float) -> bytes:
    return tag(field, 1) + struct.pack("<d", v)


def enc_float(field: int, v: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", v)


def enc_int64(field: int, v: int) -> bytes:
    return tag(field, 0) + _varint(_zigzag_free_int64(int(v)))


def enc_bytes(field: int, v: bytes) -> bytes:
    return tag(field, 2) + _varint(len(v)) + v


def enc_string(field: int, v: str) -> bytes:
    return enc_bytes(field, v.encode("utf-8"))


def enc_packed_doubles(field: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return enc_bytes(field, payload)


def histogram_proto(minv, maxv, num, total, sum_sq, limits, counts) -> bytes:
    return (enc_double(1, minv) + enc_double(2, maxv) + enc_double(3, num) +
            enc_double(4, total) + enc_double(5, sum_sq) +
            enc_packed_doubles(6, limits) + enc_packed_doubles(7, counts))


def scalar_value(tag_name: str, value: float) -> bytes:
    return enc_string(1, tag_name) + enc_float(2, value)


def histo_value(tag_name: str, histo: bytes) -> bytes:
    return enc_string(1, tag_name) + enc_bytes(5, histo)


def summary(values: List[bytes]) -> bytes:
    return b"".join(enc_bytes(1, v) for v in values)


def event(wall_time: float, step: int = None, file_version: str = None,
          summary_bytes: bytes = None) -> bytes:
    out = enc_double(1, wall_time)
    if step is not None:
        out += enc_int64(2, step)
    if file_version is not None:
        out += enc_string(3, file_version)
    if summary_bytes is not None:
        out += enc_bytes(5, summary_bytes)
    return out


# ------------------------------------------------------------------ decode
def iter_fields(data: bytes) -> Iterator[Tuple[int, int, object]]:
    """(field, wire_type, value) over a serialized message. Length-delimited
    values are returned as bytes; varints as int; fixed as raw bytes."""
    i, n = 0, len(data)
    while i < n:
        v = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = v >> 3, v & 7
        if wire == 0:
            val = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                val |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, val
        elif wire == 1:
            yield field, wire, data[i:i + 8]
            i += 8
        elif wire == 5:
            yield field, wire, data[i:i + 4]
            i += 4
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, data[i:i + ln]
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")


def parse_event(data: bytes) -> dict:
    """Decode an Event into {wall_time, step, values: [(tag, simple_value)]}."""
    out = {"wall_time": 0.0, "step": 0, "values": []}
    for field, wire, val in iter_fields(data):
        if field == 1 and wire == 1:
            out["wall_time"] = struct.unpack("<d", val)[0]
        elif field == 2 and wire == 0:
            step = val
            if step >= 1 << 63:
                step -= 1 << 64
            out["step"] = step
        elif field == 5 and wire == 2:
            for f2, w2, v2 in iter_fields(val):  # Summary.value
                if f2 == 1 and w2 == 2:
                    tag_name, simple = None, None
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag_name = v3.decode("utf-8")
                        elif f3 == 2 and w3 == 5:
                            simple = struct.unpack("<f", v3)[0]
                    if tag_name is not None and simple is not None:
                        out["values"].append((tag_name, simple))
    return out
